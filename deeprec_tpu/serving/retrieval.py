"""Full-corpus two-tower retrieval: blocked top-k over a resident,
quantized item matrix.

Everything the serving tier answered before this module is POINTWISE —
the caller supplies candidates, the model scores them. Production
traffic starts one step earlier: "which k of the whole catalog?" This
module makes that a first-class serving workload, built on the pieces
the stack already has:

  * **Corpus residency** — the item tower's output vectors live on
    device as ONE `[Cp, H]` matrix (pow2-padded block count), quantized
    int8 (per-row scale, the PR 10 residency story applied to the item
    matrix), bf16, or fp32. Items are ingested explicitly
    (`upsert_items`); encode runs in fixed-size chunks through one
    compiled program (the PR 5 `import_rows(chunk=)` discipline), so
    neither ingest nor refresh ever traces next to live traffic.
  * **Asymmetric data flow** — the user tower runs ONCE per request
    (PAPERS "Automatic Asymmetric Data Flow Optimization"); the corpus
    side is pure matmul sweep: per pow2 block, one `[B, Bk]` score tile
    merged into a streaming `[B, k]` top-k carry (`ops/topk.py`) — the
    full `[C]` score vector never materializes, so the block count (and
    with it the corpus) scales to 10M items with at most log2 retraces.
  * **Freshness rides the online loop** — `Predictor.poll_updates`
    delta replay notifies the engine (`on_model_update`), which maps the
    delta's changed item-table keys onto corpus rows (vectorized isin
    against the stored item feature columns) and re-encodes exactly
    those rows through the same fixed-chunk program: a newly trained
    item vector is retrievable within ONE poll round, at zero
    steady-state compiles (trace-guard pinned).
  * **Scale-out rides the fleet** — each backend owns the corpus shard
    of the items that hash to it (`hash_shard_np`); the frontend fans a
    `RETR` wire op to every live member and lexsort-merges the per-shard
    top-k at the edge. A dead member costs coverage, never a request:
    the merge serves the surviving shards' top-k marked `partial`, and
    `health()` shows the degraded membership.

Coalescing: `RetrievalServer` is the micro-batching front of the lane —
concurrent retrieval requests share one corpus sweep (one user-tower
batch scores every block once for ALL of them), accounted into the
`retrieval` stage histogram and the candidates-scanned counter of
`ServingStats`.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.ops import topk as _topk
from deeprec_tpu.serving.predictor import BadRequest
from deeprec_tpu.serving.stats import ServingStats
from deeprec_tpu.utils.hashing import hash_shard_np


class RetrievalResult(NamedTuple):
    """One retrieval answer: per user row, the top-k item ids and their
    scores (desc), the model version that served the WHOLE request, a
    partial flag (fleet merges missing dead shards), and the candidate
    rows scanned to produce it."""

    ids: np.ndarray  # [B, k] int64 item ids, -1 past the valid corpus
    scores: np.ndarray  # [B, k] float32, -inf where ids == -1
    version: int
    partial: bool
    scanned: int


# Residency grammar shared with Predictor(quantize=): storage dtype per mode.
_QUANT_MODES = {
    None: "float32", "fp32": "float32", "float32": "float32",
    "bf16": "bfloat16", "bfloat16": "bfloat16", "int8": "int8",
}
_STORE_DTYPES = {
    "float32": jnp.float32, "bfloat16": jnp.bfloat16, "int8": jnp.int8,
}


def fill_missing_item_features(predictor, feats: Dict) -> Dict:
    """Retrieval requests carry USER features only — the item side is the
    resident corpus. `parse_features` demands the model's full feature
    set, so the edge fills every absent item feature with its pad value
    (one column; the parser pads to the declared max_len). Sparse pads
    are the feature's pad_value (a masked non-key), dense pads are 0."""
    if not isinstance(feats, dict) or not feats:
        raise BadRequest("missing 'features' object")
    item_feats = set(getattr(predictor.model, "item_feats", ()))
    if not item_feats:
        return feats
    rows = None
    for v in feats.values():
        rows = len(v) if isinstance(v, list) else int(np.asarray(v).shape[0])
        break
    specs = {f.name: f for f in predictor._trainer.sparse_specs}
    dtypes = predictor.feature_dtypes
    out = dict(feats)
    for name in item_feats - set(feats):
        want = dtypes.get(name)
        if want is None:
            continue
        if want.kind in "iu":
            out[name] = np.full((rows, 1), specs[name].pad_value, want)
        else:
            out[name] = np.zeros((rows, 1), np.float32)
    return out


def merge_shard_topk(
    ids: List[np.ndarray], scores: List[np.ndarray], k: int
) -> Tuple[np.ndarray, np.ndarray]:
    """Merge per-shard top-k answers into one global top-k (the frontend
    edge merge). Deterministic total order: score desc, then item id asc
    — ties resolve the same way no matter how many shards contributed or
    in which order they answered. Entries with id -1 (a shard with fewer
    than k valid rows) always lose."""
    allv = np.concatenate([np.asarray(s, np.float32) for s in scores], axis=1)
    alli = np.concatenate([np.asarray(i, np.int64) for i in ids], axis=1)
    allv = np.where(alli < 0, -np.inf, allv)
    # lexsort: last key is primary — sort by -score, tie-break by id.
    order = np.lexsort((alli, -allv), axis=-1)[:, :k]
    out_i = np.take_along_axis(alli, order, axis=1)
    out_v = np.take_along_axis(allv, order, axis=1)
    out_i = np.where(np.isfinite(out_v), out_i, -1)
    return out_i, out_v


class _Corpus(NamedTuple):
    """One immutable published corpus snapshot — the retrieval analog of
    the predictor's `_Snapshot`: readers grab ONE reference and sweep it;
    ingest/fold build replacements and swap."""

    vecs: jnp.ndarray  # [Cp, H] storage dtype
    scale: Optional[jnp.ndarray]  # [Cp] f32 (int8 residency only)
    valid: jnp.ndarray  # [Cp] bool
    ids: np.ndarray  # [Cp] int64 host mirror (-1 where empty)
    rows: int  # live item count


class RetrievalEngine:
    """Device-resident item corpus + the blocked top-k sweep over it.

    Requires a two-tower model (`user_feats` / `item_feats` /
    `user_vector` / `item_vectors` — DSSM's surface). The engine hangs
    off a live `Predictor`: it encodes through the predictor's current
    snapshot state and auto-registers for model-update notifications, so
    delta replay keeps the corpus fresh without a second poller.

    Sharding: with `num_shards > 1` the engine silently keeps only the
    items whose id hashes to `shard_index` (`hash_shard_np` — every
    shard computes the same assignment, so a broadcast ingest partitions
    itself). The fleet frontend merges per-shard answers.
    """

    def __init__(self, predictor, *, quantize: str = "int8",
                 block_rows: int = 4096, chunk: int = 1024,
                 shard_index: int = 0, num_shards: int = 1):
        model = predictor.model
        for attr in ("user_feats", "item_feats", "user_vector",
                     "item_vectors"):
            if not hasattr(model, attr):
                raise ValueError(
                    f"{type(model).__name__} has no two-tower split "
                    f"(retrieval needs user_feats/item_feats/user_vector/"
                    f"item_vectors)")
        if quantize not in _QUANT_MODES:
            raise ValueError(f"unknown retrieval residency {quantize!r}")
        if block_rows & (block_rows - 1):
            raise ValueError(f"block_rows must be a power of two, "
                             f"got {block_rows}")
        self._pred = predictor
        self._trainer = predictor._trainer
        self.model = model
        self.quantize = _QUANT_MODES[quantize]
        self._store_dtype = _STORE_DTYPES[self.quantize]
        self.block_rows = int(block_rows)
        self.chunk = int(chunk)
        self.shard_index = int(shard_index)
        self.num_shards = int(num_shards)
        self._lock = threading.RLock()
        # Feature templates: one pad row per feature, so encode/retrieve
        # batches always carry the model's FULL input signature (the
        # other tower's features ride as inert pad columns).
        self._templates: Dict[str, np.ndarray] = {}
        specs = {f.name: f for f in self._trainer.sparse_specs}
        for name, want in predictor.feature_dtypes.items():
            if want.kind in "iu":
                f = specs[name]
                self._templates[name] = np.full(
                    (1, f.max_len or 1), f.pad_value, want)
            else:
                self._templates[name] = np.zeros((1, 1), np.float32)
        # item-feature -> (bundle, member tag) for reading delta keys
        # (the freshness fold's changed-row discovery).
        from deeprec_tpu.features import resolve_table_name

        self._item_tables = []
        for bname, b in self._trainer.bundles.items():
            for kx, f in enumerate(b.features):
                if f.name in model.item_feats:
                    tag = f"t{kx}" if b.stacked else "t"
                    self._item_tables.append(
                        (f.name, resolve_table_name(f), bname, tag))
        # One compiled program each for encode / scatter / user tower /
        # sweep — built here (idiomatic per-instance compile), every
        # later call is cache-hit dispatch at the fixed chunk / bucket
        # shapes. The sweep wrapper keys on (k-bucket, corpus capacity):
        # capacity doubles block-count pow2, so growth retraces at most
        # log2(C) times and a FIXED corpus never retraces.
        self._encode_jit = jax.jit(self._encode_impl)
        self._scatter_jit = jax.jit(self._scatter_impl)
        self._user_jit = jax.jit(self._user_impl)
        self._sweep_jit = jax.jit(
            _topk.blocked_topk, static_argnames=("k", "block_rows"))
        # Host mirrors: quantized rows + scale (exactly what the device
        # holds — mass rebuilds are one device_put, no recompute), item
        # feature columns (the fold's isin target), id map.
        self._h_feats: Dict[str, np.ndarray] = {}
        self._h_vecs: Optional[np.ndarray] = None
        self._h_scale: Optional[np.ndarray] = None
        self._h_valid: Optional[np.ndarray] = None
        self._h_ids: Optional[np.ndarray] = None
        self._sid = np.zeros((0,), np.int64)  # sorted live ids
        self._srow = np.zeros((0,), np.int64)  # their corpus rows
        self._rows = 0
        # Freshness stamp of the last delta fold (the bench's ingest->
        # retrievable probe reads it): wall time, rows re-encoded, and
        # the model version the fold encoded through.
        self.last_fold: Optional[Dict] = None
        self.folds = 0
        self.rows_folded = 0
        # Corpus revision: bumps whenever resident item vectors change
        # WITHOUT a model publish (ingest/upsert re-encodes rows at the
        # same model version). Together with the model version it is the
        # candidate cache's version key — `folds` alone cannot serve:
        # upsert refreshes rows without folding. Attached ReuseCaches
        # (serving/reuse.py) invalidate on every bump.
        self.corpus_rev = 0
        self._reuse_caches: List = []
        # Warm the encode program + learn H off one pad chunk, then
        # allocate the (empty) first block and publish.
        state = predictor._snap.state
        pad_batch = self._jnp_batch(self._pad_chunk_batch())
        rows_dev, _scale_dev = self._encode_jit(state, pad_batch)
        self._dim = int(rows_dev.shape[1])
        self._alloc(self.block_rows)
        self._publish(full=True)
        # Item-tower dense fingerprint: the targeted delta fold is only
        # sound while the dense half of the item tower is unchanged (the
        # sparse-only online-update regime); a drifted tower invalidates
        # EVERY resident vector, so the fold escalates to a full
        # re-encode when the fingerprint moves.
        self._dense_ref = self._dense_fp(state)
        predictor.attach_retrieval(self)

    # ----------------------------------------------------------- plumbing

    @property
    def dim(self) -> int:
        return self._dim

    def _pad_chunk_batch(self) -> Dict[str, np.ndarray]:
        return {k: np.repeat(v, self.chunk, axis=0)
                for k, v in self._templates.items()}

    @staticmethod
    def _jnp_batch(batch):
        return {k: jnp.asarray(v) for k, v in batch.items()}

    def _encode_impl(self, state, batch):
        """Item tower over one fixed-size chunk -> storage-typed rows +
        per-row scale (int8) — quantize-on-encode, the `import_rows`
        quantize-on-import discipline applied to the corpus."""
        views, _ = self._trainer.forward_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        item_in = jnp.concatenate(
            [inputs.pooled[n] for n in self.model.item_feats], axis=-1)
        vecs = self.model.item_vectors(state.dense, item_in)
        vecs = jnp.asarray(vecs, jnp.float32)
        if self.quantize == "int8":
            from deeprec_tpu.embedding.table import quantize_rows_int8

            q, scale = quantize_rows_int8(vecs)
            return q.astype(jnp.int8), scale
        return vecs.astype(self._store_dtype), None

    def _scatter_impl(self, vecs, scale, valid, rows_new, scale_new, ix, ok):
        """Fold one encoded chunk into the corpus arrays (drop-mode
        scatter at the fixed chunk shape — the zero-retrace fold)."""
        put = jnp.where(ok, ix, vecs.shape[0])
        vecs = vecs.at[put].set(rows_new, mode="drop")
        if scale is not None:
            scale = scale.at[put].set(scale_new, mode="drop")
        valid = valid.at[put].set(True, mode="drop")
        return vecs, scale, valid

    def _dense_fp(self, state) -> int:
        """crc32 fingerprint of the dense params the item tower reads —
        the model's `item_tower_params(dense)` subtree when exposed
        (DSSM: the item MLP), else conservatively the WHOLE dense tree.
        Update-cadence host pull of a small tree, never the hot path."""
        import zlib

        fn = getattr(self.model, "item_tower_params", None)
        tree = fn(state.dense) if fn is not None else state.dense
        h = 0
        for leaf in jax.tree.leaves(tree):
            h = zlib.crc32(np.asarray(leaf).tobytes(), h)  # noqa: DRT002 — update-cadence drift check, not the predict path
        return h

    def _user_impl(self, state, batch):
        """User tower once per request row — the asymmetric half."""
        views, _ = self._trainer.forward_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        return jnp.asarray(
            self.model.user_vector(state.dense, inputs), jnp.float32)

    # ----------------------------------------------------- corpus storage

    def _alloc(self, capacity: int) -> None:
        np_dtype = np.dtype(self._store_dtype)
        self._h_vecs = np.zeros((capacity, self._dim), np_dtype)
        self._h_scale = (np.zeros((capacity,), np.float32)
                         if self.quantize == "int8" else None)
        self._h_valid = np.zeros((capacity,), bool)
        self._h_ids = np.full((capacity,), -1, np.int64)

    @property
    def capacity(self) -> int:
        return 0 if self._h_ids is None else int(self._h_ids.shape[0])  # noqa: DRT002 — host shape math (name-collision reachability)

    def _grow_to(self, need: int) -> None:
        """Double the pow2 block count until `need` rows fit; mirrors are
        re-padded host-side and the next publish is a full device_put."""
        cap = self.capacity
        while cap < need:
            cap *= 2
        if cap == self.capacity:
            return
        pad = cap - self.capacity
        self._h_vecs = np.concatenate(
            [self._h_vecs, np.zeros((pad, self._dim), self._h_vecs.dtype)])
        if self._h_scale is not None:
            self._h_scale = np.concatenate(
                [self._h_scale, np.zeros((pad,), np.float32)])
        self._h_valid = np.concatenate([self._h_valid, np.zeros((pad,), bool)])
        self._h_ids = np.concatenate(
            [self._h_ids, np.full((pad,), -1, np.int64)])
        for name, col in self._h_feats.items():
            self._h_feats[name] = np.concatenate(
                [col, np.zeros((pad,) + col.shape[1:], col.dtype)])

    def _publish(self, full: bool = False,
                 chunks: Optional[List[Tuple[np.ndarray, np.ndarray,
                                             Optional[np.ndarray],
                                             np.ndarray]]] = None) -> None:
        """Swap in a fresh `_Corpus` snapshot. `full` re-uploads the host
        mirrors wholesale (mass ingest / growth / full reload); else the
        encoded `chunks` [(ix, rows, scale, ok)] fold into the CURRENT
        device arrays through the fixed-shape scatter program."""
        cur = getattr(self, "_corpus", None)
        if full or cur is None or cur.vecs.shape[0] != self.capacity:
            vecs = jnp.asarray(self._h_vecs)
            scale = (jnp.asarray(self._h_scale)
                     if self._h_scale is not None else None)
            valid = jnp.asarray(self._h_valid)
        else:
            vecs, scale, valid = cur.vecs, cur.scale, cur.valid
            for ix, rows_new, scale_new, ok in chunks or []:
                vecs, scale, valid = self._scatter_jit(
                    vecs, scale, valid, jnp.asarray(rows_new),
                    (jnp.asarray(scale_new) if scale_new is not None
                     else None),
                    jnp.asarray(ix, jnp.int32), jnp.asarray(ok))
        self._corpus = _Corpus(vecs=vecs, scale=scale, valid=valid,
                               ids=self._h_ids.copy(), rows=self._rows)

    def _refresh_rows(self, rows_ix: np.ndarray, state) -> None:
        """Re-encode the given corpus rows in fixed-size chunks through
        the one compiled encode program; fold device-side when the dirty
        set is small, rebuild from mirrors when it is not (both paths
        compile nothing in steady state)."""
        rows_ix = np.asarray(rows_ix, np.int64)  # noqa: DRT002 — host row-index list, fold bookkeeping
        if rows_ix.size == 0:
            self._publish(full=False, chunks=[])
            return
        mass = rows_ix.size > max(self.chunk, self.capacity // 8)
        chunks = []
        for off in range(0, rows_ix.size, self.chunk):
            sl = rows_ix[off:off + self.chunk]
            n = sl.size
            ok = np.zeros((self.chunk,), bool)
            ok[:n] = True
            ix = np.zeros((self.chunk,), np.int64)
            ix[:n] = sl
            batch = {}
            for name, tmpl in self._templates.items():
                if name in self._h_feats:
                    col = self._h_feats[name][ix]
                else:
                    col = np.repeat(tmpl, self.chunk, axis=0)
                batch[name] = col
            rows_dev, scale_dev = self._encode_jit(
                state, self._jnp_batch(batch))
            rows_np = np.asarray(rows_dev)  # noqa: DRT002 — update-cadence mirror maintenance, never the predict path
            scale_np = (np.asarray(scale_dev)  # noqa: DRT002 — update-cadence mirror maintenance
                        if scale_dev is not None else None)
            self._h_vecs[sl] = rows_np[:n]
            if self._h_scale is not None:
                self._h_scale[sl] = scale_np[:n]
            self._h_valid[sl] = True
            if not mass:
                # keep the DEVICE arrays for the scatter (the host pull
                # above only feeds the mirror)
                chunks.append((ix, rows_dev, scale_dev, ok))
        self._publish(full=mass, chunks=chunks)

    # ------------------------------------------------------------- ingest

    def _coerce_item_col(self, name: str, v) -> np.ndarray:
        """Item feature column -> the stored [N, L] shape (the pad/trim
        rules of `parse_features`, minus the ragged-list path — ingest is
        a bulk array interface)."""
        from deeprec_tpu.utils.ragged import pad_rect

        want = self._pred.feature_dtypes[name]
        arr = np.asarray(v)
        if want.kind in "iu":
            f = next(f for f in self._trainer.sparse_specs
                     if f.name == name)
            return pad_rect(arr, f.max_len or 1, f.pad_value, want)
        arr = arr.astype(np.float32)
        return arr[:, None] if arr.ndim == 1 else arr

    def upsert_items(self, ids, features: Dict[str, np.ndarray]) -> int:
        """Ingest (or refresh) items: assign corpus rows, store the item
        feature columns, encode through the CURRENT model snapshot, and
        publish. Items hashing to another shard are silently skipped
        (broadcast ingest partitions itself); returns the number of rows
        this shard accepted. Duplicate ids within one call keep the LAST
        occurrence; re-ingesting an existing id re-encodes its row."""
        ids = np.asarray(ids, np.int64).reshape(-1)
        if ids.size == 0:
            return 0
        cols = {}
        for name in self.model.item_feats:
            if name not in features:
                raise BadRequest(f"ingest missing item feature {name!r}",
                                 feature=name)
            col = self._coerce_item_col(name, features[name])
            if col.shape[0] != ids.size:
                raise BadRequest(
                    f"item feature {name!r} has {col.shape[0]} rows for "
                    f"{ids.size} ids")
            cols[name] = col
        if self.num_shards > 1:
            keep = np.asarray(
                hash_shard_np(ids, self.num_shards)) == self.shard_index
            ids = ids[keep]
            cols = {k: v[keep] for k, v in cols.items()}
            if ids.size == 0:
                return 0
        # last-occurrence dedup within the call
        _, last = np.unique(ids[::-1], return_index=True)
        keep_ix = np.sort(ids.size - 1 - last)
        ids = ids[keep_ix]
        cols = {k: v[keep_ix] for k, v in cols.items()}
        with self._lock:
            pos = np.searchsorted(self._sid, ids)
            pos = np.clip(pos, 0, max(self._sid.size - 1, 0))
            exists = (self._sid.size > 0) & (
                self._sid[pos] == ids if self._sid.size else
                np.zeros(ids.shape, bool))
            rows_ix = np.empty(ids.shape, np.int64)
            rows_ix[exists] = self._srow[pos[exists]] \
                if self._sid.size else 0
            n_new = int((~exists).sum())
            if n_new:
                self._grow_to(self._rows + n_new)
                rows_ix[~exists] = self._rows + np.arange(n_new)
                self._rows += n_new
            for name, col in cols.items():
                if name not in self._h_feats:
                    self._h_feats[name] = np.zeros(
                        (self.capacity,) + col.shape[1:], col.dtype)
                elif self._h_feats[name].shape[0] < self.capacity:
                    old = self._h_feats[name]
                    padn = self.capacity - old.shape[0]
                    self._h_feats[name] = np.concatenate(
                        [old, np.zeros((padn,) + old.shape[1:], old.dtype)])
                self._h_feats[name][rows_ix] = col
            self._h_ids[rows_ix] = ids
            order = np.argsort(self._h_ids[:self._rows], kind="stable")
            self._sid = self._h_ids[:self._rows][order]
            self._srow = order.astype(np.int64)
            self._refresh_rows(rows_ix, self._pred._snap.state)
            self.corpus_rev += 1
        for c in self._reuse_caches:
            c.invalidate_stale()
        return int(ids.size)

    # ---------------------------------------------------------- freshness

    def on_model_update(self, dirnames: Optional[List[str]],
                        full: bool) -> None:
        """Model-update hook (called by the Predictor after every
        published update, inside its updater lock): fold the update into
        the corpus. Full reloads re-encode everything; delta replays
        re-encode only the rows whose item feature ids appear among the
        delta's changed table keys — discovered host-side from the delta
        files the replay just consumed (dirty rows only: the files are
        small by construction)."""
        t0 = time.time()
        with self._lock:
            state = self._pred._snap.state
            fp = self._dense_fp(state)
            drift = fp != self._dense_ref
            if drift:
                # dense item-tower drift: every resident vector is stale
                # regardless of which table keys the delta carried
                full = True
            self._dense_ref = fp
            if self._rows == 0:
                return
            if full or not dirnames:
                dirty = np.nonzero(self._h_valid[:self._rows])[0]
            else:
                changed: Dict[str, List[np.ndarray]] = {}
                for d in dirnames:
                    path = os.path.join(self._pred._ck.dir, d)
                    for fname, tname, bname, tag in self._item_tables:
                        try:
                            rows = self._pred._ck._load_rows(
                                path, bname, tag)
                        except Exception:
                            continue  # quarantined/missing: nothing to fold
                        if rows is None or "keys" not in rows:
                            continue
                        changed.setdefault(fname, []).append(
                            np.asarray(rows["keys"]))  # noqa: DRT002 — delta-file keys are host npz arrays
                if not changed:
                    return
                mask = np.zeros((self._rows,), bool)
                for fname, key_lists in changed.items():
                    col = self._h_feats.get(fname)
                    if col is None:
                        continue
                    keys = np.unique(np.concatenate(key_lists))
                    mask |= np.isin(
                        col[:self._rows], keys).reshape(
                            self._rows, -1).any(axis=1)
                dirty = np.nonzero(mask & self._h_valid[:self._rows])[0]
                if dirty.size == 0:
                    return
            self._refresh_rows(dirty, state)
            self.folds += 1
            self.corpus_rev += 1
            self.rows_folded += int(dirty.size)  # noqa: DRT002 — host np scalar, fold bookkeeping
            self.last_fold = {
                "time": time.time(),
                "seconds": round(time.time() - t0, 6),
                "rows": int(dirty.size),  # noqa: DRT002 — host np scalar, fold bookkeeping
                "version": self._pred._snap.version,
                "full": bool(full or not dirnames),
                "dense_drift": drift,
            }

    # ------------------------------------------------------------ retrieve

    @staticmethod
    def _bucket(n: int, lo: int = 4) -> int:
        return max(lo, 1 << max(int(n) - 1, 0).bit_length())  # noqa: DRT002 — host bucket math (name-collision reachability)

    def warmup(self, example: Dict[str, np.ndarray], k: int = 128) -> int:
        """Compile the user-tower buckets + the sweep for the current
        corpus shape before live traffic (and the default k bucket) —
        the retrieval analog of ModelServer.warmup."""
        n = 0
        one = {key: np.asarray(v)[:1] for key, v in example.items()}  # noqa: DRT002 — warmup path: host example batch
        b = 4
        while True:
            batch = {key: np.repeat(v, b, axis=0) for key, v in one.items()}
            self.retrieve(batch, k)
            n += 1
            if b >= self._bucket(len(next(iter(example.values())))):
                break
            b <<= 1
        return n

    def retrieve(self, batch: Dict[str, np.ndarray],
                 k: int) -> RetrievalResult:
        """Score the WHOLE resident corpus for each user row of `batch`
        (a parsed full-signature batch; item columns are inert pads) and
        return the top-k item ids + scores. One user-tower evaluation,
        one blocked corpus sweep — shared across the batch's rows."""
        if k < 1:
            raise BadRequest(f"k must be >= 1, got {k}")
        snap = self._pred._snap  # one atomic model snapshot
        corpus = self._corpus  # one atomic corpus snapshot
        first = next(iter(batch.values()))
        B = int(np.asarray(first).shape[0])  # noqa: DRT002 — host row count of the incoming request payload
        if B == 0:
            raise BadRequest("empty retrieval batch")
        Bp = self._bucket(B)
        jb = {}
        for name, v in batch.items():
            a = np.asarray(v)  # noqa: DRT002 — host request payload pad, pre-dispatch
            if Bp > B:
                a = np.concatenate(
                    [a, np.repeat(a[-1:], Bp - B, axis=0)])
            jb[name] = jnp.asarray(a)
        uvec = self._user_jit(snap.state, jb)
        kb = self._bucket(k, lo=1)
        vals, rows = self._sweep_jit(
            uvec, corpus.vecs, corpus.valid, k=kb,
            block_rows=self.block_rows, scale=corpus.scale)
        vals = np.asarray(vals)[:B, :k]  # noqa: DRT002 — result D2H: the reply must land on the host
        rows = np.asarray(rows)[:B, :k]  # noqa: DRT002 — result D2H: the reply must land on the host
        ids = np.where(rows >= 0, corpus.ids[np.clip(rows, 0, None)], -1)
        return RetrievalResult(
            ids=ids.astype(np.int64), scores=vals.astype(np.float32),
            version=snap.version, partial=False,
            scanned=corpus.rows * B)

    def attach_reuse_cache(self, cache) -> None:
        """Register a ReuseCache for corpus-edge invalidation: every
        ingest/fold that moves resident vectors bumps `corpus_rev` and
        drops the cache's stale entries (model-publish invalidation
        rides `Predictor.attach_reuse_cache` separately)."""
        self._reuse_caches.append(cache)

    # ----------------------------------------------------------- accounting

    def corpus_rows(self) -> int:
        return self._rows

    def corpus_bytes(self) -> int:
        """Measured resident bytes of the corpus sweep's read set,
        straight off the device array shapes (no sync) — the quantity
        `ops/traffic.py retrieval_sweep_bytes` models and the bench gate
        pins measured == modeled."""
        c = self._corpus
        total = int(c.vecs.size) * c.vecs.dtype.itemsize
        if c.scale is not None:
            total += int(c.scale.size) * c.scale.dtype.itemsize
        total += int(c.valid.size) * c.valid.dtype.itemsize
        return total

    def sweep_info(self) -> Dict:
        """Measured vs modeled per-sweep HBM bytes + the fp32 baseline —
        surfaced through `/v1/stats` and recorded by bench_retrieval."""
        from deeprec_tpu.ops import traffic

        cap = self.capacity
        return {
            "quantize": self.quantize,
            "corpus_rows": self._rows,
            "corpus_capacity": cap,
            "dim": self._dim,
            "block_rows": self.block_rows,
            "measured_bytes": self.corpus_bytes(),
            "modeled_bytes": traffic.retrieval_sweep_bytes(
                corpus_rows=cap, dim=self._dim,
                value_dtype=self.quantize, block_rows=self.block_rows),
            "fp32_bytes": traffic.retrieval_sweep_bytes(
                corpus_rows=cap, dim=self._dim, value_dtype="float32",
                block_rows=self.block_rows),
        }

    def host_vectors(self) -> Tuple[np.ndarray, np.ndarray]:
        """(ids [N], vectors [N, H] float32) of the live corpus — the
        EXACT-scan reference for recall measurement (fp32 engines return
        their stored vectors; quantized engines return the dequantized
        rows the sweep actually scores)."""
        with self._lock:
            n = self._rows
            vecs = np.asarray(self._h_vecs[:n], np.float32)
            if self._h_scale is not None:
                vecs = vecs * self._h_scale[:n, None]
            return self._h_ids[:n].copy(), vecs


class RetrievalServer:
    """Micro-batching front of the retrieval lane: concurrent requests
    coalesce into ONE user-tower batch and ONE corpus sweep (every block
    is read once for the whole coalesced batch), per-request answers are
    sliced back out. Accounts into the shared `ServingStats` (`retrieval`
    stage histogram, candidates-scanned counter) and registers the corpus
    gauges on its registry."""

    def __init__(self, engine: RetrievalEngine, *, max_batch: int = 128,
                 max_wait_ms: float = 1.0,
                 stats: Optional[ServingStats] = None,
                 reuse_cache_bytes: int = 0):
        self.engine = engine
        self.max_batch = int(max_batch)
        self.max_wait = max_wait_ms / 1000.0
        self.stats = stats if stats is not None else ServingStats()
        r = self.stats.registry
        if r is not None:
            r.register_callback(
                "deeprec_retrieval_corpus_rows", engine.corpus_rows,
                "live items resident in this shard's corpus matrix")
            r.register_callback(
                "deeprec_retrieval_corpus_bytes", engine.corpus_bytes,
                "resident bytes of the corpus sweep's read set")
        # Candidate cache (serving/reuse.py, OPT-IN): answers keyed
        # (user fp + k, (model version, corpus_rev)) — a hit can never
        # serve across a model publish (version component) NOR an item
        # ingest/fold (corpus_rev component), which is exactly the
        # freshness contract `train_to_serve_lag_seconds` is pinned on.
        self.reuse = None
        if reuse_cache_bytes > 0:
            from deeprec_tpu.serving.reuse import ReuseCache

            self.reuse = ReuseCache(
                reuse_cache_bytes, "retrieve", registry=r,
                version_fn=lambda: (engine._pred._snap.version,
                                    engine.corpus_rev))
            engine.attach_reuse_cache(self.reuse)
            engine._pred.attach_reuse_cache(self.reuse)
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()

    def submit(self, features: Dict[str, np.ndarray], k: int,
               no_cache: bool = False) -> "queue.Queue":
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        rows = int(np.asarray(next(iter(features.values()))).shape[0])  # noqa: DRT002 — host row count of the incoming request payload
        fp = None
        if self.reuse is not None and not no_cache:
            from deeprec_tpu.serving import reuse as _reuse

            # k is part of the key: the same user at k=10 and k=100 are
            # different answers
            fp = _reuse.request_fingerprint(
                features, extra=b"k%d" % int(k))
            hit = self.reuse.get_current(fp)
            if hit is not None:
                reply.put(hit[0])
                return reply
        self._q.put((features, rows, int(k), reply, time.monotonic(), fp))  # noqa: DRT002 — host k scalar from the request
        return reply

    def request_versioned(self, features: Dict[str, np.ndarray], k: int,
                          timeout: float = 30.0,
                          no_cache: bool = False) -> RetrievalResult:
        t0 = time.monotonic()
        out = self.submit(features, k, no_cache=no_cache).get(timeout=timeout)
        self.stats.record_stage("retrieval", time.monotonic() - t0)
        if isinstance(out, Exception):
            raise out
        return out

    def warmup(self, example: Dict[str, np.ndarray], k: int = 128) -> int:
        return self.engine.warmup(example, k=k)

    def _run(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            pending = [first]
            rows = first[1]
            deadline = time.monotonic() + self.max_wait
            while rows < self.max_batch:
                left = deadline - time.monotonic()
                try:
                    nxt = (self._q.get_nowait() if left <= 0
                           else self._q.get(timeout=left))
                except queue.Empty:
                    break
                pending.append(nxt)
                rows += nxt[1]
            self._serve(pending)

    def _serve(self, pending):
        try:
            # In-window memoization: identical in-flight requests (the
            # fingerprint covers features AND k) share one sweep slice.
            leaders = pending
            dups: Dict[bytes, List] = {}
            if self.reuse is not None:
                seen: Dict[bytes, bool] = {}
                leaders = []
                for p in pending:
                    fp = p[5]
                    if fp is not None and fp in seen:
                        dups.setdefault(fp, []).append(p)
                        continue
                    if fp is not None:
                        seen[fp] = True
                    leaders.append(p)
            reqs = [p[0] for p in leaders]
            sizes = [p[1] for p in leaders]
            kmax = max(p[2] for p in leaders)
            batch = {
                key: np.concatenate([np.asarray(r[key]) for r in reqs])  # noqa: DRT002 — micro-batch assembly of host request payloads before the one sweep
                for key in reqs[0]
            }
            rev0 = (self.reuse.current_version()
                    if self.reuse is not None else None)
            res = self.engine.retrieve(batch, kmax)
            off = 0
            per_row_scan = (res.scanned // max(sum(sizes), 1))
            # populate only when the (model version, corpus_rev) pair is
            # unchanged across the sweep AND matches the answer's stamp —
            # an ingest or publish racing the sweep makes this answer
            # unstorable (it still serves THIS request correctly)
            storable = (rev0 is not None
                        and rev0 == self.reuse.current_version()
                        and rev0[0] == res.version)
            for p, _sz in zip(leaders, sizes):
                _, n, k_i, reply, _ = p[:5]
                out = RetrievalResult(
                    ids=res.ids[off:off + n, :k_i],
                    scores=res.scores[off:off + n, :k_i],
                    version=res.version, partial=False,
                    scanned=per_row_scan * n)
                reply.put(out)
                if p[5] is not None:
                    for d in dups.get(p[5], ()):
                        d[3].put(out)
                    if storable:
                        self.reuse.put(p[5], rev0, RetrievalResult(
                            ids=np.ascontiguousarray(out.ids),
                            scores=np.ascontiguousarray(out.scores),
                            version=out.version, partial=False,
                            scanned=out.scanned))
                off += n
            self.stats.record_retrieval(len(pending), res.scanned)
        except Exception as e:
            self.stats.record_error(len(pending))
            for p in pending:
                p[3].put(e)

    def close(self):
        self._stop.set()
        self._worker.join(timeout=2)
