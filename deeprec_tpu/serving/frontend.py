"""Socket-tier serving scale-out: a front tier dispatching coalesced
batches across N backend serving processes.

The PR 5 `ServerGroup` is an in-process shared-queue dispatcher — one
member per device, one GIL, one process. This module generalizes that
dispatcher over a process boundary, the DeepRec SessionGroup story taken
to its multi-process form (SURVEY §2.4/§3.4): each **backend** is a full
serving process (Predictor + micro-batching ModelServer + its own
delta-chain poller, so model updates stay zero-stall per process), and
the **frontend** is a thin routing tier that speaks a compact
length-prefixed TCP protocol (the `remote_store.py` idiom) to whichever
backends are healthy.

Responsibilities split:
  * Backend — owns a model replica: restore (optionally into a quantized
    int8/bf16 residency), micro-batch coalescing, `poll_updates` against
    the shared checkpoint dir (`_run_poll_loop` survivability contract),
    per-process `/v1/stats`-shaped accounting.
  * Frontend — owns the client edge: feature parsing, request routing
    (round-robin for plain requests; user-group hash for `group_users`
    requests, so one user's `<user, N items>` traffic keeps landing on
    one backend and its sample-aware batches keep coalescing across the
    socket split), sibling retry on member failure (a SIGKILLed backend
    mid-batch costs a retry, never a failed request), member
    health/backoff, and the merged stats/health surfaces: `/healthz` is
    the WORST member (plus the frontend's own member-availability view),
    `/v1/stats` spans every remote member.

Wire protocol (all little-endian, one frame per message):
  frame    : 4-byte op | u32 body length | body
  PRED     : body = u8 flags (bit0 = group_users) + npz(features)
             reply body = npz('__version__', 'predictions' | 'task:<t>'*)
  HLTH/STAT/INFO/POLL : empty body; reply body = JSON
  replies  : b"OK  " frame, or b"ERR " frame with JSON
             {"error": ..., "kind": "bad_request" | "server"}

Run a backend:  python -m deeprec_tpu.serving.frontend --backend \
                    --model wdl --ckpt DIR --port 0 [--quantize int8]
Run the tier :  python -m deeprec_tpu.serving.frontend --frontend \
                    --model wdl --backends host:p1,host:p2 --http-port 8500
"""
from __future__ import annotations

import io
import itertools
import json
import random
import socket
import socketserver
import struct
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from deeprec_tpu.analysis.annotations import guarded_by
from deeprec_tpu.utils import backoff
from deeprec_tpu.obs import metrics as obs_metrics
from deeprec_tpu.obs import schema as obs_schema
from deeprec_tpu.obs import trace as obs_trace
from deeprec_tpu.serving.stats import ServingStats
from deeprec_tpu.serving.predictor import (
    BadRequest,
    _run_poll_loop,
)

_MAX_FRAME = 256 << 20  # sanity bound on one frame's body

OP_PRED = b"PRED"
OP_HLTH = b"HLTH"
OP_STAT = b"STAT"
OP_POLL = b"POLL"
OP_INFO = b"INFO"
OP_METR = b"METR"  # obs metrics snapshot (JSON) — the /metrics merge op
# Full-corpus retrieval (serving/retrieval.py): RETR sweeps this
# backend's corpus SHARD (body = u8 flags + u32 k + npz user features;
# reply npz ids/scores/version/scanned), RITM ingests items (body =
# npz '__ids__' + item features; every member receives the broadcast
# and keeps only the rows that hash to its shard).
OP_RETR = b"RETR"
OP_RITM = b"RITM"
_OK = b"OK  "
_ERR = b"ERR "

_FLAG_GROUP_USERS = 1
# bit1: the npz body is prefixed by obs_trace.WIRE_BYTES of trace
# context (two LE u64s: trace id, parent span id) — how a sampled
# request's trace id crosses the frontend->backend socket hop
_FLAG_TRACE = 2
# bit2 (PRED flags byte and the RETR leading flags byte alike): force a
# real evaluation through a warm compute-reuse cache — no cache read,
# no write, no in-window memo sharing. The canary/quality-gate probe
# and parity-test contract (serving/reuse.py, docs/serving.md).
_FLAG_NO_CACHE = 4


# ------------------------------------------------------------ frame helpers


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    out = b""
    while len(out) < n:
        chunk = sock.recv(n - len(out))
        if not chunk:
            raise ConnectionError("peer closed")
        out += chunk
    return out


def _send_frame(wfile, op: bytes, body: bytes) -> None:
    wfile.write(op + struct.pack("<I", len(body)) + body)
    wfile.flush()


def _pack_arrays(arrays: Dict[str, np.ndarray]) -> bytes:
    """Dict of numpy arrays -> npz bytes (dtype/shape preserving, no
    pickle — array payloads only, so a hostile peer can't smuggle
    objects through the wire format)."""
    buf = io.BytesIO()
    np.savez(buf, **{k: np.asarray(v) for k, v in arrays.items()})  # noqa: DRT002 — wire serialization of HOST request payloads; no device value crosses here
    return buf.getvalue()


def _unpack_arrays(body: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(body), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


# ----------------------------------------------------------------- backend


@guarded_by("_conn_lock")
class BackendServer:
    """Serve one ModelServer (or ServerGroup) over the socket protocol —
    the per-process half of the tier. Connections are handled by
    stdlib threads; every PRED blocks on the model server's coalescing
    queue, so concurrent frontend connections batch into full device
    batches exactly like local callers (the socket adds transport, not a
    second batching policy). `_conns` (the live-connection registry
    stop() severs) is the only cross-thread field, guarded by
    `_conn_lock`."""

    def __init__(self, model_server, host: str = "127.0.0.1", port: int = 0,
                 *, registry=None, capacity: int = 1, member_name: str = "",
                 lease_delay_secs: float = 0.0):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def setup(self):
                super().setup()
                with outer._conn_lock:
                    outer._conns.add(self.connection)

            def finish(self):
                with outer._conn_lock:
                    outer._conns.discard(self.connection)
                super().finish()

            def handle(self):
                while True:
                    hdr = self.rfile.read(8)
                    if len(hdr) < 8:
                        return
                    op, n = hdr[:4], struct.unpack("<I", hdr[4:])[0]
                    if n > _MAX_FRAME:
                        return
                    body = self.rfile.read(n)
                    if len(body) < n:
                        return
                    try:
                        out = outer._dispatch(op, body)
                    except BadRequest as e:
                        out = (_ERR, json.dumps(
                            {**e.details, "kind": "bad_request"}).encode())
                    except Exception as e:  # request-level: keep serving
                        out = (_ERR, json.dumps(
                            {"error": str(e), "kind": "server"}).encode())
                    _send_frame(self.wfile, out[0], out[1])

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True
            request_queue_size = 128  # the PR 5 accept-queue lesson

            def handle_error(self, request, client_address):
                # A frontend dropping a pooled connection (its own
                # shutdown, a member backoff) is normal churn, not a
                # stack-trace event; real request errors were already
                # answered with an ERR frame by the handler.
                import logging

                logging.getLogger(__name__).debug(
                    "connection error from %s", client_address,
                    exc_info=True)

        self.server = model_server
        self._t0 = time.monotonic()
        self._conns: set = set()
        self._conn_lock = threading.Lock()
        self._inflight = 0  # live PRED frames (guarded by _conn_lock)
        self._srv = Server((host, port), Handler)
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None
        # Fleet membership (serving/fleet.py): with a registry, this
        # backend announces itself by stamping a lease (addr, capacity,
        # model_version, started_at) — frontends admit it at runtime; a
        # SIGKILL leaves the lease to go stale (eviction), a drain exits
        # politely. `lease_delay_secs` defers the FIRST stamp (the
        # slow-joiner fault: reachable but unannounced — the fleet must
        # not route to it until the lease lands).
        self.addr = f"{host}:{self.port}"
        self.stamper = None
        self._lease_delay = lease_delay_secs
        self._lease_timer: Optional[threading.Timer] = None
        if registry is not None:
            from deeprec_tpu.serving import fleet as _fleet

            if isinstance(registry, str):
                registry = _fleet.FleetRegistry(registry)
            self.stamper = _fleet.LeaseStamper(
                registry, self.addr, role=_fleet.ROLE_BACKEND,
                capacity=capacity, name=member_name,
                version_fn=lambda: self.server.predictor.version)

    def _dispatch(self, op: bytes, body: bytes) -> Tuple[bytes, bytes]:
        if op == OP_PRED:
            if not body:
                raise BadRequest("empty PRED body")
            grouped = bool(body[0] & _FLAG_GROUP_USERS)
            no_cache = bool(body[0] & _FLAG_NO_CACHE)
            off = 1
            ctx = None
            if body[0] & _FLAG_TRACE:
                ctx = obs_trace.unpack_wire(body[1:1 + obs_trace.WIRE_BYTES])
                off = 1 + obs_trace.WIRE_BYTES
            batch = _unpack_arrays(body[off:])
            if not batch:
                raise BadRequest("missing 'features' object")
            with self._conn_lock:
                self._inflight += 1
            try:
                probs, version = self.server.request_versioned(
                    batch, group_users=grouped, trace_ctx=ctx,
                    no_cache=no_cache)
            finally:
                with self._conn_lock:
                    self._inflight -= 1
            out = {"__version__": np.int64(version)}
            if isinstance(probs, dict):
                for k, v in probs.items():
                    out["task:" + k] = np.asarray(v)
            else:
                out["predictions"] = np.asarray(probs)
            return _OK, _pack_arrays(out)
        if op == OP_HLTH:
            return _OK, json.dumps(self.server.predictor.health()).encode()
        if op == OP_STAT:
            snap = self.server.stats_snapshot()
            # True backend-process CPU seconds ride along: the frontend's
            # scale-out model needs the serial-per-request CPU split
            # between tiers, which wall-clock histograms can't give.
            snap["process_cpu_seconds"] = time.process_time()
            snap["uptime_seconds"] = round(time.monotonic() - self._t0, 3)
            return _OK, json.dumps(snap).encode()
        if op == OP_POLL:
            updated = bool(self.server.predictor.poll_updates())
            return _OK, json.dumps({"updated": updated}).encode()
        if op == OP_INFO:
            return _OK, json.dumps(self.server.predictor.model_info()).encode()
        if op == OP_METR:
            # obs-plane snapshot (mergeable JSON, obs/metrics.py): the
            # frontend relabels it per member for the tier /metrics.
            fn = getattr(self.server, "metrics_snapshot", None)
            snap = fn() if fn is not None else {"metrics": {}}
            return _OK, json.dumps(snap).encode()
        if op == OP_RETR:
            if len(body) < 5:
                raise BadRequest("short RETR body")
            if getattr(self.server, "retrieval", None) is None:
                raise BadRequest("retrieval not enabled on this backend")
            no_cache = bool(body[0] & _FLAG_NO_CACHE)
            k = struct.unpack("<I", body[1:5])[0]
            batch = _unpack_arrays(body[5:])
            if not batch:
                raise BadRequest("missing retrieval features")
            with self._conn_lock:
                self._inflight += 1
            try:
                res = self.server.retrieve_versioned(batch, int(k),
                                                     no_cache=no_cache)
            finally:
                with self._conn_lock:
                    self._inflight -= 1
            return _OK, _pack_arrays({
                "ids": res.ids, "scores": res.scores,
                "__version__": np.int64(res.version),
                "scanned": np.int64(res.scanned),
            })
        if op == OP_RITM:
            rs = getattr(self.server, "retrieval", None)
            if rs is None:
                raise BadRequest("retrieval not enabled on this backend")
            arrays = _unpack_arrays(body)
            ids = arrays.pop("__ids__", None)
            if ids is None:
                raise BadRequest("RITM body missing '__ids__'")
            accepted = rs.engine.upsert_items(ids, arrays)
            return _OK, json.dumps({
                "accepted": int(accepted),
                "corpus_rows": rs.engine.corpus_rows(),
                "shard": [rs.engine.shard_index, rs.engine.num_shards],
            }).encode()
        raise BadRequest(f"unknown op {op!r}")

    def inflight(self) -> int:
        with self._conn_lock:
            return self._inflight

    def start(self) -> "BackendServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True)
        self._thread.start()
        if self.stamper is not None:
            if self._lease_delay > 0:
                # slow joiner: serve but don't announce yet — the first
                # stamp (and with it fleet admission) lands later
                self._lease_timer = threading.Timer(
                    self._lease_delay, lambda: self.stamper.start())
                self._lease_timer.daemon = True
                self._lease_timer.start()
            else:
                self.stamper.start()
        return self

    def drain(self, timeout: float = 30.0, respawn: bool = False,
              quiet_rounds: int = 3, poll_secs: float = 0.05) -> int:
        """The leaving half of the EXIT_RESCALE choreography applied to
        serving: stamp the lease ``draining`` (frontends stop NEW
        assignments within one membership sweep), let in-flight grouped
        streams finish (`quiet_rounds` consecutive polls with zero live
        PRED frames and an idle coalescing queue — one empty poll can be
        a gap between a stream's requests), then stop and unregister.
        Returns the exit code to leave with: EXIT_RESCALE when
        `respawn` (a supervisor respawns the member for free — rolling
        restart), else 0 (retirement)."""
        if self.stamper is not None:
            self.stamper.begin_drain(respawn=respawn)
        deadline = time.monotonic() + timeout
        quiet = 0
        while time.monotonic() < deadline and quiet < quiet_rounds:
            qsize_fn = getattr(getattr(self.server, "_q", None),
                               "qsize", lambda: 0)
            quiet = (quiet + 1
                     if self.inflight() == 0 and qsize_fn() == 0 else 0)
            time.sleep(poll_secs)
        self.stop()
        if self.stamper is not None:
            return self.stamper.exit_code()
        from deeprec_tpu.parallel.elastic import EXIT_RESCALE

        return EXIT_RESCALE if respawn else 0

    def stop(self, unregister: bool = True) -> None:
        """Stop listening AND sever live connections — so an in-process
        stop is a faithful stand-in for backend-process death (a real
        SIGKILL drops every established socket, and the fault tests rely
        on the frontend observing exactly that). `unregister=False`
        additionally leaves the lease behind to go STALE, which is what
        a real SIGKILL does — the eviction-path tests want exactly
        that."""
        if self._lease_timer is not None:
            # a slow joiner stopped BEFORE its deferred first stamp must
            # never announce a dead server afterwards
            self._lease_timer.cancel()
            self._lease_timer = None
        if self.stamper is not None:
            self.stamper.stop(unregister=unregister)
        self._srv.shutdown()
        self._srv.server_close()
        with self._conn_lock:
            conns = list(self._conns)
        for c in conns:
            try:
                c.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.close()
            except OSError:
                pass
        if self._thread:
            self._thread.join(timeout=2)


# ---------------------------------------------------------------- frontend


@guarded_by("_lock")
class _Member:
    """One backend endpoint: a small socket pool plus health/backoff
    state. Pool checkout/checkin and all state transitions go through
    the methods (which take `_lock`); `call()` holds no lock while
    waiting on the wire, so N request threads fan out to N backends
    concurrently."""

    def __init__(self, host: str, port: int, connect_timeout: float,
                 backoff_base: float, backoff_max: float):
        self.host, self.port = host, port
        self.connect_timeout = connect_timeout
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self._lock = threading.Lock()
        self._pool: List[socket.socket] = []
        self.fails = 0
        self.down_until = 0.0
        self.requests = 0
        self.errors = 0
        self.health: Dict = {}
        # Fleet-membership view (set by the frontend's membership sweep
        # under ITS lock; plain attribute reads elsewhere — a stale read
        # is one routing round behind, which churn tolerates by design):
        # a draining member takes no NEW assignments but finishes
        # in-flight grouped streams; lease carries capacity/version.
        self.draining = False
        self.lease: Optional[object] = None
        # Last obs snapshot this member answered with: a DOWN member's
        # series re-render from it stale-marked — visible absence, not
        # silent disappearance (guarded by _lock like the rest).
        self.last_metrics: Optional[Dict] = None
        self._rng = random.Random((host, port).__hash__() & 0xFFFFFFFF)

    @property
    def addr(self) -> str:
        return f"{self.host}:{self.port}"

    def available(self, now: float) -> bool:
        with self._lock:
            return now >= self.down_until

    def _checkout(self, connect_timeout: float) -> socket.socket:
        with self._lock:
            if self._pool:
                return self._pool.pop()
        return socket.create_connection(
            (self.host, self.port), timeout=connect_timeout)

    def _checkin(self, sock: socket.socket) -> None:
        with self._lock:
            self._pool.append(sock)

    def call(self, op: bytes, body: bytes,
             timeout: float) -> Tuple[bytes, bytes]:
        """One framed round trip. Socket-level failures close the
        connection and re-raise (the frontend marks the member down and
        retries a sibling). The retry after a failed POOLED socket dials
        FRESH — a backend restart strands every idle pooled socket, and
        popping a second stale one would fail a request against a
        perfectly healthy member."""
        # Dialing is bounded by BOTH the member's connect budget and the
        # caller's own timeout — a 1 s health probe must not pay a 5 s
        # connect to a partitioned host.
        dial = min(self.connect_timeout, timeout)
        attempts = 2
        for i in range(attempts):
            sock = (self._checkout(dial) if i == 0 else
                    socket.create_connection((self.host, self.port),
                                             timeout=dial))
            try:
                sock.settimeout(timeout)
                sock.sendall(op + struct.pack("<I", len(body)) + body)
                hdr = _recv_exact(sock, 8)
                status, n = hdr[:4], struct.unpack("<I", hdr[4:])[0]
                if n > _MAX_FRAME:
                    raise ConnectionError(f"oversized reply frame ({n}B)")
                resp = _recv_exact(sock, n)
            except (OSError, ConnectionError):
                try:
                    sock.close()
                except OSError:
                    pass
                if i + 1 == attempts:
                    raise
                continue
            self._checkin(sock)
            with self._lock:
                self.requests += 1
            return status, resp
        raise ConnectionError("unreachable")  # pragma: no cover

    def mark_down(self) -> float:
        """Record a failure; returns the backoff deadline. Capped
        exponential with jitter (the shared `utils/backoff.py` policy),
        so N frontend threads hitting one dead backend don't re-probe in
        lockstep."""
        with self._lock:
            self.fails += 1
            self.errors += 1
            delay = backoff.jittered_backoff(
                self.fails, self.backoff_base, self.backoff_max,
                self._rng, max_exponent=8)
            self.down_until = time.monotonic() + delay
            # A dead backend's pooled sockets are dead too.
            pool, self._pool = self._pool, []
        for s in pool:
            try:
                s.close()
            except OSError:
                pass
        return delay

    def mark_up(self, health: Optional[Dict] = None) -> None:
        with self._lock:
            self.fails = 0
            self.down_until = 0.0
            if health is not None:
                self.health = health

    def snapshot(self) -> Dict:
        with self._lock:
            out = {
                "addr": self.addr,
                "up": time.monotonic() >= self.down_until,
                "fails": self.fails,
                "requests": self.requests,
                "errors": self.errors,
                "draining": self.draining,
            }
        lease = self.lease
        if lease is not None:
            out["lease"] = {
                "capacity": lease.capacity,
                "model_version": lease.model_version,
                "age_seconds": round(lease.age, 3),
                "started_at": lease.started_at,
            }
        return out

    def close(self) -> None:
        with self._lock:
            pool, self._pool = self._pool, []
        for s in pool:
            try:
                s.close()
            except OSError:
                pass


class _FrontendPredictor:
    """Predictor facade for the frontend tier, so `HttpServer` (and the
    online-loop plumbing) binds a Frontend exactly like a ModelServer:
    feature parsing comes from a LOCAL spec-only trainer (no checkpoint,
    no table state — the model object is only read for its feature
    specs), health is the WORST member merged with the frontend's own
    member-availability view, model_info/poll fan out over the wire."""

    def __init__(self, fe: "Frontend", model):
        self._fe = fe
        self.model = model
        # parse_features clamp accounting (negative ids, oversized bags,
        # non-finite dense): the edge parses BEFORE routing, so the
        # frontend keeps its own counters — without this method the
        # clamp path would AttributeError mid-parse and abort requests
        # the firewall is documented to clamp-and-serve.
        self.record_errors: Dict[str, int] = {}
        self._trainer = None
        if model is not None:
            import optax

            from deeprec_tpu.optim.sparse import GradientDescent
            from deeprec_tpu.training.trainer import Trainer

            self._trainer = Trainer(model, GradientDescent(),
                                    optax.identity())

    @property
    def feature_dtypes(self) -> Dict[str, "np.dtype"]:
        if self._trainer is None:
            raise RuntimeError(
                "Frontend(model=None) cannot parse wire features — pass "
                "the model to Frontend() for HTTP serving")
        from deeprec_tpu import features as fcol

        out = {}
        cfgs = {n: t.cfg for n, t in self._trainer.tables.items()}
        for f in self._trainer.sparse_specs:
            out[f.name] = np.dtype(cfgs[fcol.resolve_table_name(f)].key_dtype)
        for f in self._trainer.dense_specs:
            out[f.name] = np.dtype(np.float32)
        return out

    def count_record_error(self, kind: str, n: int = 1) -> None:
        """Same contract as Predictor.count_record_error (the parser
        calls it on every clamp) — counted into this edge's own series."""
        self.record_errors[kind] = self.record_errors.get(kind, 0) + n
        if obs_metrics.metrics_enabled():
            obs_metrics.default_registry().counter(
                "deeprec_record_errors",
                "malformed input records rejected/clamped by kind",
                {"kind": kind},
            ).inc(n)

    def health(self) -> Dict:
        """Worst-member health + the frontend's availability view: 'ok'
        only when every member is reachable and healthy. A member that is
        down (socket-level) contributes a synthetic degraded entry — a
        dead process can't speak for itself."""
        return self._fe._health_sweep()

    def model_info(self) -> Dict:
        status, body = self._fe._call_any(OP_INFO, b"")
        if status != _OK:
            raise RuntimeError(
                f"backend model_info failed: {body.decode('utf-8', 'replace')}")
        info = json.loads(body)
        info["members"] = len(self._fe._members)
        return info

    def poll_updates(self) -> bool:
        """The frontend's poll round: refresh member health (marking
        recovered members back up) and, when the frontend drives updates
        (`poll_backends=True`), broadcast POLL so every backend replays
        the delta chain. Backends normally self-poll (poll_secs on the
        backend CLI) — delta replay stays per-process and zero-stall
        either way."""
        h = self._fe._health_sweep()
        if h.get("reachable", 0) == 0:
            raise RuntimeError(
                f"no reachable backends among {[m.addr for m in self._fe._members]}")
        updated = False
        if self._fe.poll_backends:
            for m in list(self._fe._members):
                if not m.available(time.monotonic()):
                    continue
                try:
                    status, body = m.call(OP_POLL, b"", self._fe.timeout)
                except (OSError, ConnectionError):
                    m.mark_down()
                    continue
                if status == _OK:
                    updated = json.loads(body).get("updated") or updated
        return updated


class Frontend:
    """Route requests across N backend serving processes.

    Duck-type compatible with ModelServer where it matters
    (`request_versioned` / `request` / `warmup` / `stats_snapshot` /
    `.predictor` / `close`), so `HttpServer(Frontend(...))` is the
    multi-process serving tier.

    Routing: plain requests round-robin over available members; grouped
    (`group_users=True`) requests route on a consistent-hash ring
    (virtual nodes over the member set, `serving/fleet.py`) keyed by a
    hash of the USER feature payload, so one user's candidate batches
    keep hitting one backend and its sample-aware coalescing (user
    tower once per distinct user per device batch) survives the socket
    split AND survives membership churn — a join/leave remaps only
    ~1/N of users instead of reshuffling everyone. On a member failure
    the request retries along the ring's preference order (which is
    exactly where those users will land if the member really left) —
    a killed backend costs latency, never a failed request, as long as
    one member lives.

    Membership is either a static `backends` list (the PR 10 shape), a
    `registry` (a `fleet.FleetRegistry` or its directory path: lease-
    file discovery — members admit themselves by stamping a lease and
    retire by draining or going stale), or both (static seeds are
    permanent, leased members come and go at runtime).
    """

    def __init__(self,
                 backends: Optional[
                     Sequence[Union[str, Tuple[str, int]]]] = None,
                 model=None, *, registry=None, timeout: float = 30.0,
                 connect_timeout: float = 5.0,
                 backoff_base: float = 0.2, backoff_max: float = 5.0,
                 health_secs: float = 0.0, poll_backends: bool = False,
                 membership_secs: float = 1.0, reprobe_secs: float = 2.0,
                 vnodes: int = 64, lease_secs: Optional[float] = None):
        from deeprec_tpu.serving import fleet as _fleet

        self._fleet_mod = _fleet
        # lease_secs must match the fleet's --lease-secs: a frontend
        # sweeping with a SHORTER bound than the members' stamp cadence
        # (lease_secs/3) would flap them in and out of membership.
        self.registry = (
            _fleet.FleetRegistry(
                registry, **({"lease_secs": lease_secs}
                             if lease_secs is not None else {}))
            if isinstance(registry, str) else registry)
        if not backends and self.registry is None:
            raise ValueError(
                "need at least one backend address or a fleet registry")
        self._member_kwargs = dict(connect_timeout=connect_timeout,
                                   backoff_base=backoff_base,
                                   backoff_max=backoff_max)
        self.vnodes = vnodes
        self._static_addrs = ["%s:%d" % self._parse_addr(b)
                              for b in (backends or [])]
        # Membership state: mutated ONLY under _mlock by whole-object
        # replacement (new list/dict/ring assigned atomically), so
        # request paths read a coherent snapshot lock-free.
        self._mlock = threading.Lock()
        self._by_addr: Dict[str, _Member] = {}
        self._members: List[_Member] = []
        self._ring = _fleet.HashRing([], vnodes=vnodes)
        self._routing_view: Dict[str, bool] = {}  # addr -> draining
        self.membership_rounds = 0
        with self._mlock:
            self._apply_membership(self._membership_view())
        self.timeout = timeout
        self.poll_backends = poll_backends
        self.stats = ServingStats()
        r = self.stats.registry
        if r is not None:
            r.register_callback(
                "deeprec_frontend_members", lambda: len(self._members),
                "admitted backend members")
            r.register_callback(
                "deeprec_frontend_members_up",
                lambda: sum(1 for m in self._members
                            if m.available(time.monotonic())),
                "members currently routable (not backed off)")
            r.register_callback(
                "deeprec_frontend_members_draining",
                lambda: sum(1 for m in self._members if m.draining),
                "members draining (in-flight only, no new assignments)")
        self.update_failures = 0  # _run_poll_loop accounting
        # Retrieval fan-out accounting: requests through the merge and
        # how many were served PARTIAL (one or more shards missing —
        # degraded-not-failed; surfaced through health()).
        self._retr_requests = 0
        self._retr_partials = 0
        self._m_retr_partials = (
            r.counter("deeprec_retrieval_partial_responses",
                      "fleet retrievals served with one or more shards "
                      "missing")
            if r is not None else None)
        self.predictor = _FrontendPredictor(self, model)
        self._rr = itertools.count()
        self._stop = threading.Event()
        self._poller = None
        if health_secs > 0:
            self._poller = threading.Thread(
                target=_run_poll_loop, args=(self, self._stop, health_secs),
                daemon=True)
            self._poller.start()
        self._membership_thread = None
        if self.registry is not None and membership_secs > 0:
            self._membership_thread = threading.Thread(
                target=self._membership_loop, args=(membership_secs,),
                daemon=True, name="fleet-membership")
            self._membership_thread.start()
        self.reprobe_secs = reprobe_secs
        self._reprober = None
        if reprobe_secs > 0:
            self._reprober = threading.Thread(
                target=self._reprobe_loop, daemon=True,
                name="member-reprobe")
            self._reprober.start()

    @staticmethod
    def _parse_addr(b) -> Tuple[str, int]:
        if isinstance(b, str):
            host, port = b.rsplit(":", 1)
            return host, int(port)  # noqa: DRT002 — parsing a host:port config string, not a device value
        host, port = b
        return host, int(port)  # noqa: DRT002 — parsing a host:port config tuple, not a device value

    # ---------------------------------------------------------- membership

    def _membership_view(self) -> Dict[str, Optional[object]]:
        """Desired membership right now: static seeds (always, with no
        lease) plus every live backend lease in the registry. One
        registry sweep — stale leases are already evicted and duplicate
        addrs already arbitrated by `FleetRegistry.members`."""
        desired: Dict[str, Optional[object]] = {
            a: None for a in self._static_addrs}
        if self.registry is not None:
            for lease in self.registry.members(self._fleet_mod.ROLE_BACKEND):
                desired[lease.addr] = lease
        return desired

    def _apply_membership(self, desired: Dict[str, Optional[object]]
                          ) -> Tuple[List[str], List[str]]:
        """Reconcile the member set (caller holds `_mlock`): admit new
        addrs, retire vanished ones (evicted/unregistered — their socket
        pools close), update drain flags, and rebuild the routing ring
        over non-draining members. Returns (admitted, retired) addrs."""
        by_addr = dict(self._by_addr)
        admitted, retired = [], []
        for addr, lease in desired.items():
            m = by_addr.get(addr)
            if m is None:
                host, port = addr.rsplit(":", 1)
                m = _Member(host, int(port), **self._member_kwargs)  # noqa: DRT002 — parsing a lease addr string, host-side control plane
                by_addr[addr] = m
                admitted.append(addr)
            m.lease = lease  # refresh age/version view even when routing
            # is unchanged (member snapshots report it)
            m.draining = bool(lease is not None and lease.draining)
        for addr in set(by_addr) - set(desired):
            retired.append(addr)
            by_addr.pop(addr).close()
        self._by_addr = by_addr
        # Rebuild the routing view (ordered list + hash ring: N*vnodes
        # hashes + a sort) only when the (membership, drain) view
        # actually changed — sweeps run every membership_secs AND on
        # every /healthz and /v1/stats call, and steady state is
        # no-change ~always. membership_rounds therefore counts CHURN
        # events, not sweeps.
        view = {a: by_addr[a].draining for a in by_addr}
        if admitted or retired or view != self._routing_view:
            self._routing_view = view
            # static seeds keep their GIVEN order (callers index
            # fe._members against the list they constructed with — the
            # PR 10 contract); leased members follow, sorted so every
            # frontend replica agrees
            static = [a for a in self._static_addrs if a in by_addr]
            dynamic = sorted(a for a in by_addr if a not in set(static))
            self._members = [by_addr[a] for a in static + dynamic]
            self._ring = self._fleet_mod.HashRing(
                [a for a, m in by_addr.items() if not m.draining],
                vnodes=self.vnodes)
            self.membership_rounds += 1
        return admitted, retired

    def refresh_membership(self) -> Tuple[List[str], List[str]]:
        """One reconcile round against the registry (the membership
        thread's body; callable directly for deterministic tests and
        for lazy refresh when routing finds nobody)."""
        if self.registry is None:
            return [], []
        desired = self._membership_view()
        with self._mlock:
            return self._apply_membership(desired)

    def _membership_loop(self, secs: float) -> None:
        while not self._stop.wait(secs):
            try:
                self.refresh_membership()
            except Exception:
                # a failed sweep (FS blip) keeps the previous view; the
                # next round retries — discovery must never kill routing
                pass

    def _reprobe_loop(self) -> None:
        """Periodic re-probe of members in failure backoff: a backend
        that died and came back at the SAME addr (process restart under
        an external supervisor — no membership churn, static lists
        included) is readmitted to routing without waiting for live
        traffic to risk a request on it or for an operator to restart
        the frontend."""
        while not self._stop.wait(self.reprobe_secs):
            now = time.monotonic()
            for m in list(self._members):
                if self._stop.is_set():
                    return
                if m.available(now) and m.fails == 0:
                    continue  # healthy: nothing to re-probe
                try:
                    self._probe_member(m)  # marks up/down itself
                except Exception:
                    pass  # probing must never kill the loop

    # ------------------------------------------------------------- routing

    def _order(self, key: Optional[int] = None) -> List[_Member]:
        """Members in attempt order for ONE request.

        Plain requests (`key=None`): round-robin over non-draining
        members. Grouped requests: the ring's preference order for
        `key` — the owner first, then the members those users would
        land on if the owner left, so failover and post-churn routing
        agree.

        Within the chosen order, available members come first and
        backed-off ones ride along as a last resort (with every sibling
        dead, trying a 'down' member beats failing the request — it may
        just have restarted). Draining members are last of all: they
        take no new assignments unless nobody else exists."""
        members = self._members  # atomic snapshot (replaced, not mutated)
        if not members:
            raise RuntimeError("no fleet members admitted")
        if key is not None:
            ring = self._ring
            by_addr = self._by_addr
            pref = [by_addr[a] for a in ring.preference(key)
                    if a in by_addr]
            chosen = set(id(m) for m in pref)
            order = pref + [m for m in members if id(m) not in chosen]
        else:
            routable = [m for m in members if not m.draining]
            pool = routable or members  # everyone draining: serve anyway
            n = len(pool)
            s = next(self._rr) % n
            order = [pool[(s + i) % n] for i in range(n)]
            order += [m for m in members if m.draining] if routable else []
        now = time.monotonic()
        up = [m for m in order if m.available(now)]
        down = [m for m in order if not m.available(now)]
        return up + down

    def _group_key(self, batch: Dict[str, np.ndarray]) -> int:
        """Stable routing hash of the request's user-feature payload.
        crc32, not builtin hash(): bytes hashing is salted per process,
        which would re-shuffle user→backend affinity on every frontend
        restart (and make routing unreproducible across a tier of
        frontends)."""
        import zlib

        feats = getattr(self.predictor.model, "user_feats", None)
        h = 0
        if feats:
            for name in feats:
                v = batch.get(name)
                if v is not None:
                    # first row identifies the user for <user, N items>
                    h ^= zlib.crc32(np.asarray(v)[:1].tobytes())  # noqa: DRT002 — routing hash of the HOST request payload; no device value crosses here
        return h & 0x7FFFFFFF

    def _call_any(self, op: bytes, body: bytes,
                  key: Optional[int] = None,
                  timeout: Optional[float] = None) -> Tuple[bytes, bytes]:
        """Send one frame to the first member that answers, in routing
        order (`key` = grouped ring routing); marks failed members down
        along the way. With a registry and an empty member set, one
        forced membership sweep runs first — a frontend that started
        before its backends admits them the moment their leases land."""
        if not self._members and self.registry is not None:
            self.refresh_membership()
        last: Optional[Exception] = None
        for m in self._order(key):
            try:
                status, resp = m.call(op, body,
                                      timeout if timeout is not None
                                      else self.timeout)
            except (OSError, ConnectionError) as e:
                m.mark_down()
                last = e
                continue
            m.mark_up()
            return status, resp
        raise RuntimeError(
            f"all {len(self._members)} backends unreachable "
            f"({[m.addr for m in self._members]})"
        ) from last

    # ------------------------------------------------------------ requests

    def request(self, features: Dict[str, np.ndarray],
                timeout: Optional[float] = None,
                group_users: bool = False):
        return self.request_versioned(features, timeout, group_users)[0]

    def request_versioned(self, features: Dict[str, np.ndarray],
                          timeout: Optional[float] = None,
                          group_users: bool = False,
                          trace_ctx: Optional[Tuple[int, int]] = None,
                          no_cache: bool = False):
        """(result, model_version) through whichever backend answered.
        The version stamps the BACKEND snapshot that served the whole
        request (coalesced neighbors on that backend share it).

        A sampled trace context (`trace_ctx`, or the calling thread's
        open span — the HTTP edge's) crosses the socket hop as a
        16-byte prefix on the PRED frame (_FLAG_TRACE), so the backend's
        dispatch + stage spans land under the same trace id."""
        t0 = time.monotonic()
        rows = (int(np.asarray(next(iter(features.values()))).shape[0])  # noqa: DRT002 — host row count of the incoming request payload
                if features else 0)
        sp = obs_trace.span("frontend_dispatch", "serving", ctx=trace_ctx)
        flags = _FLAG_GROUP_USERS if group_users else 0
        if no_cache:
            flags |= _FLAG_NO_CACHE
        prefix = b""
        if sp.ctx is not None:
            flags |= _FLAG_TRACE
            prefix = obs_trace.pack_wire(sp.ctx)
        body = bytes([flags]) + prefix + _pack_arrays(features)
        # Grouped requests route on the consistent-hash ring (stickiness
        # survives churn: ~1/N of users remap per join/leave); plain
        # requests round-robin.
        key = self._group_key(features) if group_users else None
        try:
            with sp:
                status, resp = self._call_any(OP_PRED, body, key=key,
                                              timeout=timeout)
        except Exception:
            self.stats.record_error()
            raise
        if status == _ERR:
            err = json.loads(resp)
            self.stats.record_error()
            if err.get("kind") == "bad_request":
                err.pop("kind", None)
                raise BadRequest(err.pop("error", "bad request"), **err)
            raise RuntimeError(err.get("error", "backend error"))
        out = _unpack_arrays(resp)
        version = int(out.pop("__version__"))  # noqa: DRT002 — version scalar decoded from the wire reply, already host-side
        if "predictions" in out:
            probs = out["predictions"]
        else:
            probs = {k[len("task:"):]: v for k, v in out.items()}
        self.stats.record_batch(1, rows)
        self.stats.record_stage("e2e", time.monotonic() - t0)
        return probs, version

    # ----------------------------------------------------------- retrieval

    def retrieve_versioned(self, features: Dict[str, np.ndarray], k: int,
                           timeout: Optional[float] = None,
                           no_cache: bool = False):
        """Full-corpus top-k across the fleet: fan one RETR frame to
        EVERY routable member in parallel (each owns a corpus shard) and
        lexsort-merge the per-shard answers at the edge (score desc, item
        id asc — deterministic regardless of shard count or answer
        order).

        Degraded-not-failed: a member that dies mid-query is marked down
        and its shard's candidates are simply missing from the merge —
        the reply is served from the surviving shards with
        ``partial=True``, counted in `retrieval_partials`, and visible in
        `health()` (the down member degrades the sweep). Only a fleet
        with ZERO answering members fails the request.

        DRAINING members stay in the fan-out: corpus shards are
        disjoint, so excluding a drainer would silently drop 1/N of the
        catalog for the whole drain window — drain means "no new STICKY
        assignments", and a stateless sweep of the shard it still holds
        is exactly the in-flight work the drain protocol finishes."""
        from deeprec_tpu.serving.retrieval import (
            RetrievalResult,
            merge_shard_topk,
        )

        t0 = time.monotonic()
        if not self._members and self.registry is not None:
            self.refresh_membership()
        members = list(self._members)
        if not members:
            raise RuntimeError("no fleet members admitted")
        # Honor failure backoff like every other routing path: a
        # blackholed member would stall the whole merge for a connect
        # timeout on EVERY request — skipping it yields the same
        # partial answer without the latency cliff. With everyone
        # backed off, try them all anyway (last resort beats failing).
        now = time.monotonic()
        routable = [m for m in members if m.available(now)] or members
        body = bytes([_FLAG_NO_CACHE if no_cache else 0]) + \
            struct.pack("<I", int(k)) + _pack_arrays(features)
        slots: List[Optional[Dict]] = [None] * len(routable)

        def sweep(i, m):
            try:
                status, resp = m.call(
                    OP_RETR, body,
                    timeout if timeout is not None else self.timeout)
            except (OSError, ConnectionError):
                m.mark_down()
                return
            if status != _OK:
                err = json.loads(resp)
                slots[i] = {"error": err}
                return
            m.mark_up()
            slots[i] = {"arrays": _unpack_arrays(resp)}

        if len(routable) == 1:
            sweep(0, routable[0])
        else:
            threads = [threading.Thread(target=sweep, args=(i, m),
                                        daemon=True)
                       for i, m in enumerate(routable)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        answers = [s["arrays"] for s in slots if s and "arrays" in s]
        errors = [s["error"] for s in slots if s and "error" in s]
        self._retr_requests += 1
        if not answers:
            self.stats.record_error()
            if errors and errors[0].get("kind") == "bad_request":
                e = dict(errors[0])
                e.pop("kind", None)
                raise BadRequest(e.pop("error", "bad request"), **e)
            raise RuntimeError(
                f"retrieval failed on all {len(routable)} members "
                f"({errors or 'unreachable'})")
        # partial is judged against the FULL member set: a member skipped
        # for backoff is exactly as missing from the merge as one that
        # failed mid-call — its shard's coverage is absent either way
        partial = len(answers) < len(members)
        if partial:
            self._retr_partials += 1
            if self._m_retr_partials is not None:
                self._m_retr_partials.inc()
        ids, scores = merge_shard_topk(
            [a["ids"] for a in answers],
            [a["scores"] for a in answers], int(k))
        version = max(int(a["__version__"]) for a in answers)  # noqa: DRT002 — version scalars decoded from wire replies, already host-side
        scanned = sum(int(a.get("scanned", 0)) for a in answers)  # noqa: DRT002 — wire reply ints, host-side
        self.stats.record_retrieval(1, scanned)
        self.stats.record_stage("retrieval", time.monotonic() - t0)
        return RetrievalResult(ids=ids, scores=scores, version=version,
                               partial=partial, scanned=scanned)

    def ingest_items(self, ids, features: Dict[str, np.ndarray],
                     timeout: Optional[float] = None) -> Dict[str, int]:
        """Broadcast one item batch to EVERY member (draining included —
        ingest is data plane, not load): each backend keeps the rows that
        hash to its corpus shard, so the broadcast partitions itself.
        Returns {addr: accepted} for the members that answered; a member
        that is down simply misses the batch (its shard serves stale
        coverage until re-ingest — the degraded contract)."""
        body = _pack_arrays({"__ids__": np.asarray(ids, np.int64),
                             **features})
        members = list(self._members)
        out: Dict[str, int] = {}
        lock = threading.Lock()

        def push(m):
            try:
                status, resp = m.call(
                    OP_RITM, body,
                    timeout if timeout is not None else self.timeout)
            except (OSError, ConnectionError):
                m.mark_down()
                return
            if status == _OK:
                with lock:
                    out[m.addr] = json.loads(resp).get("accepted", 0)

        if len(members) == 1:
            push(members[0])
        else:
            # parallel like the RETR fan-out: each member's upload +
            # chunked re-encode overlaps, so fleet ingest costs
            # max(member time), not the serial sum
            threads = [threading.Thread(target=push, args=(m,),
                                        daemon=True) for m in members]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        return out

    def warmup(self, example: Dict[str, np.ndarray],
               group_users: bool = False,
               ladder: Optional[Sequence[int]] = None) -> int:
        """Send warmup predicts to EVERY member — routing is bypassed on
        purpose: each backend must compile its own batch buckets before
        live traffic, or the first production burst pays a per-process
        compile storm (and a scale-out bench measures compilation as
        backend load). `ladder` warms one batch per row count (built by
        repeating the example's first row — matching what the backend's
        bucket padding produces); default is the example as-is."""
        n = 0
        flags = _FLAG_GROUP_USERS if group_users else 0
        one = {k: np.asarray(v)[:1] for k, v in example.items()}  # noqa: DRT002 — warmup path: host example batch, no device value crosses here
        batches = ([example] if not ladder else
                   [{k: np.repeat(v, size, axis=0) for k, v in one.items()}
                    for size in ladder])
        for m in list(self._members):
            ok = True
            for batch in batches:
                body = bytes([flags]) + _pack_arrays(batch)
                try:
                    status, _ = m.call(OP_PRED, body, self.timeout)
                except (OSError, ConnectionError):
                    m.mark_down()
                    ok = False
                    break
                ok = ok and status == _OK
            if ok:
                m.mark_up()
                n += 1
        return n

    # ------------------------------------------------------ health & stats

    # Health probes run with a SHORT timeout and in parallel across
    # members: /healthz is a watchdog surface — one network-partitioned
    # backend must cost the sweep ~1 s total, not connect_timeout × N
    # serial (a liveness prober timing out on /healthz would restart a
    # frontend whose request routing is perfectly healthy).
    HEALTH_PROBE_SECS = 1.0

    def _probe_member(self, m: _Member) -> Dict:
        try:
            status, body = m.call(OP_HLTH, b"", self.HEALTH_PROBE_SECS)
            h = (json.loads(body) if status == _OK
                 else obs_schema.health_payload(
                     "degraded", error=body.decode("utf-8", "replace")))
            m.mark_up(h)
        except (OSError, ConnectionError) as e:
            m.mark_down()
            # synthetic entry for a dead process — same unified schema
            # (obs/schema.py) as a live member's own health payload
            h = obs_schema.health_payload(
                "down", staleness_seconds=float("inf"),
                member=m.addr, error=str(e))
        h["member"] = m.addr
        return h

    def _health_sweep(self) -> Dict:
        """Live HLTH probe of every member (parallel, bounded); returns
        the merged /healthz body: the WORST member's health dict (the
        `_GroupPredictor` selection, spanning processes) + frontend
        availability counters. Down members contribute a synthetic
        degraded entry. In registry mode the sweep reconciles
        membership first, so /healthz always describes the CURRENT
        fleet, never a retired one."""
        if self.registry is not None:
            self.refresh_membership()
        members = list(self._members)
        if not members:
            out = obs_schema.health_payload(
                "down", error="no fleet members admitted")
            out["members"] = 0
            out["reachable"] = 0
            out["draining"] = 0
            return out
        if len(members) == 1:
            healths = [self._probe_member(members[0])]
        else:
            slots: List[Optional[Dict]] = [None] * len(members)

            def probe(i, m):
                slots[i] = self._probe_member(m)

            threads = [threading.Thread(target=probe, args=(i, m),
                                        daemon=True)
                       for i, m in enumerate(members)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            healths = [h for h in slots if h is not None]
        reachable = sum(1 for h in healths if h["status"] != "down")
        worst = healths[0]
        for h in healths:
            if h["status"] != "ok" and worst["status"] == "ok":
                worst = h
            elif (h["status"] != "ok") == (worst["status"] != "ok") and (
                (h.get("staleness_seconds") or 0) > (
                    worst.get("staleness_seconds") or 0)):
                worst = h
        out = dict(worst)
        if out.get("staleness_seconds") == float("inf"):
            out["staleness_seconds"] = None
        out["members"] = len(members)
        out["reachable"] = reachable
        out["draining"] = sum(1 for m in members if m.draining)
        # Quality-firewall rollup: gate rejections SUM across the fleet
        # (the worst-member dict above already carries that member's own
        # degraded_reason when its gate is holding freshness back).
        qg = [h.get("quality_gate_rejections") for h in healths]
        if any(v is not None for v in qg):
            out["quality_gate_rejections"] = sum(int(v or 0) for v in qg)  # noqa: DRT002 — summing JSON ints from member health bodies, host-side
        if reachable < len(members):
            out["status"] = "degraded" if reachable else "down"
        if self._retr_requests:
            # Retrieval coverage view: a dead member already degrades the
            # status above; the partial counter says how many sweeps
            # actually served with shards missing (degraded-not-failed).
            out["retrieval_requests"] = self._retr_requests
            out["retrieval_partials"] = self._retr_partials
        # Empty-shard detection: a retrieval backend that restarted lost
        # its in-process corpus and answers sweeps with nothing — which
        # no per-request signal catches (it IS a successful answer). One
        # shard at 0 rows while a sibling holds items = silently missing
        # catalog coverage, surfaced here as degraded.
        shard_rows = [h.get("retrieval_corpus_rows") for h in healths
                      if h["status"] != "down"
                      and h.get("retrieval_corpus_rows") is not None]
        if shard_rows and max(shard_rows) > 0 and min(shard_rows) == 0:
            out["retrieval_empty_shards"] = sum(
                1 for r in shard_rows if r == 0)
            if out["status"] == "ok":
                out["status"] = "degraded"
                out["degraded_reason"] = "retrieval_shard_empty"
        return out

    def stats_snapshot(self) -> Dict:
        """Merged `/v1/stats` spanning the tier: the frontend's own edge
        accounting (client-visible e2e, routed requests, retries) plus
        every reachable member's full per-process snapshot and summed
        totals — one surface shows the whole tier's load balance."""
        out = self.stats.snapshot()
        members = []
        totals = {"requests": 0, "batches": 0, "rows": 0, "errors": 0}
        model = {}
        queue_depth = 0
        backend_p99 = None
        for m in list(self._members):
            entry = m.snapshot()
            if m.available(time.monotonic()):
                try:
                    status, body = m.call(OP_STAT, b"",
                                          min(self.timeout, 5.0))
                    if status == _OK:
                        snap = json.loads(body)
                        entry["stats"] = snap
                        for k in totals:
                            totals[k] += snap.get(k, 0)
                        win = snap.get("window") or {}
                        queue_depth += int(win.get("queue_depth") or 0)
                        p99 = win.get("e2e_p99_ms")
                        if p99 is not None:
                            backend_p99 = (p99 if backend_p99 is None
                                           else max(backend_p99, p99))
                        mv = snap.get("model", {})
                        if not model or mv.get("version", -1) > model.get(
                                "version", -1):
                            model = mv
                except (OSError, ConnectionError):
                    m.mark_down()
            members.append(entry)
        out["frontend"] = {"routed": out.pop("requests"),
                           "errors": out["errors"],
                           "retrieval_requests": self._retr_requests,
                           "retrieval_partials": self._retr_partials}
        out["members"] = members
        out["backend_totals"] = totals
        out["model"] = model
        # The autoscaler's observation (fleet.load_from_stats): windowed
        # edge-visible e2e p99 (the frontend's own obs ring buffers; the
        # worst member's window when the edge plane is off) + queue depth
        # summed over members — PR 11's window_summary machinery, not
        # lifetime aggregates, so a past spike that scrolled out of the
        # window never triggers a scale event.
        edge_p99 = self.stats.window_p99_ms("e2e")
        out["fleet_load"] = {
            "e2e_p99_ms": edge_p99 if edge_p99 is not None else backend_p99,
            "backend_p99_ms": backend_p99,
            "queue_depth": queue_depth,
            "members": len(members),
            "draining": sum(1 for e in members if e.get("draining")),
            "window_seconds": 60,
        }
        out["health"] = self._health_sweep()
        return out

    # ------------------------------------------------------------- metrics

    # Scrape budget per member: /metrics is a watchdog-adjacent surface —
    # one wedged backend must cost the scrape ~2 s, not timeout × N, and
    # members are probed in PARALLEL (the _health_sweep discipline).
    METRICS_PROBE_SECS = 2.0

    def _member_metrics(self, m: _Member) -> Tuple[Optional[Dict], bool]:
        """(snapshot, stale): a live member answers METR and refreshes
        its cache; a down (or just-failed) member serves its LAST known
        snapshot with stale=True — a killed backend's series must stay
        visible in the merge, marked, never silently vanish. A failed
        scrape deliberately does NOT mark the member down: observability
        traffic must never mutate request-routing state (an external
        scraper's cadence would otherwise drive serving availability)."""
        if m.available(time.monotonic()):
            try:
                status, body = m.call(OP_METR, b"",
                                      min(self.timeout,
                                          self.METRICS_PROBE_SECS))
                if status == _OK:
                    snap = json.loads(body)
                    with m._lock:
                        m.last_metrics = snap
                    return snap, False
            except (OSError, ConnectionError):
                pass
        with m._lock:
            return m.last_metrics, True

    def metrics_text(self) -> str:
        """The tier's `GET /metrics`: the frontend's own edge series +
        the process-wide plane + every member's snapshot relabeled with
        member="host:port" (down members stale="1"), plus a
        deeprec_member_up gauge per member — one scrape shows the whole
        tier's load balance and who is missing from it. Duplicate
        family headers across the per-member blocks are collapsed
        (concat_prometheus) so real Prometheus parsers accept the body."""
        parts = []
        if self.stats.registry is not None:
            parts.append(obs_metrics.render_snapshot(
                self.stats.registry.snapshot(),
                extra_labels={"tier": "frontend"}))
        if obs_metrics.metrics_enabled():
            parts.append(
                obs_metrics.default_registry().render_prometheus())
        mlist = list(self._members)
        slots: List[Optional[Tuple[Optional[Dict], bool]]] = \
            [None] * len(mlist)
        if len(mlist) == 1:
            slots[0] = self._member_metrics(mlist[0])
        else:
            def probe(i, m):
                slots[i] = self._member_metrics(m)

            threads = [threading.Thread(target=probe, args=(i, m),
                                        daemon=True)
                       for i, m in enumerate(mlist)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        up_lines = ["# TYPE deeprec_member_up gauge"]
        for m, got in zip(mlist, slots):
            snap, stale = got if got is not None else (None, True)
            up_lines.append(
                'deeprec_member_up{member="%s"} %d'
                % (m.addr, 0 if stale else 1))
            if snap:
                parts.append(obs_metrics.render_snapshot(
                    snap, extra_labels={"member": m.addr}, stale=stale))
        parts.append("\n".join(up_lines) + "\n")
        return obs_metrics.concat_prometheus(parts)

    def close(self) -> None:
        self._stop.set()
        for t in (self._poller, self._membership_thread, self._reprober):
            if t is not None:
                t.join(timeout=2)
        for m in list(self._members):
            m.close()


# ------------------------------------------------------- process management


def backend_argv(
    *, ckpt: str, model: str = "wdl", model_json: Optional[str] = None,
    quantize: Optional[str] = None, poll_secs: float = 0.0,
    max_batch: int = 256, max_wait_ms: float = 1.0,
    registry: Optional[str] = None, lease_secs: Optional[float] = None,
    capacity: int = 1, member_name: str = "", port: int = 0,
    retrieval_shard: Optional[str] = None,
    retrieval_quantize: str = "int8",
    reuse_mb: float = 0.0,
) -> List[str]:
    """The backend CLI argv for one serving process — shared by
    `spawn_backends`, the Supervisor-driven fleet specs (a respawn with
    ``port=0`` binds a FRESH port and announces it by lease, which is
    how a rolling restart re-admits the new generation), and the
    autoscaler's scale_up."""
    import sys

    argv = [
        sys.executable, "-m", "deeprec_tpu.serving.frontend",
        "--backend", "--ckpt", ckpt, "--model", model, "--port", str(port),
        "--max_batch", str(max_batch), "--max_wait_ms", str(max_wait_ms),
        "--poll_secs", str(poll_secs),
    ]
    if model_json:
        argv += ["--model-json", model_json]
    if quantize:
        argv += ["--quantize", quantize]
    if retrieval_shard:
        argv += ["--retrieval", "--retrieval-shard", retrieval_shard,
                 "--retrieval-quantize", retrieval_quantize]
    if reuse_mb:
        argv += ["--reuse-mb", str(reuse_mb)]
    if registry:
        argv += ["--registry", registry]
        if lease_secs is not None:
            argv += ["--lease-secs", str(lease_secs)]
        if capacity != 1:
            argv += ["--capacity", str(capacity)]
        if member_name:
            argv += ["--member-name", member_name]
    return argv


def _wait_ready(procs, marker: str, ready_timeout: float):
    """Collect `marker` ports from each child's stdout (select-bounded:
    a wedged child that prints NOTHING must fail after ready_timeout,
    not block readline() forever). Kills the whole set on any miss."""
    import os
    import select

    ports = []
    deadline = time.monotonic() + ready_timeout
    for p in procs:
        port = None
        buf = ""
        while time.monotonic() < deadline:
            ready, _, _ = select.select(
                [p.stdout], [], [],
                max(0.1, min(1.0, deadline - time.monotonic())))
            if not ready:
                if p.poll() is not None:
                    break  # child died without a READY line
                continue
            chunk = os.read(p.stdout.fileno(), 4096).decode(
                "utf-8", "replace")
            if not chunk:
                break  # EOF
            buf += chunk
            # Only COMPLETE lines parse: a READY line split across two
            # pipe reads must not yield a truncated port number (or an
            # IndexError before "port=" arrives) — the partial tail
            # stays in buf until its newline lands.
            for line in buf.split("\n")[:-1]:
                if line.startswith(marker) and "port=" in line:
                    port = int(line.split("port=")[1].split()[0].strip())
                    break
            if port is not None:
                break
        if port is None:
            for q in procs:
                q.kill()
            raise RuntimeError(
                f"worker pid {p.pid} never reported {marker} "
                f"(rc={p.poll()}, output tail: {buf[-500:]!r})")
        ports.append(port)
    return ports


def spawn_backends(
    n: int, *, ckpt: str, model: str = "wdl", model_json: Optional[str] = None,
    quantize: Optional[str] = None, poll_secs: float = 0.0,
    max_batch: int = 256, max_wait_ms: float = 1.0,
    registry: Optional[str] = None, lease_secs: Optional[float] = None,
    capacity: int = 1, member_name: str = "",
    env: Optional[Dict[str, str]] = None, ready_timeout: float = 180.0,
    retrieval: bool = False, retrieval_quantize: str = "int8",
    reuse_mb: float = 0.0,
):
    """Launch `n` backend serving processes on this host and wait for
    their READY lines. Returns (procs, addrs) — pass `addrs` to
    `Frontend`, or pass `registry` and let the frontend discover them by
    lease instead. Used by tools/bench_serving.py, tools/bench_fleet.py
    and the fault-matrix tests; production deployments run the same CLI
    under their own process supervisor (docs/serving.md).
    `retrieval=True` additionally enables the full-corpus retrieval lane
    with backend i owning corpus shard i of n."""
    import os
    import subprocess

    procs = []
    for i in range(n):
        argv = backend_argv(
            ckpt=ckpt, model=model, model_json=model_json,
            quantize=quantize, poll_secs=poll_secs, max_batch=max_batch,
            max_wait_ms=max_wait_ms, registry=registry,
            lease_secs=lease_secs, capacity=capacity,
            member_name=(f"{member_name}-{i}" if member_name else ""),
            retrieval_shard=(f"{i}/{n}" if retrieval else None),
            retrieval_quantize=retrieval_quantize, reuse_mb=reuse_mb)
        p = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env={**os.environ, **(env or {})},
        )
        procs.append(p)
    ports = _wait_ready(procs, "DEEPREC_BACKEND_READY", ready_timeout)
    return procs, [("127.0.0.1", port) for port in ports]


def spawn_frontends(
    n: int, *, registry: str, model: str = "wdl",
    model_json: Optional[str] = None, lease_secs: Optional[float] = None,
    health_secs: float = 2.0, env: Optional[Dict[str, str]] = None,
    ready_timeout: float = 180.0,
):
    """Launch `n` replicated frontend edge processes sharing one lease
    registry (each discovers backends independently — no single edge).
    Returns (procs, addrs) with addrs the HTTP endpoints; hand them (or
    the registry) to a `fleet.FleetClient`."""
    import os
    import subprocess
    import sys

    procs = []
    for i in range(n):
        argv = [
            sys.executable, "-m", "deeprec_tpu.serving.frontend",
            "--frontend", "--registry", registry, "--model", model,
            "--http-port", "0", "--health_secs", str(health_secs),
            "--member-name", f"edge-{i}",
        ]
        if model_json:
            argv += ["--model-json", model_json]
        if lease_secs is not None:
            argv += ["--lease-secs", str(lease_secs)]
        p = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True, env={**os.environ, **(env or {})},
        )
        procs.append(p)
    ports = _wait_ready(procs, "DEEPREC_FRONTEND_READY", ready_timeout)
    return procs, [f"127.0.0.1:{port}" for port in ports]


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--backend", action="store_true",
                      help="run one backend serving process")
    mode.add_argument("--frontend", action="store_true",
                      help="run the routing tier + HTTP server")
    p.add_argument("--ckpt", help="checkpoint directory (backend mode)")
    p.add_argument("--model", default="wdl")
    p.add_argument("--model-json", default=None,
                   help="JSON kwargs for the model constructor")
    p.add_argument("--quantize", default=None,
                   choices=["fp32", "bf16", "int8"],
                   help="serving-side row residency (train fp32, serve "
                        "quantized)")
    p.add_argument("--port", type=int, default=0)
    p.add_argument("--host", default="127.0.0.1")
    p.add_argument("--max_batch", type=int, default=256)
    p.add_argument("--max_wait_ms", type=float, default=1.0)
    p.add_argument("--poll_secs", type=float, default=10.0,
                   help="backend delta-chain poll cadence (0 = off)")
    p.add_argument("--backends", default="",
                   help="frontend mode: comma-separated host:port list")
    p.add_argument("--http-port", type=int, default=8500)
    p.add_argument("--health_secs", type=float, default=2.0)
    p.add_argument("--registry", default=None,
                   help="fleet lease-registry directory (serving/fleet.py):"
                        " backends announce themselves by lease, frontends"
                        " discover/admit/retire members at runtime")
    p.add_argument("--lease-secs", type=float, default=10.0,
                   help="lease staleness bound (stale = evicted)")
    p.add_argument("--capacity", type=int, default=1,
                   help="advertised serving capacity (lease field)")
    p.add_argument("--member-name", default="",
                   help="supervisor spec name stamped into the lease (the"
                        " autoscaler's retire handle)")
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--retrieval", action="store_true",
                   help="backend mode: enable the full-corpus retrieval "
                        "lane (two-tower models only; this backend owns "
                        "the corpus shard of --retrieval-shard)")
    p.add_argument("--retrieval-quantize", default="int8",
                   choices=["fp32", "bf16", "int8"],
                   help="corpus matrix residency (serving/retrieval.py)")
    p.add_argument("--retrieval-block", type=int, default=4096,
                   help="pow2 rows per corpus sweep block")
    p.add_argument("--retrieval-chunk", type=int, default=1024,
                   help="fixed encode-chunk rows (one static XLA shape)")
    p.add_argument("--retrieval-shard", default="0/1",
                   help="'i/n': this backend owns corpus shard i of n "
                        "(items hash-partition across the fleet)")
    p.add_argument("--reuse-mb", type=float, default=0.0,
                   help="backend mode: compute-reuse cache budget in MiB "
                        "(serving/reuse.py; 0 = caches off). Sizes the "
                        "predict answer cache, the user-tower cache and "
                        "the retrieval candidate cache alike")
    args = p.parse_args(argv)

    kwargs = json.loads(args.model_json) if args.model_json else {}
    from deeprec_tpu.models.registry import build_model

    model = build_model(args.model, **kwargs)

    registry = None
    if args.registry:
        from deeprec_tpu.serving import fleet as _fleet

        registry = _fleet.FleetRegistry(args.registry,
                                        lease_secs=args.lease_secs)

    if args.backend:
        if not args.ckpt:
            p.error("--ckpt is required in --backend mode")
        import signal as _signal
        import sys as _sys

        from deeprec_tpu.online import faults as _faults
        from deeprec_tpu.serving.predictor import ModelServer, Predictor

        pred = Predictor(model, args.ckpt, quantize=args.quantize)
        reuse_bytes = int(args.reuse_mb * (1 << 20))
        server = ModelServer(pred, max_batch=args.max_batch,
                             max_wait_ms=args.max_wait_ms,
                             poll_updates_secs=args.poll_secs,
                             reuse_cache_bytes=reuse_bytes)
        if args.retrieval:
            from deeprec_tpu.serving.retrieval import RetrievalEngine

            si, sn = args.retrieval_shard.split("/")
            engine = RetrievalEngine(
                pred, quantize=args.retrieval_quantize,
                block_rows=args.retrieval_block,
                chunk=args.retrieval_chunk,
                shard_index=int(si), num_shards=int(sn))  # noqa: DRT002 — parsing a shard-spec config string, not a device value
            server.attach_retrieval(engine,
                                    reuse_cache_bytes=reuse_bytes)
        backend = BackendServer(
            server, host=args.host, port=args.port, registry=registry,
            capacity=args.capacity, member_name=args.member_name,
            lease_delay_secs=_faults.env_slow_join_secs()).start()
        print(f"DEEPREC_BACKEND_READY port={backend.port}", flush=True)
        if backend.stamper is not None:
            # Fleet member: wait for a drain (drain-request file via the
            # lease loop, or SIGTERM — the k8s preStop shape), finish
            # in-flight work, exit with the EXIT_RESCALE choreography's
            # code so a supervisor respawns rolling restarts for free.
            _signal.signal(
                _signal.SIGTERM,
                lambda sig, frm: backend.stamper.begin_drain(respawn=True))
            try:
                backend.stamper.draining.wait()
            except KeyboardInterrupt:
                backend.stop()
                return
            rc = backend.drain(timeout=args.drain_timeout)
            print(f"DEEPREC_BACKEND_DRAINED rc={rc}", flush=True)
            _sys.exit(rc)
        try:
            threading.Event().wait()
        except KeyboardInterrupt:
            backend.stop()
        return

    import sys as _sys

    from deeprec_tpu.serving.http_server import HttpServer

    addrs = [a for a in args.backends.split(",") if a]
    if not addrs and registry is None:
        p.error("--frontend needs --backends host:port[,...] and/or "
                "--registry DIR")
    fe = Frontend(addrs or None, model, registry=registry,
                  health_secs=args.health_secs)
    http = HttpServer(fe, port=args.http_port, host=args.host).start()
    stamper = None
    if registry is not None:
        from deeprec_tpu.serving import fleet as _fleet

        # The edge announces itself too (role="frontend"): replicated
        # frontends are discovered by FleetClient the same way backends
        # are discovered by frontends — no single edge process.
        stamper = _fleet.LeaseStamper(
            registry, f"{args.host}:{http.port}",
            role=_fleet.ROLE_FRONTEND, name=args.member_name).start()
    print(f"DEEPREC_FRONTEND_READY port={http.port} backends={addrs}",
          flush=True)
    try:
        if stamper is not None:
            stamper.draining.wait()
        else:
            threading.Event().wait()
    except KeyboardInterrupt:
        pass
    http.stop()
    fe.close()
    if stamper is not None:
        rc = stamper.exit_code()
        stamper.stop(unregister=True)
        print(f"DEEPREC_FRONTEND_DRAINED rc={rc}", flush=True)
        _sys.exit(rc)


if __name__ == "__main__":
    main()
