"""Pure-python protobuf wire codec for the reference serving protocol.

The reference's processor speaks protobuf on its C ABI: hosts serialize
``tensorflow.eas.PredictRequest`` and parse ``PredictResponse``
(/root/reference/serving/processor/serving/predict.proto, parsed in
message_coding.cc ParseRequestFromBuf/ParseResponseToBuf). For a host
built against that contract to call our ``libdeeprec_processor.so``, the
bytes on the wire must be the same — so this module implements the
proto3 wire format for exactly those messages, by hand, with no protobuf
runtime dependency (the image has none we may rely on, and the schema is
four small messages).

Wire-format notes (proto3):
- varint fields: int32/int64/enum/bool. Negative int32/int64 are encoded
  as 10-byte sign-extended varints.
- packed repeated scalars: length-delimited blob of the scalar encoding.
  Parsers must ALSO accept the unpacked form (one tagged entry per
  element) — protobuf's compatibility rule — and we do.
- map<string, ArrayProto>: repeated embedded message with field 1 = key
  (string), field 2 = value (message).
- Unknown fields are skipped by wire type, like any conforming parser.

Numpy mapping: DT_FLOAT/f4 via float_val, DT_DOUBLE/f8 via double_val,
DT_INT64/i8 via int64_val, DT_INT32 (and the narrow ints, which protobuf
carries as int32) via int_val, DT_BOOL via bool_val, DT_STRING via
string_val (object arrays of bytes).
"""
from __future__ import annotations

import struct
from typing import Dict, Iterator, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------- dtypes

DT_INVALID = 0
DT_FLOAT = 1
DT_DOUBLE = 2
DT_INT32 = 3
DT_UINT8 = 4
DT_INT16 = 5
DT_INT8 = 6
DT_STRING = 7
DT_INT64 = 9
DT_BOOL = 10

_NP_TO_DT = {
    np.dtype(np.float32): DT_FLOAT,
    np.dtype(np.float64): DT_DOUBLE,
    np.dtype(np.int32): DT_INT32,
    np.dtype(np.uint8): DT_UINT8,
    np.dtype(np.int16): DT_INT16,
    np.dtype(np.int8): DT_INT8,
    np.dtype(np.int64): DT_INT64,
    np.dtype(np.bool_): DT_BOOL,
}
_DT_TO_NP = {
    DT_FLOAT: np.float32,
    DT_DOUBLE: np.float64,
    DT_INT32: np.int32,
    DT_UINT8: np.uint8,
    DT_INT16: np.int16,
    DT_INT8: np.int8,
    DT_INT64: np.int64,
    DT_BOOL: np.bool_,
}

# ---------------------------------------------------------- wire helpers

_WT_VARINT = 0
_WT_I64 = 1
_WT_LEN = 2
_WT_I32 = 5


def _enc_varint(v: int) -> bytes:
    if v < 0:  # sign-extend to 64 bits, like protobuf int32/int64
        v += 1 << 64
    out = bytearray()
    while True:
        b = v & 0x7F
        v >>= 7
        if v:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _dec_varint(buf: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(buf):
            raise ValueError("truncated varint")
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result & ((1 << 64) - 1), pos
        shift += 7
        if shift >= 70:
            raise ValueError("varint too long")


def _to_signed64(v: int) -> int:
    return v - (1 << 64) if v >= 1 << 63 else v


def _to_signed32(v: int) -> int:
    v &= (1 << 32) - 1
    return v - (1 << 32) if v >= 1 << 31 else v


def _tag(field: int, wt: int) -> bytes:
    return _enc_varint((field << 3) | wt)


def _len_field(field: int, payload: bytes) -> bytes:
    return _tag(field, _WT_LEN) + _enc_varint(len(payload)) + payload


def _skip(buf: bytes, pos: int, wt: int) -> int:
    if wt == _WT_VARINT:
        _, pos = _dec_varint(buf, pos)
    elif wt == _WT_I64:
        pos += 8
    elif wt == _WT_LEN:
        n, pos = _dec_varint(buf, pos)
        pos += n
    elif wt == _WT_I32:
        pos += 4
    else:
        raise ValueError(f"unsupported wire type {wt}")
    if pos > len(buf):
        raise ValueError("truncated field")
    return pos


def _fields(buf: bytes) -> Iterator[Tuple[int, int, int, int]]:
    """Yield (field_number, wire_type, value_start, value_end_or_varint).

    For LEN fields the slice [start:end] is the payload; for varints the
    third element is the decoded value and end is the next position.
    """
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _dec_varint(buf, pos)
        field, wt = key >> 3, key & 7
        if wt == _WT_VARINT:
            val, pos = _dec_varint(buf, pos)
            yield field, wt, val, pos
        elif wt == _WT_LEN:
            ln, pos = _dec_varint(buf, pos)
            if pos + ln > n:
                raise ValueError("truncated length-delimited field")
            yield field, wt, pos, pos + ln
            pos += ln
        else:
            end = _skip(buf, pos, wt)
            yield field, wt, pos, end
            pos = end


def _packed_varints(payload: bytes) -> List[int]:
    out = []
    pos = 0
    while pos < len(payload):
        v, pos = _dec_varint(payload, pos)
        out.append(v)
    return out


# ------------------------------------------------------------ ArrayProto


class ArrayProto:
    """tensorflow.eas.ArrayProto (predict.proto:42-67)."""

    __slots__ = ("dtype", "shape", "values", "string_val")

    def __init__(self, dtype: int = DT_INVALID, shape: Optional[List[int]] = None,
                 values: Optional[np.ndarray] = None,
                 string_val: Optional[List[bytes]] = None):
        self.dtype = dtype
        self.shape = list(shape) if shape is not None else []
        self.values = values
        self.string_val = string_val or []

    # -- numpy bridge

    @classmethod
    def from_numpy(cls, arr: np.ndarray) -> "ArrayProto":
        arr = np.asarray(arr)
        if arr.dtype.kind in ("U", "S", "O"):
            flat = [
                s.encode() if isinstance(s, str) else bytes(s)
                for s in arr.reshape(-1)
            ]
            return cls(DT_STRING, list(arr.shape), string_val=flat)
        dt = _NP_TO_DT.get(arr.dtype)
        if dt is None:  # best-effort upcast (e.g. float16 -> float32)
            if arr.dtype.kind == "f":
                arr, dt = arr.astype(np.float32), DT_FLOAT
            elif arr.dtype.kind in "iu":
                arr, dt = arr.astype(np.int64), DT_INT64
            else:
                raise ValueError(f"unsupported dtype {arr.dtype}")
        return cls(dt, list(arr.shape), values=arr.reshape(-1))

    def to_numpy(self) -> np.ndarray:
        shape = self.shape or None
        if self.dtype == DT_STRING:
            arr = np.asarray(self.string_val, dtype=object)
        elif self.values is not None:
            arr = np.asarray(self.values, dtype=_DT_TO_NP[self.dtype])
        else:
            arr = np.zeros(0, dtype=_DT_TO_NP.get(self.dtype, np.float32))
        if shape:
            arr = arr.reshape(shape)
        return arr

    # -- wire

    def serialize(self) -> bytes:
        out = bytearray()
        if self.dtype:
            out += _tag(1, _WT_VARINT) + _enc_varint(self.dtype)
        if self.shape:
            dims = b"".join(_enc_varint(d) for d in self.shape)
            out += _len_field(2, _len_field(1, dims))
        v = self.values
        if v is not None and len(v):
            v = np.asarray(v)
            if self.dtype == DT_FLOAT:
                out += _len_field(
                    3, struct.pack(f"<{len(v)}f", *v.astype(np.float32)))
            elif self.dtype == DT_DOUBLE:
                out += _len_field(
                    4, struct.pack(f"<{len(v)}d", *v.astype(np.float64)))
            elif self.dtype in (DT_INT32, DT_UINT8, DT_INT16, DT_INT8):
                out += _len_field(
                    5, b"".join(_enc_varint(int(x)) for x in v))
            elif self.dtype == DT_INT64:
                out += _len_field(
                    7, b"".join(_enc_varint(int(x)) for x in v))
            elif self.dtype == DT_BOOL:
                out += _len_field(8, bytes(int(bool(x)) for x in v))
        for s in self.string_val:
            out += _len_field(6, s)
        return bytes(out)

    @classmethod
    def parse(cls, buf: bytes) -> "ArrayProto":
        self = cls()
        ints: List[int] = []
        floats: List[float] = []
        which = None  # field number the scalar payload came from
        for field, wt, a, b in _fields(buf):
            if field == 1 and wt == _WT_VARINT:
                self.dtype = a
            elif field == 2 and wt == _WT_LEN:
                for f2, wt2, a2, b2 in _fields(buf[a:b]):
                    if f2 == 1 and wt2 == _WT_LEN:
                        self.shape.extend(
                            _to_signed64(x)
                            for x in _packed_varints(buf[a:b][a2:b2]))
                    elif f2 == 1 and wt2 == _WT_VARINT:
                        self.shape.append(_to_signed64(a2))
            elif field == 3:  # float_val
                which = 3
                if wt == _WT_LEN:
                    floats.extend(
                        struct.unpack(f"<{(b - a) // 4}f", buf[a:b]))
                elif wt == _WT_I32:
                    floats.append(struct.unpack("<f", buf[a:b])[0])
            elif field == 4:  # double_val
                which = 4
                if wt == _WT_LEN:
                    floats.extend(
                        struct.unpack(f"<{(b - a) // 8}d", buf[a:b]))
                elif wt == _WT_I64:
                    floats.append(struct.unpack("<d", buf[a:b])[0])
            elif field in (5, 7, 8):  # int_val / int64_val / bool_val
                which = field
                if wt == _WT_LEN:
                    ints.extend(_packed_varints(buf[a:b]))
                elif wt == _WT_VARINT:
                    ints.append(a)
            elif field == 6 and wt == _WT_LEN:
                self.string_val.append(buf[a:b])
        if which in (3, 4):
            self.values = np.asarray(
                floats, np.float32 if which == 3 else np.float64)
        elif which == 5:
            self.values = np.asarray([_to_signed32(x) for x in ints],
                                     np.int64)
        elif which == 7:
            self.values = np.asarray([_to_signed64(x) for x in ints],
                                     np.int64)
        elif which == 8:
            self.values = np.asarray([bool(x) for x in ints])
        return self


# ------------------------------------------------- request/response msgs


def _map_entry(key: str, value: bytes) -> bytes:
    body = _len_field(1, key.encode()) + _len_field(2, value)
    return body


class PredictRequest:
    """tensorflow.eas.PredictRequest (predict.proto:72-93)."""

    __slots__ = ("signature_name", "inputs", "output_filter")

    def __init__(self, signature_name: str = "",
                 inputs: Optional[Dict[str, ArrayProto]] = None,
                 output_filter: Optional[List[str]] = None):
        self.signature_name = signature_name
        self.inputs: Dict[str, ArrayProto] = inputs or {}
        self.output_filter: List[str] = output_filter or []

    def serialize(self) -> bytes:
        out = bytearray()
        if self.signature_name:
            out += _len_field(1, self.signature_name.encode())
        for k, v in self.inputs.items():
            out += _len_field(2, _map_entry(k, v.serialize()))
        for f in self.output_filter:
            out += _len_field(3, f.encode())
        return bytes(out)

    @classmethod
    def parse(cls, buf: bytes) -> "PredictRequest":
        self = cls()
        for field, wt, a, b in _fields(buf):
            if field == 1 and wt == _WT_LEN:
                self.signature_name = buf[a:b].decode("utf-8", "replace")
            elif field == 2 and wt == _WT_LEN:
                key, val = "", b""
                for f2, wt2, a2, b2 in _fields(buf[a:b]):
                    if f2 == 1 and wt2 == _WT_LEN:
                        key = buf[a:b][a2:b2].decode("utf-8", "replace")
                    elif f2 == 2 and wt2 == _WT_LEN:
                        val = buf[a:b][a2:b2]
                self.inputs[key] = ArrayProto.parse(val)
            elif field == 3 and wt == _WT_LEN:
                self.output_filter.append(buf[a:b].decode("utf-8", "replace"))
        return self


class PredictResponse:
    """tensorflow.eas.PredictResponse (predict.proto:96-99)."""

    __slots__ = ("outputs",)

    def __init__(self, outputs: Optional[Dict[str, ArrayProto]] = None):
        self.outputs: Dict[str, ArrayProto] = outputs or {}

    def serialize(self) -> bytes:
        out = bytearray()
        for k, v in self.outputs.items():
            out += _len_field(1, _map_entry(k, v.serialize()))
        return bytes(out)

    @classmethod
    def parse(cls, buf: bytes) -> "PredictResponse":
        self = cls()
        for field, wt, a, b in _fields(buf):
            if field == 1 and wt == _WT_LEN:
                key, val = "", b""
                for f2, wt2, a2, b2 in _fields(buf[a:b]):
                    if f2 == 1 and wt2 == _WT_LEN:
                        key = buf[a:b][a2:b2].decode("utf-8", "replace")
                    elif f2 == 2 and wt2 == _WT_LEN:
                        val = buf[a:b][a2:b2]
                self.outputs[key] = ArrayProto.parse(val)
        return self


class ServingModelInfo:
    """tensorflow.eas.ServingModelInfo (predict.proto:102-105)."""

    __slots__ = ("model_path",)

    def __init__(self, model_path: str = ""):
        self.model_path = model_path

    def serialize(self) -> bytes:
        if not self.model_path:
            return b""
        return _len_field(1, self.model_path.encode())

    @classmethod
    def parse(cls, buf: bytes) -> "ServingModelInfo":
        self = cls()
        for field, wt, a, b in _fields(buf):
            if field == 1 and wt == _WT_LEN:
                self.model_path = buf[a:b].decode("utf-8", "replace")
        return self
