"""HTTP serving frontend over the ModelServer.

The network-facing surface of the serving stack — the role of the
reference's processor C ABI + gRPC glue (serving/processor/serving/
processor.h: initialize/process) re-cut as a dependency-free JSON/HTTP
server (stdlib http.server; a threading server whose request threads block
on the ModelServer's coalescing queue, so concurrent requests batch into
full device batches automatically).

Protocol:
  POST /v1/predict   {"features": {"C1": [..ids..], "I1": [[..]], ...}}
                  -> {"predictions": [...]} (or {"task": [...]} for MTL)
  GET  /v1/model_info -> {"step": N, "table_sizes": {...}}
  POST /v1/reload    -> {"updated": bool}   (poll full/delta updates now)
  GET  /healthz      -> 200 "ok"

Run: python -m deeprec_tpu.serving.http_server --model wdl --ckpt DIR
or embed: ``HttpServer(server, port=8500).start()``.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeprec_tpu.serving.predictor import (
    BadRequest,
    ModelServer,
    Predictor,
    parse_features,
)


class _Handler(BaseHTTPRequestHandler):
    server_version = "deeprec-tpu-serving/1.0"

    # set by HttpServer
    model_server: ModelServer = None

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def do_GET(self):
        if self.path == "/healthz":
            self._send(200, "ok")
        elif self.path == "/v1/model_info":
            self._send(200, self.model_server.predictor.model_info())
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def do_POST(self):
        try:
            n = int(self.headers.get("Content-Length", 0))
            payload = json.loads(self.rfile.read(n) or b"{}")
        except Exception as e:
            return self._send(400, {"error": f"bad json: {e}"})
        if self.path == "/v1/reload":
            try:
                updated = bool(self.model_server.predictor.poll_updates())
            except Exception as e:  # corrupt/partial checkpoint: report it
                return self._send(500, {"error": str(e)})
            return self._send(200, {"updated": updated})
        if self.path != "/v1/predict":
            return self._send(404, {"error": f"unknown path {self.path}"})
        if not isinstance(payload, dict):
            return self._send(400, {"error": "body must be a JSON object"})
        try:
            batch = parse_features(
                self.model_server.predictor, payload.get("features")
            )
        except BadRequest as e:
            return self._send(400, e.details)
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        try:
            probs = self.model_server.request(batch)
            if isinstance(probs, dict):
                out = {k: np.asarray(v).tolist() for k, v in probs.items()}
            else:
                out = np.asarray(probs).tolist()
            self._send(200, {"predictions": out})
        except Exception as e:  # request-level failure, keep serving
            self._send(500, {"error": str(e)})


class HttpServer:
    """Bind a ModelServer to a TCP port. start() is non-blocking."""

    def __init__(self, model_server: ModelServer, port: int = 8500,
                 host: str = "127.0.0.1"):
        handler = type("BoundHandler", (_Handler,),
                       {"model_server": model_server})
        self.httpd = ThreadingHTTPServer((host, port), handler)
        self.port = self.httpd.server_address[1]  # resolved if port=0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()  # release the listening socket
        if self._thread:
            self._thread.join(timeout=2)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", required=True, help="checkpoint directory")
    p.add_argument("--model", default="wdl",
                   help="modelzoo model name (see deeprec_tpu.models)")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--max_batch", type=int, default=256)
    p.add_argument("--poll_secs", type=float, default=10.0)
    p.add_argument("--emb_dim", type=int, default=16)
    p.add_argument("--capacity", type=int, default=1 << 20,
                   help="must match the trained checkpoint's table capacity")
    args = p.parse_args(argv)

    from deeprec_tpu.models.registry import build_model

    model = build_model(args.model, emb_dim=args.emb_dim,
                        capacity=args.capacity)
    pred = Predictor(model, args.ckpt)
    ms = ModelServer(pred, max_batch=args.max_batch,
                     poll_updates_secs=args.poll_secs)
    srv = HttpServer(ms, port=args.port, host=args.host)
    print(f"serving {args.model} from {args.ckpt} on "
          f"http://{args.host}:{srv.port}")
    srv.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
