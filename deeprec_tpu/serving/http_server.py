"""HTTP serving frontend over the ModelServer.

The network-facing surface of the serving stack — the role of the
reference's processor C ABI + gRPC glue (serving/processor/serving/
processor.h: initialize/process) re-cut as a dependency-free JSON/HTTP
server (stdlib http.server; a threading server whose request threads block
on the ModelServer's coalescing queue, so concurrent requests batch into
full device batches automatically).

Protocol:
  POST /v1/predict   {"features": {"C1": [..ids..], "I1": [[..]], ...}}
                  -> {"predictions": [...], "model_version": V}
                     (or {"task": [...]} predictions for MTL)
  GET  /v1/model_info -> {"step": N, "table_sizes": {...}, "model_version": V}
  GET  /v1/stats     -> per-stage latency histograms (queue/pad/device/
                        post/e2e), batch shape stats, model update counters
  POST /v1/reload    -> {"updated": bool}   (poll full/delta updates now)
  POST /v1/retrieve  {"features": {<user features>}, "k": 100}
                  -> {"items": [[id,...]], "scores": [[...]],
                      "model_version": V, "partial": false,
                      "candidates_scanned": N}
                     (full-corpus top-k, serving/retrieval.py; item
                      features are the resident corpus — absent ones are
                      pad-filled before parsing)
  GET  /healthz      -> 200 {"status": "ok", "staleness_seconds": ...,
                        "consecutive_poll_failures": 0, ...} — 503 with the
                        same body once the update poller is failing
                        (predictions still serve the last good snapshot)

Request bodies are capped (`max_body_bytes`, default 16 MiB): oversized
or malformed payloads get a structured 400 JSON error, never a 500.

Run: python -m deeprec_tpu.serving.http_server --model wdl --ckpt DIR
or embed: ``HttpServer(server, port=8500).start()``.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

import numpy as np

from deeprec_tpu.obs import metrics as obs_metrics
from deeprec_tpu.obs import trace as obs_trace
from deeprec_tpu.serving.predictor import (
    BadRequest,
    ModelServer,
    Predictor,
    parse_features,
)


def instances_to_features(instances) -> dict:
    """TF-Serving row-major request body -> this stack's column-major
    features: [{"f1": v, ...}, ...] -> {"f1": [v, ...], ...}."""
    if not isinstance(instances, list) or not instances:
        raise BadRequest("'instances' must be a non-empty list")
    if not all(isinstance(r, dict) for r in instances):
        raise BadRequest("each instance must be an object of named features")
    names = set(instances[0])
    if any(set(r) != names for r in instances):
        raise BadRequest("instances disagree on feature names")
    return {k: [r[k] for r in instances] for k in names}


class _Handler(BaseHTTPRequestHandler):
    server_version = "deeprec-tpu-serving/1.0"

    # set by HttpServer
    servers: dict = None  # name -> ModelServer
    default: str = None
    max_body: int = 16 << 20  # request-body byte cap (structured 400 past it)

    def log_message(self, fmt, *args):  # quiet by default
        pass

    @property
    def model_server(self) -> ModelServer:
        return self.servers[self.default]

    def _send(self, code: int, payload) -> None:
        body = json.dumps(payload).encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_text(self, code: int, text: str,
                   ctype: str = "text/plain; version=0.0.4") -> None:
        body = text.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _named(self, name: str) -> Optional[ModelServer]:
        srv = self.servers.get(name)
        if srv is None:
            self._send(404, {"error": f"unknown model {name!r}",
                             "models": sorted(self.servers)})
        return srv

    def do_GET(self):
        if self.path == "/healthz":
            # Watchdog surface (supervisor wedge detection): liveness +
            # model freshness. 200 while the poller is healthy, 503 once
            # it is failing consecutively — load balancers and the
            # online.supervisor treat non-200 as "degraded, watch it",
            # while predictions themselves keep serving the last good
            # snapshot either way.
            try:
                h = self.model_server.predictor.health()
            except Exception as e:  # health must never 500 the server
                return self._send(503, {"status": "error", "error": str(e)})
            self._send(200 if h.get("status") == "ok" else 503, h)
        elif self.path == "/v1/model_info":
            self._send(200, self.model_server.predictor.model_info())
        elif self.path == "/v1/stats":
            # Live per-stage serving histograms — the same accounting
            # tools/bench_serving.py records per measured configuration.
            self._send(200, self.model_server.stats_snapshot())
        elif self.path == "/metrics":
            # Prometheus-text exposition of the obs plane: this server's
            # serving series + the process-wide registry (training /
            # supervisor / placement gauges). A Frontend merges every
            # backend's series here, stale-marking down members. Must
            # never 500 — a scrape is a watchdog surface.
            try:
                fn = getattr(self.model_server, "metrics_text", None)
                text = (fn() if fn is not None
                        else obs_metrics.default_registry()
                        .render_prometheus())
            except Exception as e:
                return self._send_text(503, f"# metrics error: {e}\n")
            self._send_text(200, text)
        elif (self.path.startswith("/v1/models/")
              and self.path.endswith("/stats")):
            srv = self._named(self.path[len("/v1/models/"):-len("/stats")])
            if srv is not None:
                self._send(200, srv.stats_snapshot())
        elif self.path == "/v1/models":
            self._send(200, {"models": sorted(self.servers)})
        elif self.path.startswith("/v1/models/"):
            # TF-Serving REST model-status shape, so TFS clients can point
            # here unchanged: GET /v1/models/<name>
            srv = self._named(self.path[len("/v1/models/"):])
            if srv is not None:
                self._send(200, {"model_version_status": [{
                    "version": str(srv.predictor.step),
                    "state": "AVAILABLE",
                    "status": {"error_code": "OK", "error_message": ""},
                }]})
        else:
            self._send(404, {"error": f"unknown path {self.path}"})

    def _route_post(self):
        """(server, verb) for a POST path: the single-model back-compat
        routes (/v1/predict, /v1/reload) hit the default model; the
        TF-Serving shape (/v1/models/<name>:predict|:reload) names one."""
        if self.path in ("/v1/predict", "/v1/reload", "/v1/retrieve"):
            return self.model_server, self.path.rsplit("/", 1)[-1]
        if self.path.startswith("/v1/models/") and ":" in self.path:
            name, verb = self.path[len("/v1/models/"):].rsplit(":", 1)
            return self._named(name), verb
        self._send(404, {"error": f"unknown path {self.path}"})
        return None, None

    def do_POST(self):
        try:
            n = int(self.headers.get("Content-Length", 0))
        except (TypeError, ValueError):
            return self._send(400, {"error": "bad Content-Length"})
        if n < 0:
            return self._send(400, {"error": "bad Content-Length"})
        if n > self.max_body:
            # Reject BEFORE reading: an oversized body must cost a bounded
            # read and a structured 400, not an allocation + a 500. The
            # connection is closed (we never consumed the body).
            self.close_connection = True
            return self._send(400, {
                "error": "request body too large",
                "content_length": n,
                "limit_bytes": self.max_body,
            })
        raw = self.rfile.read(n)
        ctype = (self.headers.get("Content-Type") or "").split(";")[0].strip()
        # Only explicit protobuf media types take the protobuf path;
        # octet-stream stays on the JSON path (clients commonly use it as
        # a generic default for JSON bodies, and it worked before).
        if ctype in ("application/x-protobuf", "application/protobuf"):
            # Reference wire format: serialized PredictRequest in,
            # PredictResponse out (predict.proto). Routing still applies.
            server, verb = self._route_post()
            if server is None:
                return
            if verb != "predict":
                return self._send(400, {"error":
                                        "protobuf body only valid on :predict"})
            from deeprec_tpu.serving.cabi import process_proto

            code, body = process_proto(server, raw)
            self.send_response(code)
            self.send_header(
                "Content-Type",
                "application/x-protobuf" if code == 200 else "text/plain")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)
            return
        try:
            payload = json.loads(raw or b"{}")
        except Exception as e:
            return self._send(400, {"error": f"bad json: {e}"})
        server, verb = self._route_post()
        if server is None:
            return  # 404 already sent
        if verb == "reload":
            try:
                updated = bool(server.predictor.poll_updates())
            except Exception as e:  # corrupt/partial checkpoint: report it
                return self._send(500, {"error": str(e)})
            return self._send(200, {"updated": updated})
        if verb == "retrieve":
            # Full-corpus top-k (serving/retrieval.py): the request
            # carries USER features only — absent item features are
            # filled with pads before parsing (the item side is the
            # resident corpus). Answered by the local retrieval lane or
            # the fleet fan-out merge, whichever backs this server.
            rv = getattr(server, "retrieve_versioned", None)
            if rv is None:
                return self._send(501, {"error":
                                        "retrieval not supported here"})
            if not isinstance(payload, dict):
                return self._send(400, {"error":
                                        "body must be a JSON object"})
            from deeprec_tpu.serving.retrieval import (
                fill_missing_item_features,
            )

            try:
                k = int(payload.get("k", 10))
                feats = fill_missing_item_features(
                    server.predictor, payload.get("features"))
                batch = parse_features(server.predictor, feats)
            except BadRequest as e:
                return self._send(400, e.details)
            except (TypeError, ValueError) as e:
                return self._send(400, {"error": str(e)})
            try:
                rkw = {"no_cache": True} if payload.get("no_cache") else {}
                res = rv(batch, k, **rkw)
            except BadRequest as e:
                return self._send(400, e.details)
            except Exception as e:  # request-level failure, keep serving
                return self._send(500, {"error": str(e)})
            return self._send(200, {
                "items": res.ids.tolist(),
                # -inf marks "fewer than k valid items" (item id -1);
                # serialize it as null — json.dumps would emit
                # `-Infinity`, which is not RFC 8259 JSON and strict
                # client parsers reject the whole body
                "scores": [[round(float(s), 6) if np.isfinite(s) else None
                            for s in row] for row in res.scores],
                "model_version": res.version,
                "partial": bool(res.partial),
                "candidates_scanned": int(res.scanned),
            })
        if verb != "predict":
            return self._send(404, {"error": f"unknown verb {verb!r}"})
        if not isinstance(payload, dict):
            return self._send(400, {"error": "body must be a JSON object"})
        try:
            feats = payload.get("features")
            if feats is None and "instances" in payload:
                feats = instances_to_features(payload["instances"])
            batch = parse_features(server.predictor, feats)
        except BadRequest as e:
            return self._send(400, e.details)
        except ValueError as e:
            return self._send(400, {"error": str(e)})
        try:
            # Sampled request tracing: continue the caller's context from
            # the X-Deeprec-Trace header, or make the edge sampling
            # decision here; the span context rides into the micro-batcher
            # (and, through a Frontend, over the TCP frames to a backend)
            # so one trace id spans edge -> dispatch -> stage spans. The
            # no-op singleton makes this line free with tracing off.
            edge = obs_trace.server_span(
                "http_predict", "edge",
                header=self.headers.get(obs_trace.HEADER))
            # `no_cache` forces a real evaluation through a warm
            # compute-reuse cache (canary/parity probes) — passed only
            # when set, so servers without the reuse layer keep their
            # signature.
            kw = {"no_cache": True} if payload.get("no_cache") else {}
            if payload.get("group_users"):
                # sample-aware compression: a <user, N items> request
                # rides the grouped lane of the coalescing queue — many
                # grouped requests share one device batch and the user
                # tower runs once per distinct user across ALL of them
                # (the batcher never mixes grouped and plain requests:
                # they dispatch through different traces).
                try:
                    with edge:
                        probs, version = server.request_versioned(
                            batch, group_users=True, **kw)
                except (BadRequest, ValueError) as e:  # no tower split
                    return self._send(400, getattr(e, "details",
                                                   {"error": str(e)}))
            else:
                with edge:
                    probs, version = server.request_versioned(batch, **kw)
            if isinstance(probs, dict):
                out = {k: np.asarray(v).tolist() for k, v in probs.items()}
            else:
                out = np.asarray(probs).tolist()
            # model_version stamps WHICH snapshot served this request — a
            # coalesced batch shares one, so clients can detect update
            # boundaries (and the torn-read test can pin atomicity).
            self._send(200, {"predictions": out, "model_version": version})
        except Exception as e:  # request-level failure, keep serving
            self._send(500, {"error": str(e)})


class _ThreadingServer(ThreadingHTTPServer):
    # The stdlib default listen backlog is 5: under concurrent
    # connection-per-request clients, a momentarily busy host (e.g. a
    # model update competing for CPU) overflows the accept queue, the
    # kernel drops the SYN, and the client retries after the TCP
    # retransmission timeout — observed as a mysterious ~1.0 s request
    # spike during updates (the bulk of round-5's during_update_max_ms).
    request_queue_size = 128
    daemon_threads = True


class HttpServer:
    """Bind one server — a ModelServer, a ServerGroup, or a {name: server}
    dict for multi-model serving — to a TCP port. start() is non-blocking.
    Servers are duck-typed: anything with `.request_versioned()`,
    `.stats_snapshot()` and `.predictor` works (ServerGroup feeds requests
    through its shared queue to whichever device-pinned member is free).
    With a dict, the TF-Serving routes address each model by name and the
    bare routes hit `default_model` (first name if unset)."""

    def __init__(self, model_server, port: int = 8500,
                 host: str = "127.0.0.1", default_model: Optional[str] = None,
                 max_body_bytes: int = 16 << 20):
        if isinstance(model_server, dict):
            servers = dict(model_server)
        else:
            servers = {"default": model_server}
        if not servers:
            raise ValueError("need at least one ModelServer")
        default = default_model or next(iter(servers))
        if default not in servers:
            raise ValueError(f"default_model {default!r} not in {sorted(servers)}")
        handler = type("BoundHandler", (_Handler,),
                       {"servers": servers, "default": default,
                        "max_body": int(max_body_bytes)})
        self.httpd = _ThreadingServer((host, port), handler)
        self.port = self.httpd.server_address[1]  # resolved if port=0
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "HttpServer":
        self._thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()  # release the listening socket
        if self._thread:
            self._thread.join(timeout=2)


def main(argv=None):
    import argparse

    p = argparse.ArgumentParser()
    p.add_argument("--ckpt", help="checkpoint directory (single-model mode)")
    p.add_argument("--model", default="wdl",
                   help="modelzoo model name (see deeprec_tpu.models)")
    p.add_argument("--serve", action="append", default=[],
                   help="multi-model: JSON per model, repeatable — "
                        '\'{"name": "wdl-a", "model": "wdl", "ckpt_dir": '
                        '"...", "model_args": {...}}\' (same config schema '
                        "as the serving C ABI, serving/cabi.py)")
    p.add_argument("--port", type=int, default=8500)
    p.add_argument("--host", default="0.0.0.0")
    p.add_argument("--max_batch", type=int, default=256)
    p.add_argument("--poll_secs", type=float, default=10.0)
    p.add_argument("--emb_dim", type=int, default=16)
    p.add_argument("--capacity", type=int, default=1 << 20,
                   help="must match the trained checkpoint's table capacity")
    args = p.parse_args(argv)

    if args.serve:
        from deeprec_tpu.serving.cabi import create_server

        servers = {}
        for spec in args.serve:
            cfg = json.loads(spec)
            name = cfg.pop("name", None) or cfg.get("model", "default")
            if name in servers:
                p.error(f"duplicate --serve name {name!r}: set a distinct "
                        '"name" per model')
            cfg.setdefault("max_batch", args.max_batch)
            cfg.setdefault("poll_secs", args.poll_secs)
            servers[name] = create_server(json.dumps(cfg))
        srv = HttpServer(servers, port=args.port, host=args.host)
        print(f"serving {sorted(servers)} on http://{args.host}:{srv.port}")
    else:
        if not args.ckpt:
            p.error("--ckpt is required without --serve")
        from deeprec_tpu.models.registry import build_model

        model = build_model(args.model, emb_dim=args.emb_dim,
                            capacity=args.capacity)
        pred = Predictor(model, args.ckpt)
        ms = ModelServer(pred, max_batch=args.max_batch,
                         poll_updates_secs=args.poll_secs)
        srv = HttpServer(ms, port=args.port, host=args.host)
        print(f"serving {args.model} from {args.ckpt} on "
              f"http://{args.host}:{srv.port}")
    srv.start()
    try:
        threading.Event().wait()
    except KeyboardInterrupt:
        srv.stop()


if __name__ == "__main__":
    main()
