"""Serving: jit-compiled predictor with zero-stall full/delta model updates.

Parity with DeepRec's serving stack (SURVEY.md §2.7/§3.4) re-cut for TPU:
  * Processor initialize()/process()  -> Predictor(model, ckpt_dir) /
    predict(batch) — one jitted readonly forward, no training machinery.
  * ModelInstanceMgr's FullModelUpdate/DeltaModelUpdate background polling
    (model_instance.h:44-232) -> poll_updates(): builds the NEXT model
    state on a shadow copy (full restore or delta replay, never touching
    the live reference), pre-warms the jitted predict against the
    registered batch buckets, then publishes with one atomic reference
    swap. The predict path takes no lock at all: it reads one immutable
    (version, state) snapshot, so a request is served entirely from one
    model version and `during-update` latency is steady-state latency.
  * SessionGroup's N-sessions concurrency (direct_session_group.h) ->
    ModelServer: an adaptive micro-batching queue in front of the jitted
    function (flush on bucket-full or an arrival-rate-tuned deadline).
    ServerGroup is a shared-queue dispatcher that pins one member per
    distinct device — and degrades to a single member on a single-device
    host instead of N members thrashing one backend.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Callable, Dict, List, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu.obs import metrics as obs_metrics
from deeprec_tpu.obs import schema as obs_schema
from deeprec_tpu.obs import trace as obs_trace
from deeprec_tpu.optim.sparse import GradientDescent
from deeprec_tpu.serving.stats import ServingStats
from deeprec_tpu.training.checkpoint import CheckpointManager
from deeprec_tpu.utils import backoff as _backoff
from deeprec_tpu.training.trainer import Trainer, TrainState


class BadRequest(ValueError):
    """Client-side request error, with a structured payload for frontends
    that return machine-readable error bodies (HTTP, C ABI)."""

    def __init__(self, message: str, **details):
        super().__init__(message)
        self.details = {"error": message, **details}


# Re-export: the vectorized pad lives in utils/ragged.py now (shared with
# retrieval ingest and the reader-side packers); this name is the serving
# API surface and stays importable from here.
from deeprec_tpu.utils.ragged import pad_ragged  # noqa: E402,F401


def parse_features(predictor: "Predictor", feats: Dict) -> Dict[str, np.ndarray]:
    """Validate + coerce a wire-format feature dict (JSON-shaped lists or
    arrays) into a model batch. Shared by every frontend (HTTP, C ABI):
    validates BEFORE the coalescing queue so one bad request can't poison
    the requests batched with it. Raises ValueError with a client-facing
    message.

    Rules: id features pad/trim ragged bags to the feature's declared
    max_len with its pad value (one compiled shape per feature, not one per
    organic list length); dense features become [B, W] float32; all
    features must agree on the row count.

    Firewall rules (guard/ — malformed input must never reach the
    model): non-finite dense values REJECT the request (the client sent
    NaN/inf — scoring it would serve garbage stamped with a healthy
    model version); negative ids other than the pad value CLAMP to pad
    (treated as missing — id spaces are non-negative by construction,
    so a negative id is an upstream encoding bug, not a key). Both are
    counted per-feature into ``predictor.record_errors``."""
    if not isinstance(feats, dict) or not feats:
        raise BadRequest("missing 'features' object")
    dtypes = predictor.feature_dtypes
    unknown = sorted(set(feats) - set(dtypes))
    missing = sorted(set(dtypes) - set(feats))
    if unknown or missing:
        raise BadRequest("feature-name mismatch", unknown=unknown,
                         missing=missing)
    specs = {f.name: f for f in predictor._trainer.sparse_specs}
    batch = {}
    for k, v in feats.items():
        want = dtypes[k]
        try:
            if want.kind in "iu":
                f = specs[k]
                L = f.max_len
                if L and isinstance(v, list) and v and isinstance(v[0], list):
                    over = sum(max(0, len(r) - L) for r in v)
                    if over:  # bag ids past max_len are dropped, counted
                        predictor.count_record_error("oversized_bag", over)
                    arr = pad_ragged(v, L, f.pad_value, want)
                else:
                    arr = np.asarray(v).astype(want)
                    if L:
                        if arr.ndim == 1:
                            arr = arr[:, None]
                        if arr.shape[1] < L:
                            pad = np.full(
                                (arr.shape[0], L - arr.shape[1]), f.pad_value,
                                want,
                            )
                            arr = np.concatenate([arr, pad], axis=1)
                        else:
                            arr = arr[:, :L]
            else:
                arr = np.asarray(v).astype(np.float32)
                if arr.ndim == 1:
                    arr = arr[:, None]  # dense features are [B, W]
        except (TypeError, ValueError) as e:
            # numpy coercion of garbage values raises TypeError — still the
            # CLIENT's fault, so surface it as a request error, not a crash
            raise BadRequest(f"feature {k!r}: cannot coerce to {want}: {e}",
                             feature=k) from e
        if want.kind in "iu":
            f = specs[k]
            bad = (arr < 0) & (arr != f.pad_value)
            if bad.any():
                predictor.count_record_error("bad_id", int(bad.sum()))
                arr = np.where(bad, np.asarray(f.pad_value, arr.dtype), arr)
        else:
            nf = ~np.isfinite(arr)
            if nf.any():
                predictor.count_record_error("nonfinite_float",
                                             int(nf.sum()))
                raise BadRequest(
                    f"feature {k!r}: {int(nf.sum())} non-finite value(s)",
                    feature=k)
        batch[k] = arr
    rows = {k: a.shape[0] for k, a in batch.items()}
    if len(set(rows.values())) > 1:
        raise BadRequest("inconsistent feature row counts", rows=rows)
    return batch


class _Snapshot(NamedTuple):
    """The unit of atomicity for the serving hot path: readers grab ONE
    reference to this immutable pair and serve the whole request from it,
    so a concurrent update can never produce a torn (half-old, half-new)
    read. `version` increments on every published update."""

    version: int
    state: TrainState


class _ArrivalEWMA:
    """EWMA of request inter-arrival time and rows-per-request — the
    signal the adaptive batcher tunes its coalescing deadline from. One
    instance may be shared by every member of a ServerGroup (arrivals
    enter through one front door, members drain one shared queue)."""

    ALPHA = 0.1

    def __init__(self):
        self._lock = threading.Lock()
        self._last = None
        self._tau = None
        self._rows = None

    def note(self, t: float, rows: int) -> None:
        with self._lock:
            if self._last is not None:
                dt = max(t - self._last, 0.0)
                self._tau = (
                    dt if self._tau is None
                    else (1 - self.ALPHA) * self._tau + self.ALPHA * dt
                )
            self._last = t
            self._rows = (
                float(rows) if self._rows is None  # noqa: DRT002 — wall-clock floats, no device value crosses here
                else (1 - self.ALPHA) * self._rows + self.ALPHA * rows
            )

    def estimate(self) -> Tuple[Optional[float], float]:
        """(mean inter-arrival seconds or None, mean rows per request)."""
        with self._lock:
            return self._tau, self._rows or 1.0


class Predictor:
    """Load-latest-and-serve. Thread-safe; updates swap atomically.

    The hot path is lock-free: `predict` reads one `_Snapshot` reference
    (a GIL-atomic load) and never blocks on an in-flight update.
    `poll_updates`/`reload` serialize among THEMSELVES with `_lock`, build
    the next state off to the side (`CheckpointManager.restore_into` /
    `restore(chunk=...)` — functional replay, fixed import chunk so no
    update ever traces a fresh XLA program mid-serving), warm the jitted
    predict on the registered batch buckets, then publish the new
    snapshot.

    `stores` optionally maps table names to a feature-store object with
    ``get(keys) -> (values, freq, version, found)`` (HostKV signature) —
    the read-through analog of the reference's Redis feature store
    (serving/processor/storage/redis_feature_store.h:18): keys missing
    from the device table serve the store's row instead of the
    initializer value.
    """

    QUANTIZE_MODES = {
        None: "float32", "fp32": "float32", "float32": "float32",
        "bf16": "bfloat16", "bfloat16": "bfloat16", "int8": "int8",
    }

    def __init__(self, model, ckpt_dir: str, stores: Optional[Dict] = None,
                 device=None, restore_chunk="auto", quantize=None,
                 quality_gate=None):
        self.model = model
        # Serving needs no optimizer; slot-less sparse opt keeps restore lean
        # (checkpointed slot arrays are skipped when the template has none).
        self._trainer = Trainer(model, GradientDescent(), optax.identity())
        # Quantized serving-side row residency (train fp32, serve bf16 or
        # int8 + per-row scale): rebuild this predictor's PRIVATE bundles
        # with the residency dtype before anything traces or restores —
        # the checkpoint stays fp32 on disk, import_rows quantizes on the
        # way in, and every lookup gather dequantizes (embedding/table.py).
        # The model object itself is untouched (it may be shared with a
        # live fp32 trainer).
        if quantize not in self.QUANTIZE_MODES:
            raise ValueError(
                f"quantize must be one of {sorted(k or 'None' for k in self.QUANTIZE_MODES)}, "
                f"got {quantize!r}"
            )
        self.quantize = self.QUANTIZE_MODES[quantize]
        if self.quantize != "float32":
            import dataclasses as _dc

            from deeprec_tpu.embedding.table import EmbeddingTable

            for b in self._trainer.bundles.values():
                b.table = EmbeddingTable(
                    _dc.replace(b.table.cfg, value_dtype=self.quantize)
                )
        self._ck = CheckpointManager(ckpt_dir, self._trainer)
        if restore_chunk == "auto":
            # Every import slice copies the full values array once, so the
            # slice count must stay small relative to capacity: floor 4096
            # (one static shape, cheap slices for serving-cadence deltas),
            # scaled up for big tables so a full reload stays O(~16)
            # slices instead of O(capacity/4096).
            cap = max((t.cfg.capacity
                       for t in self._trainer.tables.values()), default=4096)
            restore_chunk = max(4096, 1 << (max(cap // 16, 1) - 1).bit_length())
        self._snap: Optional[_Snapshot] = None
        # Replica pinning (ServerGroup): committing the state to `device`
        # makes every jitted predict follow it there — N replicas on N
        # devices serve concurrently (uncommitted request arrays follow
        # the committed state under JAX placement rules).
        self._device = device
        self._restore_chunk = int(restore_chunk)
        self._applied: set = set()
        # Serializes UPDATERS only (concurrent poll_updates / reload /
        # HTTP /v1/reload); the predict path never touches it.
        self._lock = threading.RLock()
        self.stores = dict(stores or {})
        self.update_count = 0
        self.last_update_ms = 0.0
        # Poll-health telemetry (exported via /v1/stats + /healthz, and
        # stamped into ServeLoop heartbeats for supervisor wedge
        # detection): consecutive_poll_failures counts poll_updates calls
        # that raised since the last success; last_poll_ok_time is the
        # last moment a poll round CONFIRMED the served model is as fresh
        # as the checkpoint dir (staleness_seconds derives from it);
        # last_good_version is the version that confirmation served.
        self.consecutive_poll_failures = 0
        self.last_good_version = 0
        self.last_poll_ok_time = time.monotonic()
        self.last_update_time = time.monotonic()
        # Train-to-serve lag of the LAST applied update: wall-clock age
        # of the newest applied checkpoint's manifest at swap time (the
        # trainer committed it then; serving started answering from it
        # now). None until the first post-boot update. The obs plane
        # exposes it as the deeprec_train_to_serve_lag_seconds gauge,
        # and tools/bench_freshness.py pins it against its own
        # probe-measured freshness lag.
        self.last_apply_lag_seconds: Optional[float] = None
        # Per-record input-error counters (parse_features firewall:
        # clamped bad ids, rejected non-finite dense) — mirrored into
        # deeprec_record_errors{kind}; kinds are a bounded set.
        self.record_errors: Dict[str, int] = {}
        # Test seam: called after the next state is fully built and
        # warmed, immediately before the snapshot swap — lets tests gate
        # the publish on an event (torn-read pinning) without wall-clock.
        self._pre_swap: Optional[Callable[[], None]] = None
        self._warm_batches: Dict[tuple, Dict[str, np.ndarray]] = {}
        self._predict_step = jax.jit(self._predict_impl)
        self._predict_grouped_step = jax.jit(
            self._predict_grouped_impl, static_argnums=2
        )
        self._predict_grouped_uvec_step = jax.jit(
            self._predict_grouped_uvec_impl, static_argnums=2
        )
        self._predict_with_user_step = jax.jit(self._predict_with_user_impl)
        self._forward_step = jax.jit(self._forward_impl)
        self._lookup_step = jax.jit(self._lookup_views)
        # Pre-swap canary (guard/canary.py QualityGate): every update —
        # delta replay or full reload — evaluates the gate's probe batch
        # on the SHADOW state before the snapshot swap; a failing update
        # is quarantined (PR 7 rename discipline) and the old snapshot
        # keeps serving, with health() reporting degraded:quality_gate.
        self.quality_gate = quality_gate
        self._gate_blocked = False
        # Retrieval attachment (serving/retrieval.py): when an engine is
        # attached, every published model update notifies it so delta
        # replay folds changed item rows into the resident corpus matrix
        # within the SAME poll round (freshness contract).
        self._retrieval = None
        # Compute-reuse caches (serving/reuse.py): every publish is the
        # invalidation edge — entries are keyed by version, so the swap
        # makes them dead and invalidate_stale() reclaims the bytes
        # inside the SAME updater round (never a background sweep).
        self._reuse_caches: List = []
        self._m_gate_rejections = None
        if quality_gate is not None and obs_metrics.metrics_enabled():
            self._m_gate_rejections = obs_metrics.default_registry().counter(
                "deeprec_quality_gate_rejections",
                "model updates rejected by the pre-swap canary")
        self.reload()
        # Compile the delta-replay programs NOW (chunked import + prune
        # rebuild): the first poll_updates under live traffic must be
        # cache-hit dispatch, not a GIL-held trace next to requests.
        self._ck.warm_replay(self._snap.state, self._restore_chunk)
        if quality_gate is not None:
            # Prime the gate: compiles the probe shape once (later gate
            # passes are cache-hit dispatch — zero steady-state compiles)
            # and stamps the boot snapshot's predictions as reference.
            quality_gate.set_reference(self._gate_probs(self._snap.state))

    # ------------------------------------------------------------- updates

    @property
    def _state(self) -> TrainState:
        """Back-compat view of the live state (tests, tooling)."""
        return self._snap.state

    @property
    def version(self) -> int:
        """Monotonic model version: bumps on every published update."""
        return self._snap.version

    def reload(self) -> bool:
        """Full reload from the latest checkpoint chain (FullModelUpdate).
        Builds the fresh state entirely off the serving path, gates it
        through the pre-swap canary, then swaps. Returns whether a new
        snapshot published (False: the quality gate rejected it and the
        old snapshot keeps serving)."""
        with self._lock:
            # List BEFORE restoring: a delta landing mid-restore then stays
            # un-applied and is picked up by the next poll (replaying a delta
            # restore() already consumed is idempotent, missing one is not).
            dirs = set(self._dirs())
            state = self._ck.restore(chunk=self._restore_chunk)
            if self._device is not None:
                state = jax.device_put(state, self._device)
            reason = self._gate_reason(state)
            if reason is not None:
                self._gate_reject(sorted(dirs - self._applied), reason)
                return False
            self._publish(state, dirs)
            self._gate_blocked = False
            if self._retrieval is not None:
                # full reload: every resident item vector may have moved
                self._retrieval.on_model_update(None, full=True)
            return True

    def attach_retrieval(self, engine) -> None:
        """Register a RetrievalEngine for model-update notifications
        (called by the engine's own constructor)."""
        self._retrieval = engine

    def attach_reuse_cache(self, cache) -> None:
        """Register a ReuseCache for publish-edge invalidation: every
        snapshot swap drops the cache's stale-version entries before the
        updater round ends (serving/reuse.py contract)."""
        self._reuse_caches.append(cache)

    # ----------------------------------------------- pre-swap quality gate

    def _gate_probs(self, state: TrainState):
        """Probe-batch predictions on an arbitrary state — one fixed
        shape, compiled once at attach time (no store read-through: the
        canary judges the MODEL, per-row store corrections don't move
        under a delta)."""
        jb = {k: jnp.asarray(v) for k, v in self.quality_gate.probe.items()}
        return jax.tree.map(np.asarray, self._predict_step(state, jb))  # noqa: DRT002 — update-cadence canary eval, never the predict path

    def _gate_reason(self, state: TrainState) -> Optional[str]:
        """None when the shadow state passes the canary (its probe
        predictions then become the next reference); else the rejection
        reason. The gate only arms once a snapshot is serving — at boot
        there is nothing older to keep serving."""
        from deeprec_tpu.guard.canary import QualityGateRejected

        gate = self.quality_gate
        if gate is None or self._snap is None:
            return None
        probs = self._gate_probs(state)
        try:
            gate.check(probs)
        except QualityGateRejected as e:
            return e.reason
        gate.set_reference(probs)
        return None

    def _gate_reject(self, dirnames, reason: str) -> None:
        """Quarantine the update's dirs (rename discipline — the
        trainer's next save re-anchors past them) and surface the
        degraded-by-choice state: old snapshot serves, health says why."""
        for d in dirnames:
            self._ck.quarantine(
                os.path.join(self._ck.dir, d), f"quality gate: {reason}")
        self._gate_blocked = True
        if self._m_gate_rejections is not None:
            self._m_gate_rejections.inc()
        import logging

        logging.getLogger(__name__).warning(
            "quality gate rejected update (%s): quarantined %s — serving "
            "the previous snapshot", reason, list(dirnames))

    def _publish(self, state: TrainState, applied: set) -> None:
        """Warm-then-swap: run the jitted predict for every registered
        batch bucket against the INCOMING state (any straggler compile or
        cold cache is paid here, on the updater thread), then replace the
        snapshot reference — the only write the serving path ever sees."""
        self._warm_state(state)
        if self._pre_swap is not None:
            self._pre_swap()
        prev = self._snap
        self._snap = _Snapshot(prev.version + 1 if prev else 0, state)
        self._applied = set(applied)
        # Invalidation-by-version: the swap already made every cached
        # answer un-hittable (keys carry the version); this reclaims the
        # bytes and counts the drops on the publish edge.
        for c in self._reuse_caches:
            c.invalidate_stale()

    def _warm_state(self, state: TrainState) -> None:
        # list(): a concurrent warmup() may register new buckets mid-walk
        for b in list(self._warm_batches.values()):
            jb = {k: jnp.asarray(v) for k, v in b.items()}
            if self.stores:
                views, _ = self._lookup_step(state, jb)
                jax.block_until_ready(self._forward_step(state, views, jb))  # noqa: DRT002 — warm-before-swap: the UPDATER thread pays the sync, the predict path never does
            else:
                jax.block_until_ready(self._predict_step(state, jb))  # noqa: DRT002 — warm-before-swap, same contract as above

    def register_warm_batch(self, batch: Dict[str, np.ndarray]) -> None:
        """Remember one example batch per shape signature; every future
        update re-runs these against the incoming state before the swap
        (ModelServer.warmup registers its whole bucket ladder)."""
        sig = tuple(sorted(
            (k, np.asarray(v).shape, str(np.asarray(v).dtype))  # noqa: DRT002 — update-path only: shape signature of a host example batch
            for k, v in batch.items()
        ))
        with self._lock:  # vs a background poll publishing concurrently
            if sig not in self._warm_batches:
                self._warm_batches[sig] = {
                    k: np.asarray(v) for k, v in batch.items()  # noqa: DRT002 — update-path only: host copy of the warm batch
                }

    def _dirs(self) -> List[str]:
        """Basenames of the VERIFIED checkpoint chain. Corrupt or torn
        links are quarantined by the manager as a side effect and never
        returned — serving treats them as absent (degraded-serving
        contract: keep answering from the last good model)."""
        return self._ck.chain_dirs()

    def poll_updates(self) -> bool:
        """Apply anything new: a newer full checkpoint triggers a full
        reload; new deltas replay onto a SHADOW copy of the live state
        (DeltaModelUpdate) — the live snapshot is never touched until the
        finished, warmed replacement swaps in. Returns True if the model
        changed. Safe to call concurrently (HTTP /v1/reload + background
        poller): the whole check-then-act runs under the updater lock, so
        a stale delta can never replay over a newer full reload.

        Fault contract: only dirs that pass integrity verification are
        considered (corrupt deltas are quarantined and skipped — the old
        snapshot keeps serving); a verified delta whose replay still
        fails is quarantined too and the chain truncates there. A raised
        exception (IO errors listing the dir, OOM mid-warm) increments
        `consecutive_poll_failures` for the watchdogs and re-raises — the
        caller loop (`_run_poll_loop`) retries with capped backoff."""
        t0 = time.perf_counter()
        t0w = time.time()
        try:
            with self._lock:
                changed = self._poll_locked(t0)
        except BaseException:
            self.consecutive_poll_failures += 1
            raise
        self.consecutive_poll_failures = 0
        self.last_poll_ok_time = time.monotonic()
        self.last_good_version = self._snap.version
        if changed:
            # online-timeline event: the delta poll that changed the model
            obs_trace.phase_span("delta_poll", t0w, time.time(),
                                 cat="online")
        return changed

    def _stamp_apply_lag(self, dirnames) -> None:
        """Record the wall-clock age of the freshest checkpoint this
        round applied (manifest mtime = the trainer's commit instant) —
        the live train-to-serve lag signal. Host-side file metadata
        only; failure to stat must never fail the update."""
        newest = None
        for d in dirnames:
            try:
                m = os.path.getmtime(
                    os.path.join(self._ck.dir, d, "manifest.json"))
            except OSError:
                continue
            if newest is None or m > newest:
                newest = m
        if newest is not None:
            self.last_apply_lag_seconds = round(
                max(0.0, time.time() - newest), 3)

    def _poll_locked(self, t0: float) -> bool:
        new = [d for d in self._dirs() if d not in self._applied]
        if not new:
            return False
        if any(d.startswith("full-") for d in new):
            if not self.reload():
                return False  # gate-rejected: old snapshot keeps serving
            self._stamp_apply_lag(new)
        else:
            state = self._snap.state
            applied = set(self._applied)
            replayed: List[str] = []
            progressed = False
            for d in sorted(new, key=lambda s: int(s.split("-")[1])):  # noqa: DRT002 — host string parse of a checkpoint dir name, no device value
                path = os.path.join(self._ck.dir, d)
                try:
                    state = self._ck.restore_into(
                        state, path, chunk=self._restore_chunk,
                    )
                except Exception as e:
                    # Passed verification yet failed to replay (e.g. rows
                    # exceed this topology's capacity, FS error mid-read):
                    # quarantine it and stop at the gap — what already
                    # replayed this round still publishes below, and the
                    # trainer's next save re-anchors the chain.
                    self._ck.quarantine(path, f"delta replay failed: {e}")
                    break
                applied.add(d)
                replayed.append(d)
                progressed = True
            if not progressed:
                return False
            if self._device is not None:
                state = jax.device_put(state, self._device)
            reason = self._gate_reason(state)
            if reason is not None:
                # The pre-swap canary failed the replayed delta(s): the
                # shadow state is discarded, the replayed dirs leave the
                # chain namespace, the live snapshot is untouched —
                # freshness sacrificed by choice, visibly (health()).
                self._gate_reject(replayed, reason)
                return False
            self._publish(state, applied)
            self._gate_blocked = False
            if self._retrieval is not None:
                # Fold the replayed deltas' changed item rows into the
                # corpus inside the SAME poll round: a newly trained item
                # is retrievable the moment this poll returns.
                self._retrieval.on_model_update(replayed, full=False)
            self._stamp_apply_lag(replayed)
        self.update_count += 1
        self.last_update_time = time.monotonic()
        self.last_update_ms = round((time.perf_counter() - t0) * 1e3, 3)
        return True

    def count_record_error(self, kind: str, n: int = 1) -> None:
        """Account one parse_features clamp/reject (bounded kind set —
        the serving half of data/readers.py RecordErrors)."""
        self.record_errors[kind] = self.record_errors.get(kind, 0) + n
        if obs_metrics.metrics_enabled():
            obs_metrics.default_registry().counter(
                "deeprec_record_errors",
                "malformed input records rejected/clamped by kind",
                {"kind": kind},
            ).inc(n)

    def health(self) -> Dict:
        """Liveness/freshness summary for watchdogs — the `/healthz` body
        and the ServeLoop heartbeat payload. `staleness_seconds` is the
        age of the last successful poll round (the last time serving
        CONFIRMED it is as fresh as the checkpoint dir), not the age of
        the last model change — an idle trainer is not staleness.

        The payload is the unified obs schema (obs/schema.py) — the one
        shape the frontend sweep and the online-loop heartbeat also
        emit; every historical key is a canonical member of it. A
        quality-gate rejection that is still holding freshness back
        reports ``degraded`` with ``degraded_reason: quality_gate`` —
        stale by CHOICE, never silently."""
        now = time.monotonic()
        status = "ok" if self.consecutive_poll_failures == 0 else "degraded"
        extra = {}
        if self._retrieval is not None:
            # Shard-coverage signal for the fleet sweep: a retrieval
            # backend that respawned with an EMPTY corpus (in-process
            # mirrors die with the process; nothing re-ingests on
            # rejoin) answers RETR "successfully" with nothing — the
            # frontend compares this count across members and degrades
            # when one shard is empty while siblings hold items.
            extra["retrieval_corpus_rows"] = self._retrieval.corpus_rows()
        if self.quality_gate is not None:
            extra["quality_gate_rejections"] = self.quality_gate.rejections
            if self.quality_gate.last_rejection is not None:
                extra["last_quality_rejection"] = (
                    self.quality_gate.last_rejection)
            if self._gate_blocked and status == "ok":
                status = "degraded"
                extra["degraded_reason"] = "quality_gate"
        return obs_schema.health_payload(
            status,
            model_version=self.version,
            step=self.step,
            staleness_seconds=round(now - self.last_poll_ok_time, 3),
            last_update_age_seconds=round(now - self.last_update_time, 3),
            consecutive_poll_failures=self.consecutive_poll_failures,
            last_good_version=self.last_good_version,
            quarantined=self._ck.quarantine_count,
            train_to_serve_lag_seconds=self.last_apply_lag_seconds,
            **extra,
        )

    # ------------------------------------------------------------- predict

    def predict(self, batch: Dict[str, np.ndarray], group_users: bool = False):
        """Probabilities for one batch (dict keyed per task for MTL)."""
        return self.predict_versioned(batch, group_users)[0]

    def predict_versioned(
        self, batch: Dict[str, np.ndarray], group_users: bool = False
    ):
        """(probabilities, model_version) for one batch — the version is
        read atomically WITH the state, so the pair certifies which model
        produced the answer (response stamping, torn-read tests).
        Label-free: the serving path runs lookup + forward + sigmoid only —
        no loss, no dummy labels, no training machinery.

        group_users=True enables serving-side sample-aware compression for
        tower models (the reference's graph-optimizer rewrite,
        serving/processor/framework/graph_optimizer.cc, spec
        docs/docs_en/Sample-awared-Graph-Compression.md): rows of a
        ``<user, N items>`` batch that share identical user-feature values
        run the user tower ONCE per distinct user (G rows instead of B)
        and broadcast the user vector. Requires the model to expose
        ``user_feats`` / ``user_vector`` / ``apply_with_user`` (DSSM
        does). Outputs are row-for-row identical to the plain path.
        Ignores feature stores (read-through is a per-row correction that
        the grouped trace doesn't carry)."""
        snap = self._snap  # ONE atomic read; the whole request uses it
        state = snap.state
        if group_users:
            if not hasattr(self.model, "apply_with_user"):
                raise ValueError(
                    f"{type(self.model).__name__} has no user/item tower "
                    "split (needs user_feats/user_vector/apply_with_user)"
                )
            cols = np.concatenate(
                [
                    np.asarray(batch[n]).reshape(len(np.asarray(batch[n])), -1)  # noqa: DRT002 — group_users host-side dedup is the documented price of sample-aware compression
                    for n in self.model.user_feats
                ],
                axis=1,
            )
            b = cols.shape[0]
            # Bucket BOTH shapes to powers of two — one compile per
            # (row-bucket, group-bucket), not one per client batch size.
            # Pad rows by repeating the last row: its user already exists,
            # so the distinct-user count is unchanged.
            bp = 1 << max(b - 1, 0).bit_length()
            distinct = len(np.unique(cols, axis=0))
            g = min(1 << max(distinct - 1, 0).bit_length(), bp)
            def pad(v):
                v = np.asarray(v)  # noqa: DRT002 — host distinct-user count sizes the compile bucket BEFORE dispatch
                if bp > b:
                    v = np.concatenate(
                        [v, np.repeat(v[-1:], bp - b, axis=0)]
                    )
                return jnp.asarray(v)

            batch = {k: pad(v) for k, v in batch.items()}
            probs = self._predict_grouped_step(state, batch, g)
            return jax.tree.map(lambda a: np.asarray(a)[:b], probs), snap.version  # noqa: DRT002 — result D2H: the reply must land on the host
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self.stores:
            probs = self._predict_with_stores(state, batch)
        else:
            probs = self._predict_step(state, batch)
        return jax.tree.map(np.asarray, probs), snap.version

    def _lookup_views(self, state, batch):
        """Readonly lookup pass: feature -> (unique embs, inverse, mask)
        plus per-bundle results (slot_ix/uids for the store fallback)."""
        return self._trainer.forward_views(state, batch)

    def _predict_impl(self, state, batch):
        views, _ = self._lookup_views(state, batch)
        return self._trainer.probs_from_views(state, views, batch)[1]

    def _predict_grouped_impl(self, state, batch, num_groups: int):
        """Sample-aware compressed forward: user tower on G deduped rows,
        item tower + scoring on all B rows. Group identity is exact (id
        columns compared row-wise, not hashed), so equal outputs are
        guaranteed; apply_grouped returns NaN rows on group overflow,
        which cannot happen because predict() sizes num_groups from the
        host-side distinct count."""
        from deeprec_tpu import nn as _nn

        m = self.model
        views, _ = self._lookup_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        ucols = jnp.concatenate(
            [batch[n].reshape(batch[n].shape[0], -1) for n in m.user_feats],
            axis=1,
        )
        _, gids = jnp.unique(
            ucols, axis=0, size=num_groups, return_inverse=True
        )
        uvec = _nn.apply_grouped(
            lambda ins: m.user_vector(state.dense, ins),
            inputs,
            gids.reshape(-1),
            num_groups,
        )
        out = m.apply_with_user(state.dense, uvec, inputs)
        if isinstance(out, dict):
            return {k: jax.nn.sigmoid(v) for k, v in out.items()}
        return jax.nn.sigmoid(out)

    def _predict_grouped_uvec_impl(self, state, batch, num_groups: int):
        """`_predict_grouped_impl` that ALSO returns the per-row user
        vectors — the user-tower cache's population path (serving/
        reuse.py): the batcher stores each request's lead user vector so
        the next request from that user skips the user tower entirely.
        Same recipe as the grouped trace, so probabilities are
        row-for-row identical to it."""
        from deeprec_tpu import nn as _nn

        m = self.model
        views, _ = self._lookup_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        ucols = jnp.concatenate(
            [batch[n].reshape(batch[n].shape[0], -1) for n in m.user_feats],
            axis=1,
        )
        _, gids = jnp.unique(
            ucols, axis=0, size=num_groups, return_inverse=True
        )
        uvec = _nn.apply_grouped(
            lambda ins: m.user_vector(state.dense, ins),
            inputs,
            gids.reshape(-1),
            num_groups,
        )
        out = m.apply_with_user(state.dense, uvec, inputs)
        if isinstance(out, dict):
            return {k: jax.nn.sigmoid(v) for k, v in out.items()}, uvec
        return jax.nn.sigmoid(out), uvec

    def _predict_with_user_impl(self, state, batch, uvec):
        """The candidate-only lane: the user tower never runs — `uvec`
        (one cached user vector per row) is applied directly. Everything
        else (lookup, item tower, scoring head, sigmoid) is the grouped
        recipe, so a cached-user answer is row-for-row identical to the
        full evaluation that produced the vector."""
        m = self.model
        views, _ = self._lookup_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        out = m.apply_with_user(state.dense, uvec, inputs)
        if isinstance(out, dict):
            return {k: jax.nn.sigmoid(v) for k, v in out.items()}
        return jax.nn.sigmoid(out)

    def predict_grouped_uvec_versioned(self, batch: Dict[str, np.ndarray]):
        """(probabilities, per-row user vectors, model_version) — the
        grouped path through `_predict_grouped_uvec_step`. Bucketing is
        identical to `predict_versioned(group_users=True)`; the extra
        output feeds the user-tower cache."""
        snap = self._snap
        state = snap.state
        m = self.model
        cols = np.concatenate(
            [
                np.asarray(batch[n]).reshape(len(np.asarray(batch[n])), -1)  # noqa: DRT002 — group_users host-side dedup is the documented price of sample-aware compression
                for n in m.user_feats
            ],
            axis=1,
        )
        b = cols.shape[0]
        bp = 1 << max(b - 1, 0).bit_length()
        distinct = len(np.unique(cols, axis=0))
        g = min(1 << max(distinct - 1, 0).bit_length(), bp)

        def pad(v):
            v = np.asarray(v)  # noqa: DRT002 — host distinct-user count sizes the compile bucket BEFORE dispatch
            if bp > b:
                v = np.concatenate([v, np.repeat(v[-1:], bp - b, axis=0)])
            return jnp.asarray(v)

        jb = {k: pad(v) for k, v in batch.items()}
        probs, uvec = self._predict_grouped_uvec_step(state, jb, g)
        return (
            jax.tree.map(lambda a: np.asarray(a)[:b], probs),  # noqa: DRT002 — result D2H: the reply must land on the host
            np.asarray(uvec)[:b],  # noqa: DRT002 — user vectors land host-side to become cache values
            snap.version,
        )

    def predict_with_user_versioned(self, batch: Dict[str, np.ndarray],
                                    uvec: np.ndarray):
        """(probabilities, model_version) with the user tower skipped:
        `uvec` carries one user vector per batch row (from the
        user-tower cache). Rows bucket to powers of two exactly like the
        grouped path (pad repeats the last row AND its vector, so the
        pad rows stay self-consistent). The caller must re-check that
        the returned version equals the version the vectors were cached
        at — a publish between lookup and dispatch makes the answer
        stale, and the batcher falls back to the full grouped path."""
        first = next(iter(batch.values()))
        b = int(np.asarray(first).shape[0])  # noqa: DRT002 — host row count of the incoming request payload
        bp = 1 << max(b - 1, 0).bit_length()

        def pad(v):
            v = np.asarray(v)  # noqa: DRT002 — host pad of request payload, pre-dispatch
            if bp > b:
                v = np.concatenate([v, np.repeat(v[-1:], bp - b, axis=0)])
            return jnp.asarray(v)

        snap = self._snap
        jb = {k: pad(v) for k, v in batch.items()}
        juv = pad(np.asarray(uvec, np.float32))
        probs = self._predict_with_user_step(snap.state, jb, juv)
        return jax.tree.map(lambda a: np.asarray(a)[:b], probs), snap.version  # noqa: DRT002 — result D2H: the reply must land on the host

    def _forward_impl(self, state, views, batch):
        return self._trainer.probs_from_views(state, views, batch)[1]

    def _predict_with_stores(self, state, batch):
        """Read-through path: jitted lookup, host-side store correction of
        missing keys, jitted forward. Two dispatches instead of one — the
        price of consulting an external store, paid only when configured."""
        views, bundle_res = self._lookup_step(state, batch)
        views = dict(views)
        for bname, b in self._trainer.bundles.items():
            res = bundle_res[bname]
            for k, f in enumerate(b.features):
                tname = self._resolve_table_name(f)
                store = self.stores.get(tname)
                if store is None:
                    continue
                r = (
                    jax.tree.map(lambda a: a[k], res)
                    if b.stacked
                    else res[f.name]
                )
                emb, inverse, mask = views[f.name]
                missing = np.asarray(r.slot_ix < 0) & np.asarray(r.valid)  # noqa: DRT002 — read-through store correction is a documented two-dispatch host path
                if not missing.any():
                    continue
                keys = np.asarray(r.uids)[missing].astype(np.int64)  # noqa: DRT002 — read-through miss mask, host side by design
                rows, _, _, found = store.get(keys)
                if not found.any():
                    continue
                emb = np.asarray(emb).copy()  # noqa: DRT002 — read-through store keys, host side by design
                mix = np.nonzero(missing)[0][found]
                emb[mix] = rows[found].astype(emb.dtype)
                views[f.name] = (jnp.asarray(emb), inverse, mask)
        return self._forward_step(state, views, batch)

    @staticmethod
    def _resolve_table_name(f):
        from deeprec_tpu.features import resolve_table_name

        return resolve_table_name(f)

    @property
    def feature_dtypes(self) -> Dict[str, "np.dtype"]:
        """Expected numpy dtype per input feature (sparse ids use their
        table's key_dtype; dense features are float32) — lets frontends
        coerce JSON payloads without truncating 64-bit ids."""
        from deeprec_tpu import features as fcol

        out = {}
        cfgs = {n: t.cfg for n, t in self._trainer.tables.items()}
        for f in self._trainer.sparse_specs:
            out[f.name] = np.dtype(cfgs[fcol.resolve_table_name(f)].key_dtype)
        for f in self._trainer.dense_specs:
            out[f.name] = np.dtype(np.float32)
        return out

    @property
    def step(self) -> int:
        return int(self._snap.state.step)  # noqa: DRT002 — stats/health surface, not the predict path; one scalar pull

    def model_info(self) -> Dict:
        """get_serving_model_info parity."""
        snap = self._snap  # one snapshot: no torn step/sizes mix under
        sizes = {}  # a concurrent hot-swap
        for name, t in self._trainer.tables.items():
            sizes[name] = int(t.size(self._trainer.table_state(snap.state, name)))
        return {"step": int(snap.state.step), "table_sizes": sizes,
                "model_version": snap.version}

    def residency_info(self) -> Dict:
        """Serving residency accounting per table: measured value-storage
        bytes (values + per-row scale, straight off the device array
        shapes — no sync) against the `ops/traffic.py` model, plus the
        fp32 baseline the quantized residency is compared to. Surfaced
        through `/v1/stats` and recorded by tools/bench_serving.py;
        `roofline.py --assert-serving` pins measured == modeled."""
        from deeprec_tpu.ops import traffic

        snap = self._snap
        tables = {}
        totals = {"measured_bytes": 0, "modeled_bytes": 0.0, "fp32_bytes": 0.0}
        for name, t in self._trainer.tables.items():
            ts = self._trainer.table_state(snap.state, name)
            vb = int(ts.values.size) * ts.values.dtype.itemsize
            sb = (0 if ts.qscale is None
                  else int(ts.qscale.size) * ts.qscale.dtype.itemsize)
            modeled = traffic.serving_residency_bytes(
                capacity=t.cfg.capacity, dim=t.cfg.dim,
                value_dtype=t.cfg.value_dtype,
            )
            fp32 = traffic.serving_residency_bytes(
                capacity=t.cfg.capacity, dim=t.cfg.dim, value_dtype="float32",
            )
            tables[name] = {
                "value_dtype": t.cfg.value_dtype,
                "measured_bytes": vb + sb,
                "modeled_bytes": modeled,
                "fp32_bytes": fp32,
            }
            totals["measured_bytes"] += vb + sb
            totals["modeled_bytes"] += modeled
            totals["fp32_bytes"] += fp32
        return {"quantize": self.quantize, "tables": tables, **totals}


def _run_poll_loop(owner, stop: threading.Event, secs: float,
                   max_backoff_secs: float = 30.0,
                   pause: Optional[threading.Event] = None,
                   on_round=None) -> None:
    """Shared checkpoint-watch loop (ModelServer + ServerGroup + ServeLoop):
    poll `owner.predictor` for updates every `secs`.

    Survivability contract: this loop NEVER exits on an exception — a
    poll that raises (corrupt checkpoint dir mid-scan, FS blip, OOM in a
    warm pass) is counted, logged, and retried with capped exponential
    backoff + jitter; the old snapshot keeps serving throughout, and the
    failure is visible through `owner.update_failures` and the
    predictor's `consecutive_poll_failures` / `staleness_seconds`
    (/healthz), so watchdogs see a degraded poller instead of a silently
    frozen model. Backoff resets to the base cadence on the first
    success. `stop.wait` (not time.sleep) keeps shutdown prompt.

    `pause` (when set) skips rounds without stopping the thread —
    deterministic fault tests gate polling while they corrupt a delta.
    `on_round(status)` runs after every non-paused round with "ok" or
    "degraded" (ServeLoop stamps its heartbeat there); it must never
    kill the poller, so exceptions from it are swallowed."""
    import logging
    import random

    log = logging.getLogger(__name__)
    rng = random.Random(id(owner) & 0xFFFFFFFF)
    delay = secs
    while not stop.wait(delay):
        if pause is not None and pause.is_set():
            delay = secs
            continue
        status = "ok"
        try:
            owner.predictor.poll_updates()
            owner.update_failures = 0
            delay = secs
        except Exception as e:
            status = "degraded"
            try:
                n = getattr(owner, "update_failures", 0) + 1
                owner.update_failures = n
                # capped exponential backoff, jittered across [0.5, 1.5)x
                # so N pollers hitting one bad FS don't retry in lockstep
                # (shared utils/backoff.py policy; the n-th failure waits
                # secs * 2^n — one doubling up front, since the base
                # cadence already elapsed before the failure surfaced)
                delay = _backoff.jittered_backoff(
                    n + 1, secs, max_backoff_secs, rng, max_exponent=10)
                log.warning(
                    "model update poll failed (%d consecutive, retry in "
                    "%.1fs): %s", n, delay, e,
                )
            except Exception:  # accounting must never kill the poller
                delay = max_backoff_secs
        if on_round is not None:
            try:
                on_round(status)
            except Exception:
                pass  # accounting must never kill the poller


def _server_metrics_snapshot(stats: ServingStats) -> Dict:
    """One mergeable snapshot for a serving front: its own obs-plane
    series + the process-wide plane (training/supervisor/placement
    gauges) — the body of the METR wire op and of `GET /metrics`.
    Shared by ModelServer and ServerGroup so the frontend's merge sees
    one shape regardless of which server type backs a member."""
    snaps = [stats.metrics_snapshot()]
    if obs_metrics.metrics_enabled():
        snaps.append(obs_metrics.default_registry().snapshot())
    return obs_metrics.merge_snapshots([s for s in snaps if s])


class ModelServer:
    """Micro-batching front: coalesce single requests into device batches.

    The SessionGroup analog — concurrency through batching, not through N
    session replicas (docs/docs_en/SessionGroup.md's goal, TPU-shaped).

    Dispatch is deadline-based: a batch flushes when its bucket fills
    (`max_batch` ROWS, not requests) or its deadline passes. With
    `adaptive=True` (default) the deadline is tuned per batch from an
    EWMA of the arrival rate: under sparse traffic the batcher dispatches
    immediately (waiting can't fill the bucket, it only adds latency),
    under heavy traffic it waits just long enough to fill the bucket,
    capped by `max_wait_ms`. `adaptive=False` restores the fixed wait.

    `request_queue`/`stats`/`arrivals` let several members share one
    front (ServerGroup): every member drains the same queue and accounts
    into the same histograms.
    """

    def __init__(
        self,
        predictor: Predictor,
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        poll_updates_secs: float = 0.0,
        adaptive: bool = True,
        request_queue: Optional["queue.Queue"] = None,
        stats: Optional[ServingStats] = None,
        arrivals: Optional[_ArrivalEWMA] = None,
        reuse_cache_bytes: int = 0,
        user_cache_bytes: Optional[int] = None,
    ):
        self.predictor = predictor
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        self.adaptive = adaptive
        self.stats = stats if stats is not None else ServingStats()
        self._arrivals = arrivals if arrivals is not None else _ArrivalEWMA()
        self._q: "queue.Queue" = (
            request_queue if request_queue is not None else queue.Queue()
        )
        self._carry = None  # request deferred to lead the next batch
        self._stop = threading.Event()
        # obs plane collectors: evaluated at scrape time against live
        # objects, zero cost between scrapes. A ServerGroup's members
        # share one stats registry — re-registration replaces, so the
        # group's /metrics shows one (shared-queue) depth and the last
        # member's model identity, matching the shared-front semantics.
        r = self.stats.registry
        if r is not None:
            r.register_callback(
                "deeprec_serving_queue_depth", self._q.qsize,
                "requests waiting in the coalescing queue")
            r.register_callback(
                "deeprec_serving_model_version",
                lambda: self.predictor.version, "live snapshot version")
            r.register_callback(
                "deeprec_serving_staleness_seconds",
                lambda: time.monotonic() - self.predictor.last_poll_ok_time,
                "age of the last successful update poll round")
            r.register_callback(
                "deeprec_train_to_serve_lag_seconds",
                lambda: self.predictor.last_apply_lag_seconds,
                "trainer-commit to serving-swap age of the last applied "
                "checkpoint")
        # Compute reuse (serving/reuse.py) — OPT-IN (`reuse_cache_bytes`
        # > 0): an answer cache keyed (request fp, model version) plus,
        # for tower models, a user-tower cache keyed (user-features fp,
        # model version) that routes hits onto the candidate-only lane.
        # Off by default: caching changes the traffic a bench arm
        # measures, so every arm opts in explicitly.
        self.reuse = None
        self.user_reuse = None
        self.memo_shared = 0  # in-window memoization: requests served
        self._m_memo = None   # off a coalesced twin's computation
        if reuse_cache_bytes > 0:
            from deeprec_tpu.serving.reuse import ReuseCache

            ub = (user_cache_bytes if user_cache_bytes is not None
                  else reuse_cache_bytes)
            self.reuse = ReuseCache(
                reuse_cache_bytes, "predict", registry=r,
                version_fn=lambda: self.predictor.version)
            predictor.attach_reuse_cache(self.reuse)
            if ub > 0 and hasattr(predictor.model, "apply_with_user"):
                self.user_reuse = ReuseCache(
                    ub, "user_tower", registry=r,
                    version_fn=lambda: self.predictor.version)
                predictor.attach_reuse_cache(self.user_reuse)
            if r is not None:
                self._m_memo = r.counter(
                    "deeprec_reuse_memo_shared",
                    "in-flight requests that shared a coalesced twin's "
                    "computation inside one micro-batch window",
                    {"cache": "predict"})
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        self.retrieval = None  # RetrievalServer once attach_retrieval ran
        self._poller = None
        if poll_updates_secs > 0:
            self._poller = threading.Thread(
                target=self._poll_loop, args=(poll_updates_secs,), daemon=True
            )
            self._poller.start()

    def _poll_loop(self, secs):
        _run_poll_loop(self, self._stop, secs)

    # Sparse-traffic cutoff: skip the coalescing wait entirely once the
    # mean inter-arrival is this many windows long — the chance another
    # request lands inside the window is small enough that waiting only
    # adds latency. Closed-loop/bursty clients sit well under this (their
    # EWMA is a few windows at most), so bursts still coalesce.
    SPARSE_FACTOR = 8.0

    def _pick_wait(self, rows: int) -> float:
        """Coalescing deadline for a batch currently holding `rows` rows."""
        if rows >= self.max_batch:
            return 0.0
        if not self.adaptive:
            return self.max_wait
        tau, rows_per_req = self._arrivals.estimate()
        if tau is None:
            return self.max_wait  # no history yet: behave like fixed
        if tau >= self.SPARSE_FACTOR * self.max_wait:
            return 0.0  # sparse traffic: dispatch now, waiting can't fill
        need = (self.max_batch - rows) / max(rows_per_req, 1.0)
        return min(self.max_wait, tau * need)

    def _take(self, pending, rows, nxt) -> int:
        """Admit `nxt` into the forming batch unless it would push the row
        count past max_batch — an overflowing batch falls off the bucket
        ladder and traces a fresh arrival-timing-dependent XLA shape, the
        exact stall class this server exists to prevent — or it disagrees
        with the batch on its lane (plain / grouped / grouped-with-
        cached-user dispatch through three different traces: they cannot
        share a dispatch). The rejected request leads the NEXT batch
        instead. Returns the new row count (== max_batch signals 'batch
        is full, dispatch')."""
        if pending and (rows + nxt[1] > self.max_batch
                        or nxt[4] != pending[0][4]):
            self._carry = nxt
            return self.max_batch
        pending.append(nxt)
        return rows + nxt[1]

    def _run(self):
        while not self._stop.is_set():
            if self._carry is not None:
                first, self._carry = self._carry, None
            else:
                try:
                    first = self._q.get(timeout=0.1)
                except queue.Empty:
                    continue
            pending = [first]
            rows = first[1]
            # Opportunistic drain first: whatever is ALREADY queued rides
            # along for free — batching backlog never needs a deadline.
            while rows < self.max_batch:
                try:
                    nxt = self._q.get_nowait()
                except queue.Empty:
                    break
                rows = self._take(pending, rows, nxt)
            wait = self._pick_wait(rows)
            if wait > 0 and rows < self.max_batch:
                deadline = time.monotonic() + wait
                while rows < self.max_batch:
                    left = deadline - time.monotonic()
                    if left <= 0:
                        break
                    try:
                        nxt = self._q.get(timeout=left)
                    except queue.Empty:
                        break
                    rows = self._take(pending, rows, nxt)
            self._serve(pending)

    def _serve(
        self, pending: List[Tuple[Dict, int, "queue.Queue", float, int,
                                  Optional[tuple], Optional[bytes],
                                  Optional[bytes], Optional[tuple]]]
    ):
        t0 = time.monotonic()
        lane = pending[0][4]  # homogeneous by _take's admission rule
        for p in pending:
            self.stats.record_stage("queue", t0 - p[3])
        # In-window memoization: identical in-flight requests (same
        # answer fingerprint — same features, same lane) share ONE
        # computation and one answer instead of padding the batch with
        # duplicate rows. Only the first occurrence rides the batch; its
        # twins get the same slice. no_cache requests carry fp=None and
        # never share.
        leaders = pending
        dups: Dict[bytes, List] = {}
        if self.reuse is not None:
            seen: Dict[bytes, bool] = {}
            leaders = []
            for p in pending:
                fp = p[6]
                if fp is not None and fp in seen:
                    dups.setdefault(fp, []).append(p)
                    continue
                if fp is not None:
                    seen[fp] = True
                leaders.append(p)
        reqs = [p[0] for p in leaders]
        sizes = [p[1] for p in leaders]
        batch = {
            k: np.concatenate([np.asarray(r[k]) for r in reqs])  # noqa: DRT002 — micro-batch assembly of host request payloads before the one dispatch
            for k in reqs[0]
        }
        # Pad to a bucket from the fixed ladder so the jitted predict
        # compiles once per bucket instead of once per arrival-timing
        # dependent size — otherwise concurrent load is a compile storm.
        # Repeating the LAST row keeps a grouped batch's distinct-user
        # count unchanged (the padding user already exists).
        total = sum(sizes)
        bucket = self._bucket_for(total)
        if bucket > total:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], bucket - total, axis=0)])
                for k, v in batch.items()
            }
        self.stats.record_stage("pad", time.monotonic() - t0)
        try:
            t1 = time.monotonic()
            probs, version, uvec_rows = self._dispatch(batch, lane, leaders,
                                                       sizes, total, bucket)
            t2 = time.monotonic()
            self.stats.record_stage("device", t2 - t1)
            off = 0
            for p, n in zip(leaders, sizes):
                sl = (
                    {k: v[off : off + n] for k, v in probs.items()}
                    if isinstance(probs, dict)
                    else probs[off : off + n]
                )
                p[2].put((sl, version))
                if p[6] is not None:
                    for d in dups.get(p[6], ()):
                        d[2].put((sl, version))
                    # store a COPY: a view would pin the whole padded
                    # batch output, breaking the byte accounting
                    self.reuse.put(
                        p[6], version,
                        {k: np.ascontiguousarray(v) for k, v in sl.items()}
                        if isinstance(sl, dict)
                        else np.ascontiguousarray(sl))
                if (p[7] is not None and uvec_rows is not None
                        and self.user_reuse is not None):
                    # lead row's user vector — the whole request shares
                    # one user by the grouped-request contract
                    self.user_reuse.put(p[7], version,
                                        np.ascontiguousarray(uvec_rows[off]))
                off += n
            shared = len(pending) - len(leaders)
            if shared:
                self.memo_shared += shared
                if self._m_memo is not None:
                    self._m_memo.inc(shared)
            t3 = time.monotonic()
            self.stats.record_stage("post", t3 - t2)
            self.stats.record_batch(len(pending), total)
            if obs_trace.tracing_enabled():
                # Retrospective per-request stage spans off the timings
                # already accounted above (tracing adds emission, never a
                # second clock): every sampled request in the batch gets
                # its own queue/pad/device/post children under its
                # dispatch span. monotonic -> wall via one offset.
                wall = time.time() - t3
                for p in pending:
                    t_enq, ctx = p[3], p[5]
                    if ctx is None:
                        continue
                    for nm, a, b in (("stage_queue", t_enq, t0),
                                     ("stage_pad", t0, t1),
                                     ("stage_device", t1, t2),
                                     ("stage_post", t2, t3)):
                        obs_trace.emit(nm, "serving", wall + a, wall + b,
                                       ctx=obs_trace.child(ctx),
                                       parent=ctx[1])
        except Exception as e:
            self.stats.record_error(len(pending))
            for p in pending:
                p[2].put(e)

    def _dispatch(self, batch, lane: int, leaders, sizes, total: int,
                  bucket: int):
        """One device dispatch for the assembled batch: per lane, the
        plain trace, the grouped trace (returning per-row user vectors
        when the user-tower cache wants them), or the candidate-only
        trace fed by cached user vectors. Returns (probs, version,
        per-row user vectors or None). Lane 2 falls back to the full
        grouped evaluation whenever the cached vectors' version no
        longer matches the snapshot that answered — a publish between
        cache lookup and dispatch must never produce a mixed-version
        answer."""
        if lane == 0:
            probs, version = self.predictor.predict_versioned(batch)
            return probs, version, None
        if lane == 2:
            uvers = {p[8][1] for p in leaders}
            if len(uvers) == 1:
                urows = np.concatenate([
                    np.broadcast_to(
                        np.asarray(p[8][0], np.float32).reshape(1, -1),
                        (n, np.asarray(p[8][0]).size))
                    for p, n in zip(leaders, sizes)
                ])
                if bucket > total:
                    urows = np.concatenate(
                        [urows, np.repeat(urows[-1:], bucket - total,
                                          axis=0)])
                probs, version = self.predictor.predict_with_user_versioned(
                    batch, urows)
                if version == next(iter(uvers)):
                    return probs, version, None
            probs, version = self.predictor.predict_versioned(
                batch, group_users=True)
            return probs, version, None
        if self.user_reuse is not None and any(p[7] is not None
                                               for p in leaders):
            probs, uvec_rows, version = (
                self.predictor.predict_grouped_uvec_versioned(batch))
            return probs, version, uvec_rows
        probs, version = self.predictor.predict_versioned(
            batch, group_users=True)
        return probs, version, None

    def _buckets(self) -> List[int]:
        """The ONE bucket ladder (shared by _serve and warmup — any change
        here keeps them in lockstep): powers of two from 8, capped by
        max_batch, which is always the last (and heaviest) bucket."""
        sizes = []
        b = 8
        while b < self.max_batch:
            sizes.append(b)
            b <<= 1
        sizes.append(self.max_batch)
        return sizes

    def _bucket_for(self, total: int) -> int:
        for b in self._buckets():
            if total <= b:
                return b
        return total  # > max_batch: serve as-is (caller bounded by queue)

    def warmup(self, example: Dict[str, np.ndarray],
               group_users: bool = False) -> int:
        """Precompile every batch bucket from one example row, so the first
        production burst never waits on XLA. Returns the number of buckets
        compiled. The serving counterpart of the reference's warmup
        requests (Processor.md warmup section). Each bucket batch is also
        registered with the predictor, so every future model update
        re-warms the same ladder against the incoming state BEFORE the
        snapshot swap (warm-before-swap). `group_users=True` additionally
        compiles the sample-aware grouped trace per bucket (one-repeated-
        user batches: the G=1 group bucket — live traffic's larger
        distinct-user buckets compile on first sight, bounded by the
        power-of-two group ladder)."""
        one = {k: np.asarray(v)[:1] for k, v in example.items()}  # noqa: DRT002 — warmup path: builds the bucket ladder from one host example
        sizes = self._buckets()
        for size in sizes:
            batch = {
                k: np.concatenate([v] * size, axis=0) for k, v in one.items()
            }
            self.predictor.predict(batch)
            if group_users:
                self.predictor.predict(batch, group_users=True)
                if self.user_reuse is not None:
                    # the user-tower-cache lanes: compile the grouped-
                    # with-uvec trace (population) and the candidate-only
                    # trace (hits) at this bucket too
                    _, uv, _ = self.predictor.predict_grouped_uvec_versioned(
                        batch)
                    self.predictor.predict_with_user_versioned(batch, uv)
            self.predictor.register_warm_batch(batch)
        return len(sizes)

    def submit(self, features: Dict[str, np.ndarray],
               group_users: bool = False,
               trace_ctx: Optional[tuple] = None,
               no_cache: bool = False) -> "queue.Queue":
        """Enqueue one request onto the coalescing queue and return the
        reply queue (a one-shot future: `.get()` yields `(result,
        model_version)` or an Exception). The non-blocking half of
        `request_versioned` — frontends that multiplex many in-flight
        requests (the socket tier) use this directly.

        `group_users=True` marks the request for sample-aware compression:
        the batcher coalesces it ONLY with other grouped requests, so one
        device batch carries many `<user, N items>` requests and the user
        tower runs once per distinct user across all of them. Validated
        here (not at dispatch) so a tower-less model fails this request
        alone, never a coalesced batch of strangers.

        With compute reuse enabled an answer-cache hit at the live model
        version replies right here — no enqueue, no dispatch; a grouped
        request whose user vector is cached rides the candidate-only
        lane instead. `no_cache=True` (the canary/parity probe contract)
        bypasses reads, writes AND in-window memo sharing: the request
        is a full evaluation, always."""
        if group_users and not hasattr(self.predictor.model,
                                       "apply_with_user"):
            raise BadRequest(
                f"{type(self.predictor.model).__name__} has no user/item "
                "tower split (needs user_feats/user_vector/apply_with_user)"
            )
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        rows = (
            int(np.asarray(next(iter(features.values()))).shape[0])  # noqa: DRT002 — host row count of the incoming request payload
            if features else 0
        )
        # Queue-item lanes (homogeneous per batch, _take enforces):
        # 0 plain, 1 grouped, 2 grouped-with-cached-user-vector. fp keys
        # the answer cache (None: reuse off or no_cache), ufp marks a
        # user-tower entry to POPULATE after dispatch, uaux carries a
        # cached (user vector, version) for lane 2.
        lane = 1 if group_users else 0
        fp = ufp = uaux = None
        if self.reuse is not None and not no_cache:
            from deeprec_tpu.serving import reuse as _reuse

            fp = _reuse.request_fingerprint(
                features, extra=b"g" if group_users else b"")
            hit = self.reuse.get_current(fp)
            if hit is not None:
                reply.put(hit)  # (answer, version) — read atomically
                return reply
            if group_users and self.user_reuse is not None:
                ufp = _reuse.request_fingerprint(
                    features, names=list(self.predictor.model.user_feats))
                uhit = self.user_reuse.get_current(ufp)
                if uhit is not None:
                    uaux, ufp, lane = uhit, None, 2
        t0 = time.monotonic()
        self._arrivals.note(t0, rows)
        self._q.put((features, rows, reply, t0, lane, trace_ctx,
                     fp, ufp, uaux))
        return reply

    def attach_retrieval(self, engine, **kwargs) -> "object":
        """Wire a full-corpus RetrievalEngine behind this server's stats:
        builds the coalescing RetrievalServer for the lane (one corpus
        sweep per coalesced user batch) and exposes
        `retrieve_versioned`. Returns the RetrievalServer."""
        from deeprec_tpu.serving.retrieval import RetrievalServer

        self.retrieval = RetrievalServer(engine, stats=self.stats, **kwargs)
        return self.retrieval

    def retrieve_versioned(self, features: Dict[str, np.ndarray], k: int,
                           timeout: float = 30.0, no_cache: bool = False):
        """Full-corpus top-k for each user row (serving/retrieval.py) —
        the retrieval lane's analog of request_versioned."""
        if self.retrieval is None:
            raise BadRequest("retrieval not enabled on this server")
        return self.retrieval.request_versioned(features, k, timeout=timeout,
                                                no_cache=no_cache)

    def request(self, features: Dict[str, np.ndarray], timeout: float = 30.0,
                group_users: bool = False):
        """Blocking predict for one (mini-)request — the process() call."""
        return self.request_versioned(features, timeout, group_users)[0]

    def request_versioned(
        self, features: Dict[str, np.ndarray], timeout: float = 30.0,
        group_users: bool = False, trace_ctx: Optional[tuple] = None,
        no_cache: bool = False,
    ):
        """(result, model_version) — the version the whole request was
        served from (one snapshot; coalesced neighbors share it, so a
        grouped request's N candidate scores are stamped with ONE
        version even when strangers' users rode the same device batch).

        `trace_ctx` (or the calling thread's open span — e.g. the HTTP
        edge's) makes this request a sampled trace: a `dispatch` span
        here plus the stage spans the batcher emits under it."""
        t0 = time.monotonic()
        sp = obs_trace.span("dispatch", "serving", ctx=trace_ctx)
        with sp:
            reply = self.submit(features, group_users=group_users,
                                trace_ctx=sp.ctx, no_cache=no_cache)
            out = reply.get(timeout=timeout)
        self.stats.record_stage("e2e", time.monotonic() - t0)
        if isinstance(out, Exception):
            raise out
        return out

    def stats_snapshot(self) -> Dict:
        """Live serving stats + model identity — the `/v1/stats` body.
        The ``window`` section is the autoscaler's load signal (PR 11
        ring buffers, NOT lifetime aggregates): e2e p99 over the
        trailing 60 s plus the coalescing queue's instantaneous depth."""
        out = self.stats.snapshot()
        p = self.predictor
        out["model"] = {
            "version": p.version,
            "step": p.step,
            "updates": p.update_count,
            "last_update_ms": p.last_update_ms,
        }
        out["window"] = {
            "e2e_p99_ms": self.stats.window_p99_ms("e2e"),
            "queue_depth": self._q.qsize(),
            "window_seconds": 60,
        }
        out["health"] = p.health()
        out["residency"] = p.residency_info()
        if self.retrieval is not None:
            out["retrieval_corpus"] = self.retrieval.engine.sweep_info()
        reuse = {}
        if self.reuse is not None:
            reuse["predict"] = self.reuse.snapshot()
        if self.user_reuse is not None:
            reuse["user_tower"] = self.user_reuse.snapshot()
        if (self.retrieval is not None
                and getattr(self.retrieval, "reuse", None) is not None):
            reuse["retrieve"] = self.retrieval.reuse.snapshot()
        if reuse:
            reuse["memo_shared"] = self.memo_shared
            out["reuse"] = reuse
        return out

    def metrics_snapshot(self) -> Dict:
        return _server_metrics_snapshot(self.stats)

    def metrics_text(self) -> str:
        """Prometheus text for `GET /metrics` on this server."""
        return obs_metrics.render_snapshot(self.metrics_snapshot())

    def close(self):
        self._stop.set()
        if self.retrieval is not None:
            self.retrieval.close()
        self._worker.join(timeout=2)


class _GroupPredictor:
    """Predictor facade over a replica group: reads delegate to replica 0,
    `poll_updates` rolls across EVERY replica (the single checkpoint
    watcher the group shares). Lets ServerGroup slot into anything built
    for ModelServer (HttpServer routes use `server.predictor`)."""

    def __init__(self, members: List[Predictor]):
        self._members = members

    def __getattr__(self, name):
        return getattr(self._members[0], name)

    def poll_updates(self) -> bool:
        # Rolling update: replicas refresh one at a time, the others keep
        # serving the previous version — SessionGroup's model-update story
        # without a serving gap. Each member's refresh is itself
        # zero-stall (shadow build + warm + swap).
        changed = False
        for m in self._members:
            changed = bool(m.poll_updates()) or changed
        return changed

    def reload(self) -> None:
        for m in self._members:
            m.reload()

    def model_info(self) -> Dict:
        info = self._members[0].model_info()
        info["replicas"] = len(self._members)
        return info

    def health(self) -> Dict:
        """Worst member's health — a wedged replica is the group's
        status, not an average."""
        healths = [m.health() for m in self._members]
        worst = max(healths, key=lambda h: h["staleness_seconds"])
        if any(h["status"] != "ok" for h in healths):
            worst = next(h for h in healths if h["status"] != "ok")
        worst["replicas"] = len(self._members)
        return worst


class ServerGroup:
    """N serving replicas behind ONE shared request queue — the
    DirectSessionGroup analog (direct_session_group.h:28,
    docs/docs_en/SessionGroup.md). One member is pinned per DISTINCT
    device, so on a multi-device host the members drain the shared queue
    in parallel, each dispatching to its own chip; on a single-device
    host the group degrades to a single member (requested replicas are
    capped at the device count) — N members time-slicing one backend is
    strictly worse than one member batching for it, which is exactly the
    negative scaling the old least-loaded/per-member-queue design showed
    (SERVING_BENCH round 5: group-4 at 336 rps vs 719 single).

    The shared queue replaces least-loaded routing: work is pulled by
    whichever member is free (no routing decision can back the wrong
    queue), and every member accounts into one ServingStats."""

    def __init__(self, model, ckpt_dir: str, *, replicas: int = 2,
                 devices=None, stores: Optional[Dict] = None,
                 max_batch: int = 256, max_wait_ms: float = 2.0,
                 poll_updates_secs: float = 0.0, adaptive: bool = True,
                 quantize=None):
        if devices is None:
            avail = jax.local_devices()
            devices = avail[: max(1, min(replicas, len(avail)))]
        else:
            # One member per DISTINCT device even for explicit lists (the
            # old API modulo-duplicated devices; N members sharing one
            # backend is the anti-scaling regime this class exists to
            # prevent) — order-preserving dedup.
            devices = list(dict.fromkeys(devices))
        self.stats = ServingStats()
        self._arrivals = _ArrivalEWMA()
        self._q: "queue.Queue" = queue.Queue()
        self.members = [
            ModelServer(
                Predictor(model, ckpt_dir, stores=stores, device=d,
                          quantize=quantize),
                max_batch=max_batch, max_wait_ms=max_wait_ms,
                adaptive=adaptive, request_queue=self._q, stats=self.stats,
                arrivals=self._arrivals,
            )
            for d in devices
        ]
        self.predictor = _GroupPredictor([s.predictor for s in self.members])
        self._stop = threading.Event()
        self._poller = None
        if poll_updates_secs > 0:
            self._poller = threading.Thread(
                target=self._poll_loop, args=(poll_updates_secs,),
                daemon=True,
            )
            self._poller.start()

    def _poll_loop(self, secs: float):
        _run_poll_loop(self, self._stop, secs)

    def request(self, features: Dict[str, np.ndarray], timeout: float = 30.0,
                group_users: bool = False):
        # Any member's request() enqueues onto the SHARED queue; whichever
        # member is free serves it.
        return self.members[0].request(features, timeout=timeout,
                                       group_users=group_users)

    def request_versioned(
        self, features: Dict[str, np.ndarray], timeout: float = 30.0,
        group_users: bool = False, trace_ctx: Optional[tuple] = None,
        no_cache: bool = False,
    ):
        return self.members[0].request_versioned(
            features, timeout=timeout, group_users=group_users,
            trace_ctx=trace_ctx, no_cache=no_cache)

    def submit(self, features: Dict[str, np.ndarray],
               group_users: bool = False,
               trace_ctx: Optional[tuple] = None,
               no_cache: bool = False) -> "queue.Queue":
        return self.members[0].submit(features, group_users=group_users,
                                      trace_ctx=trace_ctx,
                                      no_cache=no_cache)

    def warmup(self, example: Dict[str, np.ndarray],
               group_users: bool = False) -> int:
        return sum(s.warmup(example, group_users=group_users)
                   for s in self.members)

    def stats_snapshot(self) -> Dict:
        out = self.stats.snapshot()
        ps = [s.predictor for s in self.members]
        out["replicas"] = len(self.members)
        out["model"] = {
            "version": ps[0].version,
            "step": ps[0].step,
            "updates": sum(p.update_count for p in ps),
            "last_update_ms": max(p.last_update_ms for p in ps),
        }
        # Group health: the worst member speaks for the group — the SAME
        # selection /healthz uses (_GroupPredictor.health), so the two
        # watchdog surfaces can never disagree about the group's status.
        out["health"] = self.predictor.health()
        out["residency"] = ps[0].residency_info()
        return out

    def metrics_snapshot(self) -> Dict:
        return _server_metrics_snapshot(self.stats)

    def metrics_text(self) -> str:
        return obs_metrics.render_snapshot(self.metrics_snapshot())

    def close(self):
        self._stop.set()
        for s in self.members:
            s.close()
