"""Serving: jit-compiled predictor with hot-swapped full/delta model updates.

Parity with DeepRec's serving stack (SURVEY.md §2.7/§3.4) re-cut for TPU:
  * Processor initialize()/process()  -> Predictor(model, ckpt_dir) /
    predict(batch) — one jitted readonly forward, no training machinery.
  * ModelInstanceMgr's FullModelUpdate/DeltaModelUpdate background polling
    (model_instance.h:44-232) -> poll_updates(): picks up new full
    checkpoints and replays incremental deltas IN PLACE on the live sparse
    tables, then atomically swaps the state reference.
  * SessionGroup's N-sessions concurrency (direct_session_group.h) ->
    ModelServer: a micro-batching queue in front of the jitted function.
    JAX dispatch is thread-safe and XLA executes one program at a time per
    device, so the TPU-native equivalent of "N sessions" is request
    coalescing into full batches, not N executors.
"""
from __future__ import annotations

import os
import queue
import threading
import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax

from deeprec_tpu.optim.sparse import GradientDescent
from deeprec_tpu.training.checkpoint import CheckpointManager
from deeprec_tpu.training.trainer import Trainer, TrainState


class BadRequest(ValueError):
    """Client-side request error, with a structured payload for frontends
    that return machine-readable error bodies (HTTP, C ABI)."""

    def __init__(self, message: str, **details):
        super().__init__(message)
        self.details = {"error": message, **details}


def parse_features(predictor: "Predictor", feats: Dict) -> Dict[str, np.ndarray]:
    """Validate + coerce a wire-format feature dict (JSON-shaped lists or
    arrays) into a model batch. Shared by every frontend (HTTP, C ABI):
    validates BEFORE the coalescing queue so one bad request can't poison
    the requests batched with it. Raises ValueError with a client-facing
    message.

    Rules: id features pad/trim ragged bags to the feature's declared
    max_len with its pad value (one compiled shape per feature, not one per
    organic list length); dense features become [B, W] float32; all
    features must agree on the row count."""
    if not isinstance(feats, dict) or not feats:
        raise BadRequest("missing 'features' object")
    dtypes = predictor.feature_dtypes
    unknown = sorted(set(feats) - set(dtypes))
    missing = sorted(set(dtypes) - set(feats))
    if unknown or missing:
        raise BadRequest("feature-name mismatch", unknown=unknown,
                         missing=missing)
    specs = {f.name: f for f in predictor._trainer.sparse_specs}
    batch = {}
    for k, v in feats.items():
        want = dtypes[k]
        try:
            if want.kind in "iu":
                f = specs[k]
                L = f.max_len
                if L and isinstance(v, list) and v and isinstance(v[0], list):
                    rows = [(r + [f.pad_value] * (L - len(r)))[:L] for r in v]
                    arr = np.asarray(rows, want)
                else:
                    arr = np.asarray(v).astype(want)
                    if L:
                        if arr.ndim == 1:
                            arr = arr[:, None]
                        if arr.shape[1] < L:
                            pad = np.full(
                                (arr.shape[0], L - arr.shape[1]), f.pad_value,
                                want,
                            )
                            arr = np.concatenate([arr, pad], axis=1)
                        else:
                            arr = arr[:, :L]
            else:
                arr = np.asarray(v).astype(np.float32)
                if arr.ndim == 1:
                    arr = arr[:, None]  # dense features are [B, W]
        except (TypeError, ValueError) as e:
            # numpy coercion of garbage values raises TypeError — still the
            # CLIENT's fault, so surface it as a request error, not a crash
            raise BadRequest(f"feature {k!r}: cannot coerce to {want}: {e}",
                             feature=k) from e
        batch[k] = arr
    rows = {k: a.shape[0] for k, a in batch.items()}
    if len(set(rows.values())) > 1:
        raise BadRequest("inconsistent feature row counts", rows=rows)
    return batch


class Predictor:
    """Load-latest-and-serve. Thread-safe; updates swap atomically.

    `stores` optionally maps table names to a feature-store object with
    ``get(keys) -> (values, freq, version, found)`` (HostKV signature) —
    the read-through analog of the reference's Redis feature store
    (serving/processor/storage/redis_feature_store.h:18): keys missing
    from the device table serve the store's row instead of the
    initializer value.
    """

    def __init__(self, model, ckpt_dir: str, stores: Optional[Dict] = None,
                 device=None):
        self.model = model
        # Serving needs no optimizer; slot-less sparse opt keeps restore lean
        # (checkpointed slot arrays are skipped when the template has none).
        self._trainer = Trainer(model, GradientDescent(), optax.identity())
        self._ck = CheckpointManager(ckpt_dir, self._trainer)
        self._state: Optional[TrainState] = None
        # Replica pinning (ServerGroup): committing the state to `device`
        # makes every jitted predict follow it there — N replicas on N
        # devices serve concurrently (uncommitted request arrays follow
        # the committed state under JAX placement rules).
        self._device = device
        self._applied: set = set()
        # Reentrant: poll_updates holds it across its check-then-act (a
        # concurrent full reload must not interleave with a delta replay)
        # and may call reload() which takes it again.
        self._lock = threading.RLock()
        self.stores = dict(stores or {})
        self._predict_step = jax.jit(self._predict_impl)
        self._predict_grouped_step = jax.jit(
            self._predict_grouped_impl, static_argnums=2
        )
        self._forward_step = jax.jit(self._forward_impl)
        self._lookup_step = jax.jit(self._lookup_views)
        self.reload()

    # ------------------------------------------------------------- updates

    def reload(self) -> None:
        """Full reload from the latest checkpoint chain (FullModelUpdate)."""
        with self._lock:
            # List BEFORE restoring: a delta landing mid-restore then stays
            # un-applied and is picked up by the next poll (replaying a delta
            # restore() already consumed is idempotent, missing one is not).
            dirs = set(self._dirs())
            state = self._ck.restore()
            if self._device is not None:
                state = jax.device_put(state, self._device)
            self._state = state
            self._applied = dirs

    def _dirs(self) -> List[str]:
        fulls = self._ck._list("full")
        if not fulls:
            return []
        out = [f"full-{fulls[-1]}"]
        out += [f"incr-{s}" for s in self._ck._list("incr") if s > fulls[-1]]
        return out

    def poll_updates(self) -> bool:
        """Apply anything new: a newer full checkpoint triggers a full
        reload; new deltas replay onto the live state (DeltaModelUpdate).
        Returns True if the model changed. Safe to call concurrently (HTTP
        /v1/reload + background poller): the whole check-then-act runs
        under the lock, so a stale delta can never replay over a newer
        full reload."""
        with self._lock:
            new = [d for d in self._dirs() if d not in self._applied]
            if not new:
                return False
            if any(d.startswith("full-") for d in new):
                self.reload()
                return True
            state = self._state
            last_step = int(state.step)
            for d in sorted(new, key=lambda s: int(s.split("-")[1])):
                state = self._ck._apply_ckpt(
                    state, os.path.join(self._ck.dir, d), load_dense=True
                )
                last_step = max(last_step, int(d.split("-")[1]))
                self._applied.add(d)
            state = TrainState(
                step=jnp.asarray(last_step, jnp.int32),
                tables=state.tables,
                dense=state.dense,
                opt_state=state.opt_state,
            )
            if self._device is not None:
                state = jax.device_put(state, self._device)
            self._state = state
        return True

    # ------------------------------------------------------------- predict

    def predict(self, batch: Dict[str, np.ndarray], group_users: bool = False):
        """Probabilities for one batch (dict keyed per task for MTL).
        Label-free: the serving path runs lookup + forward + sigmoid only —
        no loss, no dummy labels, no training machinery.

        group_users=True enables serving-side sample-aware compression for
        tower models (the reference's graph-optimizer rewrite,
        serving/processor/framework/graph_optimizer.cc, spec
        docs/docs_en/Sample-awared-Graph-Compression.md): rows of a
        ``<user, N items>`` batch that share identical user-feature values
        run the user tower ONCE per distinct user (G rows instead of B)
        and broadcast the user vector. Requires the model to expose
        ``user_feats`` / ``user_vector`` / ``apply_with_user`` (DSSM
        does). Outputs are row-for-row identical to the plain path.
        Ignores feature stores (read-through is a per-row correction that
        the grouped trace doesn't carry)."""
        state = self._state  # atomic reference read
        if group_users:
            if not hasattr(self.model, "apply_with_user"):
                raise ValueError(
                    f"{type(self.model).__name__} has no user/item tower "
                    "split (needs user_feats/user_vector/apply_with_user)"
                )
            cols = np.concatenate(
                [
                    np.asarray(batch[n]).reshape(len(np.asarray(batch[n])), -1)
                    for n in self.model.user_feats
                ],
                axis=1,
            )
            b = cols.shape[0]
            # Bucket BOTH shapes to powers of two — one compile per
            # (row-bucket, group-bucket), not one per client batch size.
            # Pad rows by repeating the last row: its user already exists,
            # so the distinct-user count is unchanged.
            bp = 1 << max(b - 1, 0).bit_length()
            distinct = len(np.unique(cols, axis=0))
            g = min(1 << max(distinct - 1, 0).bit_length(), bp)
            def pad(v):
                v = np.asarray(v)
                if bp > b:
                    v = np.concatenate(
                        [v, np.repeat(v[-1:], bp - b, axis=0)]
                    )
                return jnp.asarray(v)

            batch = {k: pad(v) for k, v in batch.items()}
            probs = self._predict_grouped_step(state, batch, g)
            return jax.tree.map(lambda a: np.asarray(a)[:b], probs)
        batch = {k: jnp.asarray(v) for k, v in batch.items()}
        if self.stores:
            probs = self._predict_with_stores(state, batch)
        else:
            probs = self._predict_step(state, batch)
        return jax.tree.map(np.asarray, probs)

    def _lookup_views(self, state, batch):
        """Readonly lookup pass: feature -> (unique embs, inverse, mask)
        plus per-bundle results (slot_ix/uids for the store fallback)."""
        return self._trainer.forward_views(state, batch)

    def _predict_impl(self, state, batch):
        views, _ = self._lookup_views(state, batch)
        return self._trainer.probs_from_views(state, views, batch)[1]

    def _predict_grouped_impl(self, state, batch, num_groups: int):
        """Sample-aware compressed forward: user tower on G deduped rows,
        item tower + scoring on all B rows. Group identity is exact (id
        columns compared row-wise, not hashed), so equal outputs are
        guaranteed; apply_grouped returns NaN rows on group overflow,
        which cannot happen because predict() sizes num_groups from the
        host-side distinct count."""
        from deeprec_tpu import nn as _nn

        m = self.model
        views, _ = self._lookup_views(state, batch)
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._trainer._build_inputs(embs, views, batch)
        ucols = jnp.concatenate(
            [batch[n].reshape(batch[n].shape[0], -1) for n in m.user_feats],
            axis=1,
        )
        _, gids = jnp.unique(
            ucols, axis=0, size=num_groups, return_inverse=True
        )
        uvec = _nn.apply_grouped(
            lambda ins: m.user_vector(state.dense, ins),
            inputs,
            gids.reshape(-1),
            num_groups,
        )
        out = m.apply_with_user(state.dense, uvec, inputs)
        if isinstance(out, dict):
            return {k: jax.nn.sigmoid(v) for k, v in out.items()}
        return jax.nn.sigmoid(out)

    def _forward_impl(self, state, views, batch):
        return self._trainer.probs_from_views(state, views, batch)[1]

    def _predict_with_stores(self, state, batch):
        """Read-through path: jitted lookup, host-side store correction of
        missing keys, jitted forward. Two dispatches instead of one — the
        price of consulting an external store, paid only when configured."""
        views, bundle_res = self._lookup_step(state, batch)
        views = dict(views)
        for bname, b in self._trainer.bundles.items():
            res = bundle_res[bname]
            for k, f in enumerate(b.features):
                tname = self._resolve_table_name(f)
                store = self.stores.get(tname)
                if store is None:
                    continue
                r = (
                    jax.tree.map(lambda a: a[k], res)
                    if b.stacked
                    else res[f.name]
                )
                emb, inverse, mask = views[f.name]
                missing = np.asarray(r.slot_ix < 0) & np.asarray(r.valid)
                if not missing.any():
                    continue
                keys = np.asarray(r.uids)[missing].astype(np.int64)
                rows, _, _, found = store.get(keys)
                if not found.any():
                    continue
                emb = np.asarray(emb).copy()
                mix = np.nonzero(missing)[0][found]
                emb[mix] = rows[found].astype(emb.dtype)
                views[f.name] = (jnp.asarray(emb), inverse, mask)
        return self._forward_step(state, views, batch)

    @staticmethod
    def _resolve_table_name(f):
        from deeprec_tpu.features import resolve_table_name

        return resolve_table_name(f)

    @property
    def feature_dtypes(self) -> Dict[str, "np.dtype"]:
        """Expected numpy dtype per input feature (sparse ids use their
        table's key_dtype; dense features are float32) — lets frontends
        coerce JSON payloads without truncating 64-bit ids."""
        from deeprec_tpu import features as fcol

        out = {}
        cfgs = {n: t.cfg for n, t in self._trainer.tables.items()}
        for f in self._trainer.sparse_specs:
            out[f.name] = np.dtype(cfgs[fcol.resolve_table_name(f)].key_dtype)
        for f in self._trainer.dense_specs:
            out[f.name] = np.dtype(np.float32)
        return out

    @property
    def step(self) -> int:
        return int(self._state.step)

    def model_info(self) -> Dict:
        """get_serving_model_info parity."""
        state = self._state  # one snapshot: no torn step/sizes mix under
        sizes = {}  # a concurrent hot-swap
        for name, t in self._trainer.tables.items():
            sizes[name] = int(t.size(self._trainer.table_state(state, name)))
        return {"step": int(state.step), "table_sizes": sizes}


def _run_poll_loop(owner, stop: threading.Event, secs: float) -> None:
    """Shared checkpoint-watch loop (ModelServer + ServerGroup): poll
    `owner.predictor` for updates every `secs`, surfacing failures via a
    consecutive-failure counter + log — a corrupt checkpoint must not
    silently freeze the served model."""
    while not stop.is_set():
        time.sleep(secs)
        try:
            owner.predictor.poll_updates()
            owner.update_failures = 0
        except Exception as e:
            owner.update_failures = getattr(owner, "update_failures", 0) + 1
            import logging

            logging.getLogger(__name__).warning(
                "model update poll failed (%d consecutive): %s",
                owner.update_failures, e,
            )


class ModelServer:
    """Micro-batching front: coalesce single requests into device batches.

    The SessionGroup analog — concurrency through batching, not through N
    session replicas (docs/docs_en/SessionGroup.md's goal, TPU-shaped).
    """

    def __init__(
        self,
        predictor: Predictor,
        max_batch: int = 256,
        max_wait_ms: float = 2.0,
        poll_updates_secs: float = 0.0,
    ):
        self.predictor = predictor
        self.max_batch = max_batch
        self.max_wait = max_wait_ms / 1000.0
        self._q: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._worker = threading.Thread(target=self._run, daemon=True)
        self._worker.start()
        self._poller = None
        if poll_updates_secs > 0:
            self._poller = threading.Thread(
                target=self._poll_loop, args=(poll_updates_secs,), daemon=True
            )
            self._poller.start()

    def _poll_loop(self, secs):
        _run_poll_loop(self, self._stop, secs)

    def _run(self):
        while not self._stop.is_set():
            try:
                first = self._q.get(timeout=0.1)
            except queue.Empty:
                continue
            pending = [first]
            deadline = time.monotonic() + self.max_wait
            while len(pending) < self.max_batch:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                try:
                    pending.append(self._q.get(timeout=left))
                except queue.Empty:
                    break
            self._serve(pending)

    def _serve(self, pending: List[Tuple[Dict, "queue.Queue"]]):
        reqs = [r for r, _ in pending]
        sizes = [next(iter(r.values())).shape[0] for r in reqs]
        batch = {
            k: np.concatenate([np.asarray(r[k]) for r in reqs])
            for k in reqs[0]
        }
        # Pad to a bucket from the fixed ladder so the jitted predict
        # compiles once per bucket instead of once per arrival-timing
        # dependent size — otherwise concurrent load is a compile storm.
        total = sum(sizes)
        bucket = self._bucket_for(total)
        if bucket > total:
            batch = {
                k: np.concatenate([v, np.repeat(v[-1:], bucket - total, axis=0)])
                for k, v in batch.items()
            }
        try:
            probs = self.predictor.predict(batch)
            off = 0
            for (_, reply), n in zip(pending, sizes):
                sl = (
                    {k: v[off : off + n] for k, v in probs.items()}
                    if isinstance(probs, dict)
                    else probs[off : off + n]
                )
                reply.put(sl)
                off += n
        except Exception as e:
            for _, reply in pending:
                reply.put(e)

    def _buckets(self) -> List[int]:
        """The ONE bucket ladder (shared by _serve and warmup — any change
        here keeps them in lockstep): powers of two from 8, capped by
        max_batch, which is always the last (and heaviest) bucket."""
        sizes = []
        b = 8
        while b < self.max_batch:
            sizes.append(b)
            b <<= 1
        sizes.append(self.max_batch)
        return sizes

    def _bucket_for(self, total: int) -> int:
        for b in self._buckets():
            if total <= b:
                return b
        return total  # > max_batch: serve as-is (caller bounded by queue)

    def warmup(self, example: Dict[str, np.ndarray]) -> int:
        """Precompile every batch bucket from one example row, so the first
        production burst never waits on XLA. Returns the number of buckets
        compiled. The serving counterpart of the reference's warmup
        requests (Processor.md warmup section)."""
        one = {k: np.asarray(v)[:1] for k, v in example.items()}
        sizes = self._buckets()
        for size in sizes:
            batch = {
                k: np.concatenate([v] * size, axis=0) for k, v in one.items()
            }
            self.predictor.predict(batch)
        return len(sizes)

    def request(self, features: Dict[str, np.ndarray], timeout: float = 30.0):
        """Blocking predict for one (mini-)request — the process() call."""
        reply: "queue.Queue" = queue.Queue(maxsize=1)
        self._q.put((features, reply))
        out = reply.get(timeout=timeout)
        if isinstance(out, Exception):
            raise out
        return out

    def close(self):
        self._stop.set()
        self._worker.join(timeout=2)


class _GroupPredictor:
    """Predictor facade over a replica group: reads delegate to replica 0,
    `poll_updates` rolls across EVERY replica (the single checkpoint
    watcher the group shares). Lets ServerGroup slot into anything built
    for ModelServer (HttpServer routes use `server.predictor`)."""

    def __init__(self, members: List[Predictor]):
        self._members = members

    def __getattr__(self, name):
        return getattr(self._members[0], name)

    def poll_updates(self) -> bool:
        # Rolling update: replicas refresh one at a time, the others keep
        # serving the previous version — SessionGroup's model-update story
        # without a serving gap.
        changed = False
        for m in self._members:
            changed = bool(m.poll_updates()) or changed
        return changed

    def reload(self) -> None:
        for m in self._members:
            m.reload()

    def model_info(self) -> Dict:
        info = self._members[0].model_info()
        info["replicas"] = len(self._members)
        return info


class ServerGroup:
    """N serving replicas sharing one checkpoint watcher — the
    DirectSessionGroup analog (direct_session_group.h:28,
    docs/docs_en/SessionGroup.md). Each replica is a full ModelServer
    (own coalescing queue + worker thread) whose Predictor state is
    committed to its own device; requests go to the least-loaded replica.

    On a multi-device host this is true device parallelism; on a single
    chip it still removes host-side head-of-line blocking (request
    parsing/concat of a big batch no longer stalls every later arrival —
    the reference's per-session threadpool rationale).
    """

    def __init__(self, model, ckpt_dir: str, *, replicas: int = 2,
                 devices=None, stores: Optional[Dict] = None,
                 max_batch: int = 256, max_wait_ms: float = 2.0,
                 poll_updates_secs: float = 0.0):
        if devices is None:
            avail = jax.local_devices()
            devices = [avail[i % len(avail)] for i in range(replicas)]
        self.members = [
            ModelServer(
                Predictor(model, ckpt_dir, stores=stores, device=d),
                max_batch=max_batch, max_wait_ms=max_wait_ms,
            )
            for d in devices
        ]
        self.predictor = _GroupPredictor([s.predictor for s in self.members])
        self._rr = 0
        self._stop = threading.Event()
        self._poller = None
        if poll_updates_secs > 0:
            self._poller = threading.Thread(
                target=self._poll_loop, args=(poll_updates_secs,),
                daemon=True,
            )
            self._poller.start()

    def _poll_loop(self, secs: float):
        _run_poll_loop(self, self._stop, secs)

    def _pick(self) -> "ModelServer":
        """Least-loaded replica; round-robin breaks ties so idle groups
        still spread arrivals across devices."""
        n = len(self.members)
        self._rr = (self._rr + 1) % n
        order = self.members[self._rr:] + self.members[: self._rr]
        return min(order, key=lambda s: s._q.qsize())

    def request(self, features: Dict[str, np.ndarray], timeout: float = 30.0):
        return self._pick().request(features, timeout=timeout)

    def warmup(self, example: Dict[str, np.ndarray]) -> int:
        return sum(s.warmup(example) for s in self.members)

    def close(self):
        self._stop.set()
        for s in self.members:
            s.close()
