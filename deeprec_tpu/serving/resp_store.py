"""RESP (Redis protocol) feature store client — drop-in against a real
Redis deployment of the reference's feature store.

The reference serves sparse embedding rows out of Redis
(serving/processor/storage/redis_feature_store.h:18,85 — LocalRedis /
ClusterRedis over hiredis). This module speaks the same wire scheme with
zero dependencies, so a Redis instance populated by a reference deployment
(or by this repo's exporter) serves either stack:

  * row key   = LE u64 model_version ++ LE u64 feature2id ++ LE i64 id
    (redis_feature_store.cc BatchGet: memcpy of model_version, feature2id,
    then the raw 8-byte key — binary keys, not strings)
  * row value = raw little-endian f32 bytes of the embedding row
  * batch read  = MGET (one command, N binary keys; nil => missing)
  * batch write = MSET (chunked)
  * metadata  = "GET/SET model_version" ("full,latest"), "GET/SET active",
    "SET model_lock <v> EX <t> NX" (GetStorageLock) — the same literal
    commands GetRedisMeta/SetModelVersion/SetActiveStatus issue.

``RedisFeatureStore`` exposes the HostKV ``get(keys) -> (values, freqs,
versions, found)`` signature, so it plugs into
``Predictor(stores={table: store})`` exactly like RemoteKVClient — the
bespoke-protocol store stays available as the no-Redis fallback. Redis
holds only values (the reference stores no freq/version per row); freqs
and versions come back zero with an exact found mask.
"""
from __future__ import annotations

import socket
import struct
import threading
from typing import List, Optional, Sequence, Tuple

import numpy as np

_CRLF = b"\r\n"


class RespError(RuntimeError):
    """A Redis `-ERR ...` reply."""


def encode_command(*args: bytes | str | int) -> bytes:
    """RESP array of bulk strings — the one request shape Redis accepts."""
    out = [b"*%d\r\n" % len(args)]
    for a in args:
        if isinstance(a, int):
            a = str(a).encode()
        elif isinstance(a, str):
            a = a.encode()
        out.append(b"$%d\r\n" % len(a))
        out.append(a)
        out.append(_CRLF)
    return b"".join(out)


class RespConnection:
    """One Redis connection: pipelined command send + reply parse.

    Thread-safe at the call level (a lock spans each send+receive), one
    persistent socket with lazy (re)connect — the RemoteKVClient pattern.
    """

    def __init__(self, host: str, port: int = 6379, *,
                 password: Optional[str] = None, db: int = 0,
                 timeout: float = 10.0):
        self.host, self.port = host, port
        self.password = password
        self.db = db
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._buf = b""
        self._lock = threading.Lock()

    # -- socket plumbing

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
            self._buf = b""
            hello: List[Tuple[bytes, ...]] = []
            if self.password is not None:
                hello.append((b"AUTH", self.password.encode()))
            if self.db:
                hello.append((b"SELECT", str(self.db).encode()))
            for cmd in hello:
                self._sock.sendall(encode_command(*cmd))
                self._read_reply()  # raises RespError on AUTH/SELECT failure
        return self._sock

    def _drop(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None
                self._buf = b""

    def close(self) -> None:
        with self._lock:
            self._drop()

    # -- RESP parsing

    def _read_line(self) -> bytes:
        while _CRLF not in self._buf:
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("redis closed the connection")
            self._buf += chunk
        line, self._buf = self._buf.split(_CRLF, 1)
        return line

    def _read_exact(self, n: int) -> bytes:
        while len(self._buf) < n:
            chunk = self._sock.recv(1 << 16)
            if not chunk:
                raise ConnectionError("redis closed the connection")
            self._buf += chunk
        out, self._buf = self._buf[:n], self._buf[n:]
        return out

    def _read_reply(self):
        line = self._read_line()
        kind, rest = line[:1], line[1:]
        if kind == b"+":
            return rest  # simple string (e.g. b"OK")
        if kind == b"-":
            raise RespError(rest.decode(errors="replace"))
        if kind == b":":
            return int(rest)
        if kind == b"$":
            n = int(rest)
            if n == -1:
                return None  # nil bulk
            data = self._read_exact(n)
            self._read_exact(2)  # trailing CRLF
            return data
        if kind == b"*":
            n = int(rest)
            if n == -1:
                return None  # nil array
            return [self._read_reply() for _ in range(n)]
        raise ConnectionError(f"bad RESP type byte {kind!r}")

    # -- public

    def command(self, *args):
        """One command, one reply. RespError for -ERR, reconnect on IO
        failure (next call redials)."""
        with self._lock:
            try:
                s = self._conn()
                s.sendall(encode_command(*args))
                return self._read_reply()
            except (OSError, ConnectionError):
                self._drop()
                raise

    def pipeline(self, commands: Sequence[Tuple]) -> list:
        """Send every command in one write, read every reply in order —
        what redisAppendCommand/redisGetReply do for the reference's
        async batches. Per-command `-ERR` replies are drained (the
        connection stays in sync — an unread reply would be handed to the
        NEXT command) and the first one raises after the full read."""
        if not commands:
            return []
        with self._lock:
            try:
                s = self._conn()
                s.sendall(b"".join(encode_command(*c) for c in commands))
                replies, first_err = [], None
                for _ in commands:
                    try:
                        replies.append(self._read_reply())
                    except RespError as e:
                        replies.append(e)
                        first_err = first_err or e
                if first_err is not None:
                    raise first_err
                return replies
            except (OSError, ConnectionError):
                self._drop()
                raise


class RedisFeatureStore:
    """HostKV-shaped view of a (reference-scheme) Redis feature store.

    Key/value encoding per redis_feature_store.cc (see module docstring).
    `feature2id` is the per-table integer the reference's graph optimizer
    assigns (graph_optimizer.cc:1792, sequential per EV node) — match the
    deployment's assignment when reading a reference-populated store.
    """

    # Bound keys per MGET/MSET command: a 4M-row promote burst must not
    # become one giant command buffer on either end.
    CHUNK = 8192

    def __init__(self, host: str, port: int = 6379, dim: int = None, *,
                 model_version: int = 0, feature2id: int = 0,
                 password: Optional[str] = None, db: int = 0,
                 timeout: float = 10.0,
                 conn: Optional[RespConnection] = None):
        if dim is None:
            raise ValueError("dim is required (embedding row width)")
        self.dim = dim
        self.model_version = model_version
        self.feature2id = feature2id
        self.conn = conn or RespConnection(
            host, port, password=password, db=db, timeout=timeout
        )

    # -- key scheme

    def _key(self, k: int) -> bytes:
        return struct.pack("<QQq", self.model_version, self.feature2id,
                           int(k))

    # -- HostKV surface (what Predictor's read-through fallback calls)

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
        keys = np.asarray(keys, np.int64)
        n = len(keys)
        vals = np.zeros((n, self.dim), np.float32)
        found = np.zeros(n, bool)
        for lo in range(0, n, self.CHUNK):
            chunk = keys[lo:lo + self.CHUNK]
            reply = self.conn.command(
                b"MGET", *[self._key(k) for k in chunk]
            )
            if not isinstance(reply, list) or len(reply) != len(chunk):
                raise ConnectionError(
                    f"MGET returned {type(reply).__name__} of "
                    f"{len(reply) if isinstance(reply, list) else '?'}, "
                    f"expected {len(chunk)} rows"
                )
            for i, item in enumerate(reply):
                if item is None:
                    continue
                if len(item) != 4 * self.dim:
                    raise ConnectionError(
                        f"row for key {int(chunk[i])} is {len(item)} bytes, "
                        f"expected {4 * self.dim} (dim mismatch?)"
                    )
                vals[lo + i] = np.frombuffer(item, "<f4")
                found[lo + i] = True
        zeros = np.zeros(n, np.int32)
        return vals, zeros.copy(), zeros.copy(), found

    def put(self, keys, values, freqs=None, versions=None) -> None:
        """MSET the rows (freq/version are accepted for HostKV-signature
        compatibility and dropped — the reference scheme stores values
        only)."""
        del freqs, versions
        keys = np.asarray(keys, np.int64)
        values = np.asarray(values, np.float32).reshape(len(keys), self.dim)
        for lo in range(0, len(keys), self.CHUNK):
            args: List[bytes] = [b"MSET"]
            for k, row in zip(keys[lo:lo + self.CHUNK],
                              values[lo:lo + self.CHUNK]):
                args.append(self._key(k))
                args.append(row.astype("<f4").tobytes())
            reply = self.conn.command(*args)
            if reply != b"OK":
                raise ConnectionError(f"MSET returned {reply!r}")

    def delete(self, keys) -> int:
        """DEL rows (the reference's Cleanup path eval-scans and deletes
        stale versions; per-key delete is the building block)."""
        keys = np.asarray(keys, np.int64)
        removed = 0
        for lo in range(0, len(keys), self.CHUNK):
            removed += int(self.conn.command(
                b"DEL", *[self._key(k) for k in keys[lo:lo + self.CHUNK]]
            ))
        return removed

    # -- metadata parity (GetRedisMeta / SetModelVersion / SetActiveStatus
    #    / GetStorageLock literal command strings)

    def get_model_version(self) -> Tuple[int, int]:
        reply = self.conn.command(b"GET", b"model_version")
        if reply is None:
            return -1, -1
        text = reply.decode()
        if "," not in text:
            raise RespError(f"unparseable model_version {text!r}")
        full, latest = text.split(",", 1)
        return int(full), int(latest)

    def set_model_version(self, full_version: int,
                          latest_version: int) -> None:
        self.conn.command(
            b"SET", b"model_version", f"{full_version},{latest_version}"
        )

    def get_active(self) -> bool:
        reply = self.conn.command(b"GET", b"active")
        return reply is not None and reply != b"0"

    def set_active(self, active: bool) -> None:
        self.conn.command(b"SET", b"active", b"1" if active else b"0")

    def acquire_lock(self, value: int, timeout_secs: int) -> bool:
        """SET model_lock <v> EX <t> NX — the reference's distributed
        update lock; True when this caller won it."""
        reply = self.conn.command(
            b"SET", b"model_lock", str(value), b"ex", str(timeout_secs),
            b"nx",
        )
        return reply is not None

    def release_lock(self) -> None:
        self.conn.command(b"DEL", b"model_lock")

    def close(self) -> None:
        self.conn.close()
