"""Version-keyed compute-reuse caches for the serving tier.

Heavy traffic is redundant traffic: a power-law user population
re-requests the same user tower and the same candidate sets within
seconds. This module is the one primitive behind all three reuse sites
(predict answer cache, user-tower cache, retrieval candidate cache):

  * **Key derivation** — `request_fingerprint` hashes the request's
    feature arrays (name + dtype + shape + bytes, name-sorted so dict
    order never matters) into a 128-bit blake2b digest. The digest is
    the cache key together with the producing version; builtin `hash()`
    is never used (per-process salted) and 32-bit checksums are not
    enough (birthday collisions at ~77k hot entries would serve one
    user another user's answer).
  * **Invalidation by version, never by sweep** — every entry is keyed
    `(fingerprint, version)` where `version` comes from the owner's
    `version_fn` (model snapshot version for predict, `(model version,
    corpus_rev)` for retrieval). A hit is only a hit at the CURRENT
    version; a delta publish bumps the version and the publish edge
    calls `invalidate_stale()`, so a cache can never serve an answer
    across a version the freshness bench would call stale.
  * **Byte-bounded LRU** — capacity is bytes of cached values, not
    entry count; inserts evict from the cold end until under budget and
    evictions are counted. An entry larger than the whole budget is
    simply not stored.

Observability (DRT007-clean: the only label is the cache's name, a
bounded set fixed at construction): `deeprec_reuse_{hits,misses,
evictions,invalidations}_total` counters plus occupancy/capacity/entry
callback gauges, all merged across the fleet by the frontend's
/metrics relabeling. docs/serving.md "Frontend compute reuse" is the
contract; tools/bench_serving.py `compute_reuse` measures it and
`roofline.py --assert-reuse` gates it.
"""
from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np


def request_fingerprint(features: Dict[str, Any],
                        names: Optional[list] = None,
                        extra: bytes = b"") -> bytes:
    """128-bit digest of a request's feature arrays. `names` restricts
    the digest to a subset (the user-tower cache keys on user features
    only); `extra` folds request parameters that change the answer into
    the key (retrieval folds k). Name-bound and order-independent:
    permuting dict insertion order never moves the digest, renaming a
    feature always does."""
    h = hashlib.blake2b(digest_size=16)
    keys = sorted(names) if names is not None else sorted(features)
    for name in keys:
        v = np.ascontiguousarray(features[name])  # noqa: DRT002 — cache-key digest of the HOST request payload, pre-dispatch by design
        h.update(name.encode())
        h.update(b"\x00")
        h.update(v.dtype.str.encode())
        h.update(repr(v.shape).encode())
        h.update(v.tobytes())
    if extra:
        h.update(b"\x01")
        h.update(extra)
    return h.digest()


def value_nbytes(value: Any) -> int:
    """Bytes a cached value occupies (array leaves summed; dicts/tuples
    recursed) — the unit the LRU's byte budget is enforced in."""
    if isinstance(value, dict):
        return sum(value_nbytes(v) for v in value.values())
    if isinstance(value, (tuple, list)):
        return sum(value_nbytes(v) for v in value)
    if hasattr(value, "nbytes"):
        return int(value.nbytes)  # noqa: DRT002 — host-side cache accounting
    return int(np.asarray(value).nbytes)  # noqa: DRT002 — host-side cache accounting


class ReuseCache:
    """Byte-bounded LRU keyed ``(fingerprint, version)``.

    ``version_fn`` is read at lookup time: `get_current` only answers
    when the stored version equals the live one, so a stale entry is
    dead the instant the owner publishes — `invalidate_stale()` (called
    on the publish edge) merely reclaims the bytes and counts the
    drops. Thread-safe; the serving path holds the lock only for dict
    ops, never for compute."""

    def __init__(self, capacity_bytes: int, name: str,
                 registry=None,
                 version_fn: Optional[Callable[[], Any]] = None):
        self.capacity_bytes = int(capacity_bytes)
        self.name = name
        self.version_fn = version_fn
        self._lock = threading.Lock()
        self._entries: OrderedDict[Tuple[bytes, Any], Tuple[Any, int]] = (
            OrderedDict())
        self._bytes = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.invalidations = 0
        self._m_hits = self._m_misses = None
        self._m_evict = self._m_inval = None
        if registry is not None:
            lab = {"cache": name}  # bounded: one series per cache site
            self._m_hits = registry.counter(
                "deeprec_reuse_hits",
                "cache hits served without running the model", lab)
            self._m_misses = registry.counter(
                "deeprec_reuse_misses",
                "cache lookups that fell through to evaluation", lab)
            self._m_evict = registry.counter(
                "deeprec_reuse_evictions",
                "entries dropped by the LRU byte budget", lab)
            self._m_inval = registry.counter(
                "deeprec_reuse_invalidations",
                "entries dropped because their version went stale", lab)
            registry.register_callback(
                "deeprec_reuse_occupancy_bytes", lambda: self._bytes,
                "bytes of cached answers resident right now", lab)
            registry.register_callback(
                "deeprec_reuse_capacity_bytes",
                lambda: self.capacity_bytes,
                "LRU byte budget of this cache", lab)
            registry.register_callback(
                "deeprec_reuse_entries", lambda: len(self._entries),
                "entries resident right now", lab)

    # ------------------------------------------------------------- lookup

    def current_version(self) -> Any:
        return self.version_fn() if self.version_fn is not None else None

    def get_current(self, fp: bytes):
        """(value, version) when `fp` is cached AT the live version,
        else None (counted as a miss). Hits refresh LRU recency."""
        version = self.current_version()
        key = (fp, version)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                if self._m_misses is not None:
                    self._m_misses.inc()
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            if self._m_hits is not None:
                self._m_hits.inc()
            return ent[0], version

    def put(self, fp: bytes, version: Any, value: Any,
            nbytes: Optional[int] = None) -> bool:
        """Insert (or refresh) an entry produced at `version`; evicts
        from the cold end until the byte budget holds. Returns whether
        the value is resident (False: larger than the whole budget, or
        already stale vs the live version)."""
        if nbytes is None:
            nbytes = value_nbytes(value)
        if nbytes > self.capacity_bytes:
            return False
        live = self.current_version()
        if self.version_fn is not None and version != live:
            return False  # produced before a publish: born stale
        key = (fp, version)
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._bytes -= old[1]
            self._entries[key] = (value, nbytes)
            self._bytes += nbytes
            while self._bytes > self.capacity_bytes and self._entries:
                _, (_, nb) = self._entries.popitem(last=False)
                self._bytes -= nb
                self.evictions += 1
                if self._m_evict is not None:
                    self._m_evict.inc()
        return True

    # -------------------------------------------------------- invalidation

    def invalidate_stale(self) -> int:
        """Drop every entry whose version differs from the live one —
        the publish-edge hook (Predictor._publish / retrieval's
        corpus_rev bump). Returns the number dropped."""
        live = self.current_version()
        dropped = 0
        with self._lock:
            for key in [k for k in self._entries if k[1] != live]:
                _, nb = self._entries.pop(key)
                self._bytes -= nb
                dropped += 1
            self.invalidations += dropped
        if dropped and self._m_inval is not None:
            self._m_inval.inc(dropped)
        return dropped

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._bytes = 0

    # ----------------------------------------------------------- snapshot

    def occupancy_bytes(self) -> int:
        return self._bytes

    def __len__(self) -> int:
        return len(self._entries)

    def snapshot(self) -> Dict[str, Any]:
        """Counters + occupancy for `/v1/stats` and the bench arms."""
        total = self.hits + self.misses
        return {
            "name": self.name,
            "hits": self.hits,
            "misses": self.misses,
            "hit_rate": self.hits / total if total else 0.0,
            "evictions": self.evictions,
            "invalidations": self.invalidations,
            "entries": len(self._entries),
            "occupancy_bytes": self._bytes,
            "capacity_bytes": self.capacity_bytes,
        }
