"""Remote feature store: a network KV service + client for serving-time
embedding fallback.

Parity: the reference's Redis feature store
(serving/processor/storage/redis_feature_store.h:18) lets serving hosts
read embedding rows they don't hold locally. The TPU-repo shape: a HostKV
served over a compact length-prefixed TCP protocol. The client exposes the
HostKV ``get(keys) -> (values, freqs, versions, found)`` signature, so it
plugs straight into ``Predictor(stores={table: client})`` — read-through
works the same whether the store is in-process or remote.

Wire protocol (all little-endian):
  request : b"GETB" | u32 n | n * i64 keys
  response: u32 n | u32 dim | n * u8 found | n*dim f32 values
            | n * i32 freqs | n * i32 versions
  request : b"PUTB" | u32 n | u32 dim | payload (same layout as response)
  response: b"OK\\n\\n"
"""
from __future__ import annotations

import socket
import socketserver
import struct
import threading
from typing import Optional, Tuple

import numpy as np

from deeprec_tpu.native import HostKV

_MAX_BATCH = 1 << 22  # sanity bound on n


def _recv_exact(rfile, n: int) -> bytes:
    data = rfile.read(n)
    if len(data) != n:
        raise ConnectionError("short read")
    return data


class RemoteKVServer:
    """Serve one HostKV (one table's rows) on a TCP port."""

    def __init__(self, kv: HostKV, dim: int, host: str = "127.0.0.1",
                 port: int = 0):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                while True:
                    op = self.rfile.read(4)
                    if len(op) < 4:
                        return
                    if op == b"GETB":
                        (n,) = struct.unpack("<I", _recv_exact(self.rfile, 4))
                        if n > _MAX_BATCH:
                            return
                        keys = np.frombuffer(
                            _recv_exact(self.rfile, 8 * n), "<i8"
                        )
                        with outer._lock:
                            vals, freqs, vers, found = outer.kv.get(keys)
                        out = struct.pack("<II", n, outer.dim)
                        out += found.astype(np.uint8).tobytes()
                        out += vals.astype("<f4").tobytes()
                        out += freqs.astype("<i4").tobytes()
                        out += vers.astype("<i4").tobytes()
                        self.wfile.write(out)
                        self.wfile.flush()
                    elif op == b"PUTB":
                        n, dim = struct.unpack(
                            "<II", _recv_exact(self.rfile, 8)
                        )
                        if n > _MAX_BATCH or dim != outer.dim:
                            return
                        keys = np.frombuffer(
                            _recv_exact(self.rfile, 8 * n), "<i8"
                        )
                        vals = np.frombuffer(
                            _recv_exact(self.rfile, 4 * n * dim), "<f4"
                        ).reshape(n, dim)
                        freqs = np.frombuffer(
                            _recv_exact(self.rfile, 4 * n), "<i4"
                        )
                        vers = np.frombuffer(
                            _recv_exact(self.rfile, 4 * n), "<i4"
                        )
                        with outer._lock:
                            outer.kv.put(keys, vals, freqs, vers)
                        self.wfile.write(b"OK\n\n")
                        self.wfile.flush()
                    else:
                        return  # unknown op: drop the connection

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.kv = kv
        self.dim = dim
        self._lock = threading.Lock()
        self._srv = Server((host, port), Handler)
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "RemoteKVServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=2)


class RemoteKVClient:
    """HostKV-shaped client for a RemoteKVServer (or anything speaking the
    protocol). One persistent connection, reconnects on failure."""

    def __init__(self, host: str, port: int, dim: int,
                 timeout: float = 10.0):
        self.host = host
        self.port = port
        self.dim = dim
        self.timeout = timeout
        self._sock: Optional[socket.socket] = None
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )
        return self._sock

    def _drop(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _recv(self, sock, n: int) -> bytes:
        out = b""
        while len(out) < n:
            chunk = sock.recv(n - len(out))
            if not chunk:
                raise ConnectionError("server closed")
            out += chunk
        return out

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        keys = np.asarray(keys, np.int64)
        n = len(keys)
        with self._lock:
            try:
                s = self._conn()
                s.sendall(b"GETB" + struct.pack("<I", n) +
                          keys.astype("<i8").tobytes())
                rn, dim = struct.unpack("<II", self._recv(s, 8))
                if rn != n or dim != self.dim:
                    # explicit (not assert: -O must not strip it) — a
                    # mismatched header means the byte stream would be
                    # misinterpreted as embedding rows
                    raise ConnectionError(
                        f"protocol mismatch: got n={rn} dim={dim}, "
                        f"expected n={n} dim={self.dim}"
                    )
                found = np.frombuffer(self._recv(s, n), np.uint8).astype(bool)
                vals = np.frombuffer(
                    self._recv(s, 4 * n * dim), "<f4"
                ).reshape(n, dim).copy()
                freqs = np.frombuffer(self._recv(s, 4 * n), "<i4").copy()
                vers = np.frombuffer(self._recv(s, 4 * n), "<i4").copy()
                return vals, freqs, vers, found
            except (OSError, ConnectionError):
                self._drop()
                raise

    def put(self, keys, values, freqs=None, versions=None) -> None:
        keys = np.asarray(keys, np.int64)
        n = len(keys)
        values = np.asarray(values, np.float32).reshape(n, self.dim)
        freqs = (np.zeros(n, np.int32) if freqs is None
                 else np.asarray(freqs, np.int32))
        versions = (np.zeros(n, np.int32) if versions is None
                    else np.asarray(versions, np.int32))
        with self._lock:
            try:
                s = self._conn()
                s.sendall(
                    b"PUTB" + struct.pack("<II", n, self.dim)
                    + keys.astype("<i8").tobytes()
                    + values.astype("<f4").tobytes()
                    + freqs.astype("<i4").tobytes()
                    + versions.astype("<i4").tobytes()
                )
                ack = self._recv(s, 4)
                if ack != b"OK\n\n":
                    raise ConnectionError(f"bad ack {ack!r}")
            except (OSError, ConnectionError):
                self._drop()
                raise

    def close(self) -> None:
        with self._lock:
            self._drop()
