"""Elastic serving fleet: lease-file discovery, membership churn, and
rolling restarts with zero failed requests.

PR 10's socket tier (serving/frontend.py) is the right dispatch shape,
but its frontend took a FROZEN member list in ``__init__`` — a config
edit or a backend upgrade meant restarting the edge. This module is the
membership/control plane that makes the tier a deployable fleet, the
DeepRec SessionGroup + elastic-PS serving story (SURVEY §2.4/§5) done
with the machinery this repo already trusts:

  * **Lease-file registry** (`FleetRegistry`) — discovery over a shared
    directory, reusing the online Supervisor's `Heartbeat` atomic
    tmp+rename stamps (PR 7): every backend re-stamps
    ``addr, capacity, model_version, started_at`` each interval; a lease
    older than ``lease_secs`` means the member is EVICTED from routing
    (it rejoins the moment it stamps again — eviction is a routing
    decision, not a tombstone). Two leases claiming one addr resolve
    last-writer-wins; the loser is quarantined (renamed
    ``*.quarantined``) so the conflict is visible, the checkpoint-chain
    discipline applied to membership.
  * **Consistent-hash routing** (`HashRing`) — virtual-node ring keyed
    by the frontend's existing `_group_key` user hash, so `group_users`
    stickiness survives join/leave with only ~1/N of users remapping
    (a modular ``% len(members)`` reshuffles nearly everyone on every
    churn event, which destroys cross-request coalescing fleet-wide at
    exactly the moment the fleet is degraded).
  * **Drain protocol** (`LeaseStamper` + `FleetRegistry.request_drain`)
    — a leaving backend stamps its lease ``draining``; frontends stop
    NEW assignments, in-flight grouped streams finish, then the backend
    exits with `parallel/elastic.py`'s ``EXIT_RESCALE`` (a supervisor
    respawns it for free — the elastic-training planned-exit contract
    applied to serving) or 0 (a retirement: the supervisor lets it go).
  * **Replicated frontends** (`FleetClient`) — N edge processes share
    the registry (each stamps a ``role="frontend"`` lease and sweeps
    health independently; no single edge). The client-side retry
    contract is pinned here: predictions are idempotent, so a SIGKILLed
    frontend costs the client a reconnect to a sibling edge, never a
    failed request.
  * **Load-driven autoscaling** (`FleetAutoscaler`) — consumes the
    windowed e2e p99 + queue-depth signal the PR 11 obs plane already
    answers from ring buffers (surfaced as ``fleet_load`` in the
    frontend's ``/v1/stats``), and spawns/retires backends between
    ``min_members``/``max_members`` with hysteresis (N consecutive
    breaches) and a cooldown, so one latency spike never triggers a
    flapping fleet.

`tools/bench_fleet.py` drives the headline: sustained rps through a
rolling restart of EVERY backend and a 2→4→2 scale event with zero
failed requests, recorded as SERVING_BENCH.json's ``multi_host`` section
and gated by ``roofline.py --assert-serving``.
"""
from __future__ import annotations

import bisect
import hashlib
import json
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from deeprec_tpu.online.supervisor import Heartbeat
from deeprec_tpu.utils import backoff as _backoff

#: lease roles — backends serve PRED traffic, frontends are HTTP edges
ROLE_BACKEND = "backend"
ROLE_FRONTEND = "frontend"

#: lease statuses — "up" routes, "draining" finishes in-flight only
STATUS_UP = "up"
STATUS_DRAINING = "draining"


def _sanitize(addr: str) -> str:
    return addr.replace(":", "_").replace("/", "_")


@dataclass
class MemberLease:
    """One member's view in the registry: the decoded lease payload plus
    where it came from. ``age`` is seconds since the stamp at scan time
    (the eviction clock)."""

    addr: str
    role: str
    status: str
    capacity: int
    model_version: int
    started_at: float
    pid: int
    time: float
    age: float
    name: str
    path: str

    @property
    def draining(self) -> bool:
        return self.status == STATUS_DRAINING


class FleetRegistry:
    """Lease-file membership over a shared directory.

    One file per member PROCESS (`lease-<role>-<addr>-<pid>.lease`), so
    two processes claiming the same addr are two files the sweep can
    arbitrate (last writer wins, older quarantined) instead of one file
    silently flip-flopping. Writes go through `Heartbeat` (atomic
    tmp+rename), so a reader never sees a torn lease — and a torn file
    planted by anything else (fault injection, FS corruption) reads as
    'no lease' and is skipped, never trusted.

    Drain requests are separate small files (`drain-<addr>.json`): the
    CONTROLLER writes them (autoscaler, rolling-restart choreography,
    an operator), the member's `LeaseStamper` picks them up on its next
    beat. The member always owns its own lease; nothing else ever
    writes it.
    """

    def __init__(self, directory: str, lease_secs: float = 10.0):
        self.dir = directory
        self.lease_secs = lease_secs
        os.makedirs(directory, exist_ok=True)

    # ---------------------------------------------------------- paths

    def lease_path(self, addr: str, role: str = ROLE_BACKEND,
                   pid: Optional[int] = None) -> str:
        pid = os.getpid() if pid is None else pid
        return os.path.join(
            self.dir, f"lease-{role}-{_sanitize(addr)}-{pid}.lease")

    def _drain_path(self, addr: str) -> str:
        return os.path.join(self.dir, f"drain-{_sanitize(addr)}.json")

    # ------------------------------------------------------- sweeping

    def members(self, role: Optional[str] = ROLE_BACKEND,
                now: Optional[float] = None,
                include_draining: bool = True) -> List[MemberLease]:
        """Current membership: every live lease of `role` (None = all),
        stale leases excluded (evicted), duplicate-addr claims resolved
        last-writer-wins with the older lease quarantined. Sorted by
        addr so every frontend replica sees the same order."""
        now = time.time() if now is None else now
        by_addr: Dict[str, MemberLease] = {}
        losers: List[MemberLease] = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return []
        for fn in names:
            if not fn.endswith(".lease"):
                continue
            path = os.path.join(self.dir, fn)
            payload = Heartbeat.read(path)
            if payload is None:
                continue  # torn/unreadable: not a lease (fault-injected
                # tears land here — never trusted, never fatal)
            try:
                lease = MemberLease(
                    addr=str(payload["addr"]),
                    role=str(payload.get("role", ROLE_BACKEND)),
                    status=str(payload.get("status", STATUS_UP)),
                    capacity=int(payload.get("capacity", 1)),  # noqa: DRT002 — decoding a JSON lease payload, host-side control plane (no device value)
                    model_version=int(payload.get("model_version", -1)),  # noqa: DRT002 — JSON lease payload decode, host-side control plane
                    started_at=float(payload.get("started_at", 0.0)),  # noqa: DRT002 — JSON lease payload decode, host-side control plane
                    pid=int(payload.get("pid", 0)),  # noqa: DRT002 — JSON lease payload decode, host-side control plane
                    time=float(payload["time"]),  # noqa: DRT002 — JSON lease payload decode, host-side control plane
                    age=max(0.0, now - float(payload["time"])),  # noqa: DRT002 — JSON lease payload decode, host-side control plane
                    name=str(payload.get("name", "")),
                    path=path,
                )
            except (KeyError, TypeError, ValueError):
                continue  # schema-garbage lease: skip, don't crash a sweep
            if role is not None and lease.role != role:
                continue
            if lease.age > self.lease_secs:
                continue  # stale = evicted from routing (file kept: the
                # member rejoins by stamping again; gc() reaps the dead)
            if not include_draining and lease.draining:
                continue
            prev = by_addr.get(lease.addr)
            if prev is None:
                by_addr[lease.addr] = lease
            elif lease.time > prev.time:
                losers.append(prev)
                by_addr[lease.addr] = lease
            else:
                losers.append(lease)
        for lost in losers:
            # Last-writer-wins: the older claimant's lease is quarantined
            # (rename, not unlink — visible conflict, the checkpoint-
            # chain discipline). Its process may still be alive and will
            # recreate the file on its next beat; it loses again until it
            # stops claiming the addr.
            try:
                os.replace(lost.path, lost.path + ".quarantined")
            except OSError:
                pass
        return sorted(by_addr.values(), key=lambda m: m.addr)

    def gc(self, evict_secs: Optional[float] = None) -> int:
        """Reap lease files dead for much longer than the lease (default
        10×): eviction itself never unlinks (a slow-but-live member must
        be able to rejoin by re-stamping — unlinking would race its
        beat), so long-dead files are reaped on this separate, much
        longer clock. Returns the number reaped."""
        evict_secs = (10 * self.lease_secs if evict_secs is None
                      else evict_secs)
        now = time.time()
        n = 0
        try:
            names = os.listdir(self.dir)
        except OSError:
            return 0
        for fn in names:
            if not (fn.endswith(".lease") or fn.endswith(".quarantined")):
                continue
            path = os.path.join(self.dir, fn)
            payload = Heartbeat.read(path)
            stamp = (payload or {}).get("time")
            if stamp is not None and now - float(stamp) <= evict_secs:
                continue
            if stamp is None:
                # unreadable: age by mtime so torn junk is reaped too
                try:
                    if now - os.path.getmtime(path) <= evict_secs:
                        continue
                except OSError:
                    continue
            try:
                os.unlink(path)
                n += 1
            except OSError:
                pass
        return n

    # -------------------------------------------------------- draining

    def request_drain(self, addr: str, respawn: bool = False) -> None:
        """Ask the member at `addr` to leave: its LeaseStamper sees this
        on the next beat, stamps its lease ``draining`` (frontends stop
        new assignments), finishes in-flight work, and exits —
        EXIT_RESCALE when ``respawn`` (rolling restart: the supervisor
        respawns for free) or 0 (retirement). Atomic tmp+rename like
        every other control file here."""
        path = self._drain_path(addr)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w") as f:
            json.dump({"respawn": bool(respawn), "time": time.time()}, f)
        os.replace(tmp, path)

    def drain_requested(self, addr: str) -> Optional[dict]:
        try:
            with open(self._drain_path(addr)) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    def clear_drain(self, addr: str) -> None:
        try:
            os.unlink(self._drain_path(addr))
        except OSError:
            pass

    def unregister(self, addr: str, role: str = ROLE_BACKEND,
                   pid: Optional[int] = None) -> None:
        """Remove this process's lease (planned exit). A SIGKILLed member
        never gets here — its lease goes stale and eviction handles it."""
        try:
            os.unlink(self.lease_path(addr, role, pid))
        except OSError:
            pass


class LeaseStamper:
    """One member's lease heartbeat: stamps every ``interval`` (default
    lease_secs/3 — three missed beats = evicted) and picks up drain
    requests. Runs on a daemon thread; `stamp()` is also callable
    directly for tests and for a final synchronous stamp.

    ``draining`` (a threading.Event) is the member-side drain signal:
    set when a drain request is observed (or `begin_drain` is called);
    the owner (backend CLI, BackendServer) watches it, finishes
    in-flight work, and exits with `exit_code()`.
    """

    def __init__(self, registry: FleetRegistry, addr: str, *,
                 role: str = ROLE_BACKEND, capacity: int = 1,
                 name: str = "",
                 version_fn: Optional[Callable[[], int]] = None,
                 interval: Optional[float] = None):
        self.registry = registry
        self.addr = addr
        self.role = role
        self.capacity = capacity
        self.name = name
        self.version_fn = version_fn
        self.interval = (registry.lease_secs / 3.0 if interval is None
                         else interval)
        self.started_at = time.time()
        self.draining = threading.Event()
        self.drain_respawn = False
        self.beats = 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # stamp() can be entered from two threads at once (the loop vs a
        # SIGTERM handler's begin_drain on the main thread); Heartbeat's
        # tmp path is per-PID, not per-thread, so unserialized writers
        # could rename each other's half-written tmp into the live lease
        # — exactly the torn lease the atomic write exists to prevent.
        self._stamp_lock = threading.Lock()

    def stamp(self, status: Optional[str] = None) -> None:
        """One atomic lease write (serialized — see _stamp_lock). Never
        raises (Heartbeat.beat already swallows FS errors: a missed
        stamp surfaces as a stale lease on the sweep side, which is the
        correct signal). A stamp AFTER stop() is a no-op — checked
        under the same lock stop()'s unregister takes, so a racing
        deferred first stamp (the slow-join Timer firing as its server
        shuts down) can never re-announce a dead member."""
        version = -1
        if self.version_fn is not None:
            try:
                version = int(self.version_fn())  # noqa: DRT002 — Predictor.version is a host int (snapshot stamp), read on the lease thread, never the request path
            except Exception:
                version = -1  # a wedged model must not kill the lease
        with self._stamp_lock:
            if self._stop.is_set():
                return
            hb = Heartbeat(self.registry.lease_path(self.addr, self.role))
            hb.beat(
                status=(status if status is not None else
                        (STATUS_DRAINING if self.draining.is_set()
                         else STATUS_UP)),
                addr=self.addr, role=self.role, capacity=self.capacity,
                model_version=version, started_at=self.started_at,
                name=self.name,
            )
            self.beats += 1

    def begin_drain(self, respawn: bool = False) -> None:
        """Member-side drain entry (drain file, SIGTERM handler, or a
        direct call): stamp ``draining`` immediately so frontends stop
        new assignments within one sweep, then let the owner finish
        in-flight work."""
        self.drain_respawn = self.drain_respawn or bool(respawn)
        self.draining.set()
        self.stamp(STATUS_DRAINING)

    def exit_code(self) -> int:
        """The drain exit contract: EXIT_RESCALE for a rolling restart
        (supervisor respawns for free — the parallel/elastic.py planned-
        exit choreography applied to serving), 0 for a retirement."""
        from deeprec_tpu.parallel.elastic import EXIT_RESCALE

        return EXIT_RESCALE if self.drain_respawn else 0

    def _loop(self) -> None:
        while not self._stop.wait(self.interval):
            if not self.draining.is_set():
                req = self.registry.drain_requested(self.addr)
                if req is not None:
                    self.begin_drain(respawn=bool(req.get("respawn")))
                    continue  # begin_drain already stamped
            self.stamp()

    def start(self) -> "LeaseStamper":
        if self._stop.is_set():
            return self  # stopped before the (possibly deferred) start
        self.stamp()  # register before the first interval elapses
        self._thread = threading.Thread(
            target=self._loop, daemon=True,
            name=f"lease-{_sanitize(self.addr)}")
        self._thread.start()
        return self

    def stop(self, unregister: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None
        # Under _stamp_lock: any in-flight stamp finishes first, any
        # later stamp sees _stop and no-ops — the unregister below is
        # therefore FINAL (no racing writer can resurrect the lease).
        with self._stamp_lock:
            if unregister:
                self.registry.unregister(self.addr, self.role)
                self.registry.clear_drain(self.addr)


# ---------------------------------------------------------------- hashing


class HashRing:
    """Consistent-hash ring with virtual nodes.

    Each member owns ``vnodes`` points on a 64-bit ring (blake2b — an
    UNSALTED hash, so every frontend replica and every restart builds
    the identical ring; builtin hash() would reshuffle user affinity
    per process, the same trap `_group_key` documents for crc32).
    ``lookup(key)`` walks clockwise to the next point; when a member
    joins, it captures only the arcs its new points land on (~1/N of
    keys), and when it leaves, its keys fall to each arc's NEXT distinct
    member — which is exactly `preference()`'s retry order, so failover
    routing and post-churn routing agree."""

    def __init__(self, members: Sequence[str], vnodes: int = 64):
        self.members = sorted(set(members))
        self.vnodes = vnodes
        points: List[Tuple[int, str]] = []
        for m in self.members:
            for i in range(vnodes):
                points.append((self._hash(f"{m}#{i}"), m))
        points.sort()
        self._points = points
        self._hashes = [h for h, _ in points]

    @staticmethod
    def _hash(s: str) -> int:
        return int.from_bytes(
            hashlib.blake2b(s.encode(), digest_size=8).digest(), "little")

    def lookup(self, key: int) -> str:
        """The member owning `key` (any int — e.g. the frontend's
        `_group_key` crc32)."""
        if not self._points:
            raise RuntimeError("empty hash ring (no fleet members)")
        i = bisect.bisect_right(self._hashes, self._hash(str(key)))
        return self._points[i % len(self._points)][1]

    def preference(self, key: int, k: Optional[int] = None) -> List[str]:
        """Ordered distinct members for `key`: the owner first, then each
        successive distinct member clockwise — the retry order that keeps
        failover consistent with what routing will do if the owner
        actually leaves."""
        if not self._points:
            return []
        k = len(self.members) if k is None else min(k, len(self.members))
        i = bisect.bisect_right(self._hashes, self._hash(str(key)))
        out: List[str] = []
        seen = set()
        n = len(self._points)
        for j in range(n):
            m = self._points[(i + j) % n][1]
            if m not in seen:
                seen.add(m)
                out.append(m)
                if len(out) >= k:
                    break
        return out


# ----------------------------------------------------------- fleet client


class FleetClient:
    """Client half of the replicated-frontend contract: POST
    ``/v1/predict`` against any of N edge processes, reconnecting to a
    sibling on socket-level failure. Predictions are idempotent (no
    server-side state advances per request), so a retry after a killed
    frontend is ALWAYS safe — the contract the fleet bench pins: a
    SIGKILLed frontend costs a reconnect, never a failed request.

    Frontend addresses come from a static list, a `FleetRegistry`
    (``role="frontend"`` leases), or both; the registry view refreshes
    whenever every known edge failed (membership may have moved under
    us) and on a cadence."""

    def __init__(self, frontends: Optional[Sequence[str]] = None,
                 registry: Optional[FleetRegistry] = None, *,
                 timeout: float = 30.0, deadline: float = 60.0,
                 backoff_base: float = 0.05, backoff_max: float = 1.0,
                 refresh_secs: float = 2.0, rng=None):
        if not frontends and registry is None:
            raise ValueError("need frontend addrs and/or a registry")
        self._static = list(frontends or [])
        self.registry = registry
        self.timeout = timeout
        self.deadline = deadline
        self.backoff_base = backoff_base
        self.backoff_max = backoff_max
        self.refresh_secs = refresh_secs
        self._rng = rng or _backoff.seeded_rng(
            "fleet-client", pid=os.getpid())
        self._edges: List[str] = list(self._static)
        self._refreshed = 0.0
        self._i = 0
        self.reconnects = 0  # socket-level failovers (the pinned count)
        self.requests = 0
        self._refresh(force=True)

    def _refresh(self, force: bool = False) -> None:
        if self.registry is None:
            return
        now = time.monotonic()
        if not force and now - self._refreshed < self.refresh_secs:
            return
        self._refreshed = now
        leased = [m.addr for m in self.registry.members(ROLE_FRONTEND)
                  if not m.draining]
        merged = leased + [a for a in self._static if a not in leased]
        if merged:
            self._edges = merged

    def edges(self) -> List[str]:
        self._refresh()
        return list(self._edges)

    def predict(self, features: Dict, group_users: bool = False) -> Dict:
        """One prediction through whichever edge answers. Retries socket
        failures and 5xx on sibling edges with jittered backoff until
        `deadline`; 4xx (a bad request is bad on every edge) raises
        immediately."""
        import urllib.error
        import urllib.request

        body = json.dumps({
            "features": {k: (v.tolist() if hasattr(v, "tolist") else v)
                         for k, v in features.items()},
            **({"group_users": True} if group_users else {}),
        }).encode()
        stop = time.monotonic() + self.deadline
        attempt = 0
        last: Optional[Exception] = None
        while time.monotonic() < stop:
            self._refresh()
            edges = self._edges
            if not edges:
                time.sleep(_backoff.jittered(self.backoff_base, self._rng))
                continue
            addr = edges[self._i % len(edges)]
            self._i += 1
            try:
                r = urllib.request.urlopen(urllib.request.Request(
                    f"http://{addr}/v1/predict", data=body,
                    headers={"Content-Type": "application/json"},
                    method="POST"), timeout=self.timeout)
                out = json.loads(r.read())
                self.requests += 1
                return out
            except urllib.error.HTTPError as e:
                if e.code < 500:
                    raise  # bad request: no sibling will disagree
                last = e
            except (OSError, urllib.error.URLError) as e:
                last = e
            # socket-level failure or 5xx: reconnect to a sibling edge
            attempt += 1
            self.reconnects += 1
            self._refresh(force=True)
            time.sleep(_backoff.jittered_backoff(
                attempt, self.backoff_base, self.backoff_max, self._rng))
        raise RuntimeError(
            f"no frontend answered within {self.deadline}s "
            f"(edges {self._edges})") from last


# ------------------------------------------------------------- autoscaler


@dataclass
class FleetLoad:
    """One load observation: the ``fleet_load`` section of the
    frontend's ``/v1/stats`` (windowed e2e p99 over the obs ring
    buffers, queue depth summed over members)."""

    p99_ms: Optional[float]
    queue_depth: int
    members: int


class FleetAutoscaler:
    """Scale the backend count from observed load, between hard bounds,
    without flapping.

    Pure decision core: `observe(load)` is one tick — callable from a
    thread (`start(interval)`), from the bench loop, or from tests with
    a fake clock. Actions go through two injected callables:

      * ``scale_up()``   — spawn one backend (Supervisor.add_spec +
        the backend CLI with ``--registry``; the new member admits
        itself by stamping a lease).
      * ``scale_down(n)`` — retire one backend given the current count
        (pick a victim, `registry.request_drain(addr)`; the member
        drains and exits 0).

    Policy: a breach (windowed p99 above ``p99_high_ms`` OR queue depth
    above ``queue_high``) must persist for ``sustain`` consecutive
    observations before scaling up (hysteresis); calm (p99 below
    ``p99_low_ms`` AND queue below ``queue_low``) must persist equally
    before scaling down. Every action arms a ``cooldown_secs`` window in
    which no further action fires — a spawn takes seconds to absorb
    load, and acting again off the same stale signal is how autoscalers
    oscillate. ``set_target`` overrides load entirely (rolling
    operations and the bench's deterministic 2→4→2 event), still one
    member per tick and still respecting the cooldown."""

    def __init__(self, *, members_fn: Callable[[], int],
                 scale_up: Callable[[], None],
                 scale_down: Callable[[int], None],
                 min_members: int = 1, max_members: int = 8,
                 p99_high_ms: float = 100.0, p99_low_ms: float = 20.0,
                 queue_high: int = 64, queue_low: int = 4,
                 sustain: int = 3, cooldown_secs: float = 30.0,
                 clock: Callable[[], float] = time.monotonic):
        if min_members < 1 or max_members < min_members:
            raise ValueError("need 1 <= min_members <= max_members")
        self.members_fn = members_fn
        self.scale_up = scale_up
        self.scale_down = scale_down
        self.min_members = min_members
        self.max_members = max_members
        self.p99_high_ms = p99_high_ms
        self.p99_low_ms = p99_low_ms
        self.queue_high = queue_high
        self.queue_low = queue_low
        self.sustain = sustain
        self.cooldown_secs = cooldown_secs
        self.clock = clock
        self._breach_up = 0
        self._breach_down = 0
        self._cooldown_until = -float("inf")
        self._target: Optional[int] = None
        self.actions: List[Dict] = []  # decision log (bench + tests)
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()

    # --------------------------------------------------------- control

    def set_target(self, n: Optional[int]) -> None:
        """Manual override: scale toward `n` (clamped to the bounds)
        regardless of load; None returns control to the load policy."""
        with self._lock:
            self._target = (None if n is None else
                            max(self.min_members,
                                min(self.max_members, int(n))))
            self._breach_up = self._breach_down = 0

    def at_target(self) -> bool:
        with self._lock:
            t = self._target
        return t is None or self.members_fn() == t

    # -------------------------------------------------------- decision

    def _act(self, kind: str, n: int, why: str) -> Optional[str]:
        # The callable runs FIRST and may decline with an explicit False
        # (deployment backpressure: a join/retirement already in flight —
        # see attach_autoscaler). A declined action arms no cooldown and
        # logs nothing; the next tick simply retries.
        acted = (self.scale_up() if kind == "up" else self.scale_down(n))
        if acted is False:
            return None
        now = self.clock()
        self._cooldown_until = now + self.cooldown_secs
        self._breach_up = self._breach_down = 0
        self.actions.append(
            {"action": kind, "members_before": n, "why": why, "t": now})
        return kind

    def observe(self, load: Optional[FleetLoad] = None) -> Optional[str]:
        """One tick: returns "up"/"down" when an action fired, else
        None. `load=None` (no signal yet — obs plane off, no traffic)
        never breaches in either direction but still serves a manual
        target."""
        with self._lock:
            n = self.members_fn()
            now = self.clock()
            cooling = now < self._cooldown_until
            if self._target is not None:
                if n < self._target and not cooling:
                    return self._act("up", n, f"target {self._target}")
                if n > self._target and not cooling:
                    return self._act("down", n, f"target {self._target}")
                if n == self._target:
                    self._target = None  # reached: hand back to load
                return None
            if load is None or load.p99_ms is None:
                return None
            if load.p99_ms > self.p99_high_ms or \
                    load.queue_depth > self.queue_high:
                self._breach_up += 1
                self._breach_down = 0
            elif load.p99_ms < self.p99_low_ms and \
                    load.queue_depth < self.queue_low:
                self._breach_down += 1
                self._breach_up = 0
            else:
                self._breach_up = self._breach_down = 0
            if cooling:
                return None
            if self._breach_up >= self.sustain and n < self.max_members:
                return self._act(
                    "up", n,
                    f"p99={load.p99_ms:.1f}ms q={load.queue_depth} "
                    f"over ({self.p99_high_ms}, {self.queue_high}) "
                    f"x{self._breach_up}")
            if self._breach_down >= self.sustain and n > self.min_members:
                return self._act(
                    "down", n,
                    f"p99={load.p99_ms:.1f}ms q={load.queue_depth} "
                    f"under ({self.p99_low_ms}, {self.queue_low}) "
                    f"x{self._breach_down}")
            return None

    # --------------------------------------------------------- threading

    def start(self, interval: float,
              load_fn: Callable[[], Optional[FleetLoad]]) -> "FleetAutoscaler":
        """Poll `load_fn` every `interval` on a daemon thread (the
        Supervisor-resident deployment shape; `observe` stays callable
        directly for deterministic tests/benches)."""

        import logging

        log = logging.getLogger(__name__)

        def loop():
            while not self._stop.wait(interval):
                try:
                    self.observe(load_fn())
                except Exception:
                    # The loop survives (scaling must never die mid-
                    # deployment; the next tick retries) but the failure
                    # is LOGGED — a config error like attach_autoscaler's
                    # missing --member-name ValueError raising every tick
                    # must be visible, not a silent never-scales wedge.
                    log.warning("autoscaler tick failed", exc_info=True)

        self._thread = threading.Thread(target=loop, daemon=True,
                                        name="fleet-autoscaler")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)
            self._thread = None


def attach_autoscaler(supervisor, registry: FleetRegistry,
                      argv_fn: Callable[[str], Sequence[str]], *,
                      name_prefix: str = "backend",
                      env: Optional[Dict[str, str]] = None,
                      **knobs) -> FleetAutoscaler:
    """Wire a `FleetAutoscaler` into an online `Supervisor` (the
    Supervisor-resident deployment the ROADMAP names):

      * scale UP   — `supervisor.add_spec` of a fresh backend spec built
        by ``argv_fn(member_name)`` (the serving CLI with ``--registry``
        and ``--member-name``; ``--port 0`` means each generation binds
        a fresh port and announces it by lease — discovery IS the
        spawn-ack).
      * scale DOWN — pick the youngest live member the supervisor owns,
        `registry.request_drain(addr)` (retirement: the member stamps
        ``draining``, finishes in-flight, exits 0), and reap its spec
        once the supervisor saw the clean exit.
      * member count — live backend leases in the registry (NOT the
        spec count: a spawned-but-not-yet-serving member shouldn't
        suppress further scale-ups forever; the cooldown paces those).

    Death/wedge handling stays the Supervisor's: a SIGKILLed member is
    respawned on budget and rejoins by lease; a drained member exits 0
    and is released. Returns the autoscaler (call ``observe``/
    ``start`` yourself — pacing belongs to the deployment)."""
    from deeprec_tpu.online.supervisor import ProcessSpec

    counter = {"n": 0}
    draining: Dict[str, str] = {}   # member name -> addr
    pending: Dict[str, float] = {}  # spawned, lease not yet seen -> t0
    join_timeout = knobs.pop("join_timeout_secs", 180.0)

    def members_fn() -> int:
        return len(registry.members(ROLE_BACKEND, include_draining=False))

    def _settle_pending() -> None:
        leased = {m.name for m in registry.members(ROLE_BACKEND)}
        now = time.monotonic()
        for name in list(pending):
            st = supervisor.state(name)
            if (name in leased or st is None or st.gave_up
                    or now - pending[name] > join_timeout):
                # joined, abandoned, or never coming — either way, stop
                # gating scale-ups on it (a silent forever-pending entry
                # would wedge the autoscaler for the process lifetime)
                pending.pop(name)

    def scale_up() -> None:
        # Joining takes seconds (process start + model restore) while
        # autoscaler ticks take fractions of one: without this gate a
        # sustained breach spawns a NEW member every post-cooldown tick
        # until the first one finally leases — the runaway the cooldown
        # alone cannot prevent because it paces ticks, not joins. One
        # join in flight at a time; the next tick retries.
        _settle_pending()
        if pending:
            return False
        counter["n"] += 1
        name = f"{name_prefix}-as{counter['n']}"
        argv = [str(x) for x in argv_fn(name)]
        if "--member-name" not in argv:
            # the join gate matches leases BY NAME: an unnamed member
            # would lease fine yet never settle pending — fail loud at
            # spawn time instead of wedging silently
            raise ValueError(
                "attach_autoscaler: argv_fn(name) must pass --member-name "
                f"(got {argv})")
        pending[name] = time.monotonic()
        supervisor.add_spec(ProcessSpec(
            name=name, argv=argv, lease_secs=None,
            env=env, stdout=None))
        return True

    def reap() -> None:
        """Release the specs of drained members whose processes exited
        cleanly (called before every scale-down and directly by
        deployments at settle points)."""
        for name in list(draining):
            st = supervisor.state(name)
            if st is None or st.done:
                supervisor.remove_spec(name, kill=False)
                registry.clear_drain(draining.pop(name))

    def scale_down(n: int) -> None:
        reap()
        live = {m.name: m for m in registry.members(ROLE_BACKEND)}
        for name in draining:
            m = live.get(name)
            if m is not None and not m.draining:
                # a requested drain hasn't reached its lease yet: the
                # member count still includes it, and acting again off
                # that stale count would over-retire (the join-gate's
                # mirror image). One retirement in flight at a time.
                return False
        owned = {s.name for s in list(supervisor.specs)}
        victims = [m for m in live.values()
                   if not m.draining and m.name in owned
                   and m.name not in draining]
        if not victims:
            return False  # nothing the supervisor owns is retirable now
        victim = max(victims, key=lambda m: m.started_at)  # youngest
        registry.request_drain(victim.addr, respawn=False)
        draining[victim.name] = victim.addr
        return True

    scaler = FleetAutoscaler(members_fn=members_fn, scale_up=scale_up,
                             scale_down=scale_down, **knobs)
    scaler.reap = reap  # spec cleanup handle (no scaling side effects)
    return scaler


def load_from_stats(stats: Dict) -> Optional[FleetLoad]:
    """Decode a frontend ``/v1/stats`` body into the autoscaler's
    observation (None when the snapshot carries no ``fleet_load`` —
    pre-fleet frontends, obs plane off)."""
    fl = stats.get("fleet_load")
    if not fl:
        return None
    return FleetLoad(p99_ms=fl.get("e2e_p99_ms"),
                     queue_depth=int(fl.get("queue_depth") or 0),
                     members=int(fl.get("members") or 0))
