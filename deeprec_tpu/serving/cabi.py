"""Python side of the serving C ABI (native/processor.cpp).

The reference exposes its serving stack to external RPC frameworks (EAS,
custom frontends) through a 4-function C ABI —
``initialize(model_entry, model_config, &state)`` / ``process`` /
``batch_process`` / ``get_serving_model_info``
(/root/reference/serving/processor/serving/processor.h). This framework
keeps the SAME symbol contract so a host written against it can load
``libdeeprec_processor.so`` instead. Payloads may be the reference's
protobuf wire format (serialized ``tensorflow.eas.PredictRequest`` ->
``PredictResponse``, decoded by :mod:`predict_pb`) or JSON
(``{"features": {...}}``); the format is sniffed per request. The one
remaining substitution: the model graph comes from the modelzoo registry
+ a checkpoint dir rather than a SavedModel bundle.

The C layer embeds CPython and forwards to the three functions below; all
serving logic (validation, coalescing, hot-swap polling, warmup) is the
ordinary Python stack, so every frontend — HTTP, C ABI, in-process —
behaves identically.

Config JSON accepted by :func:`create_server` (= the C ``model_config``):

    {
      "model": "wdl",                  # modelzoo registry name
      "ckpt_dir": "/path/to/ckpts",    # required
      "model_args": {"emb_dim": 16, "capacity": 1048576},
      "max_batch": 256,                # ModelServer coalescing cap (ROWS)
      "max_wait_ms": 2.0,              # coalescing deadline upper bound
      "adaptive": true,                # arrival-rate-tuned deadline (EWMA)
      "poll_secs": 10.0,               # 0 disables background hot-swap
      "warmup": false                  # precompile every batch bucket
    }
"""
from __future__ import annotations

import json
from typing import Tuple

import numpy as np

from deeprec_tpu.serving.predictor import (
    BadRequest,
    ModelServer,
    Predictor,
    parse_features,
)


def create_server(config_json: str) -> ModelServer:
    cfg = json.loads(config_json)
    if "ckpt_dir" not in cfg:
        raise ValueError("model_config must set 'ckpt_dir'")
    from deeprec_tpu.models.registry import build_model

    model = build_model(cfg.get("model", "wdl"), **cfg.get("model_args", {}))
    pred = Predictor(model, cfg["ckpt_dir"])
    server = ModelServer(
        pred,
        max_batch=int(cfg.get("max_batch", 256)),
        max_wait_ms=float(cfg.get("max_wait_ms", 2.0)),
        poll_updates_secs=float(cfg.get("poll_secs", 0.0)),
        adaptive=bool(cfg.get("adaptive", True)),
    )
    if cfg.get("warmup"):
        example = _synth_example(pred)
        server.warmup(example)
    return server


def _synth_example(pred: Predictor) -> dict:
    """One all-zeros row per feature — enough to trace every bucket shape."""
    out = {}
    specs = {f.name: f for f in pred._trainer.sparse_specs}
    dense = {f.name: f for f in pred._trainer.dense_specs}
    for name, dt in pred.feature_dtypes.items():
        if dt.kind in "iu":
            L = specs[name].max_len or 1
            out[name] = np.zeros((1, L), dt)
        else:
            # Warmup must trace the REAL dense width, not assume 1 — a
            # width-W feature warmed at width 1 would compile a useless
            # bucket and recompile (or fail) on the first live request.
            w = dense[name].width if name in dense else 1
            out[name] = np.zeros((1, w), np.float32)
    return out


def process_request(server: ModelServer, payload: bytes) -> Tuple[int, bytes]:
    """Wire-format dispatch for the C ABI: a JSON object (first
    non-whitespace byte ``{``) takes the JSON path; anything else is
    parsed as a serialized ``tensorflow.eas.PredictRequest`` — the
    reference's native wire format (predict.proto, message_coding.cc) —
    so a host built against the reference processor can call this library
    with its protobuf payloads unchanged. A valid protobuf message never
    begins with RAW byte 0x7b ('{'): that would be field 15 wire-type 3,
    a group start, which protoc never emits for proto3. The sniff must
    NOT strip whitespace first — protobuf tag/length bytes 0x09-0x0d/0x20
    are ASCII whitespace (e.g. a tag byte of 0x0a is '\\n'), so stripping
    can expose a '{' from inside a valid message. Whitespace-prefixed
    JSON still works via the fallback below."""
    if not payload or payload[:1] == b"{":
        return process_json(server, payload)
    if payload.lstrip()[:1] == b"{":
        # Ambiguous: whitespace-prefixed '{' is either JSON or a protobuf
        # whose first tag byte happens to be ASCII whitespace. Proto3
        # "successfully" parses many JSON-ish byte strings by skipping
        # unknown fields, yielding an empty-inputs request and a misleading
        # parse_features 400 — so the proto path wins only when the parse
        # yields actual inputs; otherwise a payload that IS a JSON object
        # routes to the JSON path, and non-JSON bytes keep the protobuf
        # path's error reporting (e.g. an inputs-less proto request still
        # 400s with the proto-side message).
        from deeprec_tpu.serving import predict_pb as pb

        try:
            has_inputs = bool(pb.PredictRequest.parse(bytes(payload)).inputs)
        except Exception:
            has_inputs = False
        if not has_inputs:
            try:
                is_json = isinstance(json.loads(payload), dict)
            except Exception:
                is_json = False
            if is_json:
                return process_json(server, payload)
    return process_proto(server, payload)


def process_proto(server: ModelServer, payload: bytes) -> Tuple[int, bytes]:
    """PredictRequest in, PredictResponse out. Error bodies are plain-text
    messages (the reference returns strndup'd error strings, not protobuf,
    on non-200 — processor.cc:38-46)."""
    from deeprec_tpu.serving import predict_pb as pb

    try:
        req = pb.PredictRequest.parse(bytes(payload))
        feats = {k: v.to_numpy() for k, v in req.inputs.items()}
    except Exception as e:
        return 400, f"bad PredictRequest: {e}".encode()
    try:
        batch = parse_features(server.predictor, feats)
    except BadRequest as e:
        return 400, json.dumps(e.details).encode()
    except ValueError as e:
        return 400, str(e).encode()
    try:
        probs = server.request(batch)
        items = (
            list(probs.items())
            if isinstance(probs, dict)
            else [("probabilities", probs)]
        )
        outputs = {
            k: pb.ArrayProto.from_numpy(np.asarray(v))
            for k, v in items
            if not req.output_filter or k in req.output_filter
        }
        if not outputs:
            known = sorted(k for k, _ in items)
            return 400, (
                f"output_filter {req.output_filter} matches none of "
                f"{known}".encode()
            )
        return 200, pb.PredictResponse(outputs).serialize()
    except Exception as e:
        return 500, str(e).encode()


def process_json(server: ModelServer, payload: bytes) -> Tuple[int, bytes]:
    """One request through the coalescing queue. Returns (status, body):
    200 on success, 400 on a client error, 500 on a serving error — the
    C return code, mirroring the HTTP frontend's codes."""
    try:
        req = json.loads(payload or b"{}")
    except Exception as e:
        return 400, json.dumps({"error": f"bad json: {e}"}).encode()
    try:
        if not isinstance(req, dict):
            raise BadRequest("body must be a JSON object")
        batch = parse_features(server.predictor, req.get("features"))
    except BadRequest as e:
        return 400, json.dumps(e.details).encode()
    except ValueError as e:
        return 400, json.dumps({"error": str(e)}).encode()
    try:
        probs, version = server.request_versioned(batch)
        out = (
            {k: np.asarray(v).tolist() for k, v in probs.items()}
            if isinstance(probs, dict)
            else np.asarray(probs).tolist()
        )
        return 200, json.dumps(
            {"predictions": out, "model_version": version}
        ).encode()
    except Exception as e:
        return 500, json.dumps({"error": str(e)}).encode()


def model_info_json(server: ModelServer) -> Tuple[int, bytes]:
    try:
        return 200, json.dumps(server.predictor.model_info()).encode()
    except Exception as e:
        return 500, json.dumps({"error": str(e)}).encode()
