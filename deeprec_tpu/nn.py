"""Minimal functional NN layer library for the modelzoo.

Plain pytree params + pure apply functions — no framework dependency, full
control of dtypes (bf16 compute / f32 params, the TPU translation of
DeepRec's BFloat16 scope: docs/docs_en/BFloat16.md, usage
modelzoo/wide_and_deep/train.py:187-199). All matmuls carry
preferred_element_type=float32 so the MXU accumulates in f32.

Layers cover the reference modelzoo's building blocks: MLP towers, DIN's
local-activation attention (modelzoo/din), DIEN's GRU/AUGRU (modelzoo/dien),
BST's transformer block (modelzoo/bst), DCN's cross network (modelzoo/dcnv2),
DeepFM's FM layer and DLRM's dot interaction.
"""
from __future__ import annotations

from typing import Any, Dict, Optional, Sequence

import jax
import jax.numpy as jnp

Params = Dict[str, Any]


def _glorot(key, shape, dtype=jnp.float32):
    fan_in, fan_out = shape[0], shape[-1]
    lim = jnp.sqrt(6.0 / (fan_in + fan_out))
    return jax.random.uniform(key, shape, dtype, -lim, lim)


def matmul(x, w):
    return jnp.dot(x, w, preferred_element_type=jnp.float32)


# ----------------------------------------------------------------- dense / MLP


def dense_init(key, in_dim: int, out_dim: int) -> Params:
    kw, _ = jax.random.split(key)
    return {"w": _glorot(kw, (in_dim, out_dim)), "b": jnp.zeros((out_dim,))}


def dense_apply(p: Params, x, compute_dtype=jnp.bfloat16):
    y = matmul(x.astype(compute_dtype), p["w"].astype(compute_dtype))
    return y.astype(jnp.float32) + p["b"]


def mlp_init(key, in_dim: int, hidden: Sequence[int]) -> Params:
    keys = jax.random.split(key, len(hidden))
    layers = []
    d = in_dim
    for k, h in zip(keys, hidden):
        layers.append(dense_init(k, d, h))
        d = h
    return {"layers": layers}


def mlp_apply(p: Params, x, activation=jax.nn.relu, final_activation=None,
              compute_dtype=jnp.bfloat16):
    n = len(p["layers"])
    for i, layer in enumerate(p["layers"]):
        x = dense_apply(layer, x, compute_dtype)
        if i < n - 1:
            x = activation(x)
        elif final_activation is not None:
            x = final_activation(x)
    return x


def layernorm_init(dim: int) -> Params:
    return {"g": jnp.ones((dim,)), "b": jnp.zeros((dim,))}


def layernorm_apply(p: Params, x, eps=1e-6):
    mu = jnp.mean(x, axis=-1, keepdims=True)
    var = jnp.var(x, axis=-1, keepdims=True)
    return (x - mu) * jax.lax.rsqrt(var + eps) * p["g"] + p["b"]


# ------------------------------------------------------- DIN attention pooling


def din_attention_init(key, dim: int, hidden: Sequence[int] = (36,)) -> Params:
    # scorer input: [item, hist, item-hist, item*hist]
    return {"mlp": mlp_init(key, 4 * dim, list(hidden) + [1])}


def din_attention_apply(p: Params, query, keys, mask):
    """DIN local activation unit (modelzoo/din/train.py attention):
    query [B, D] target item, keys [B, L, D] behavior sequence."""
    B, L, D = keys.shape
    q = jnp.broadcast_to(query[:, None, :], (B, L, D))
    feats = jnp.concatenate([q, keys, q - keys, q * keys], axis=-1)
    scores = mlp_apply(p["mlp"], feats.reshape(B * L, 4 * D)).reshape(B, L)
    scores = jnp.where(mask, scores, -1e9)
    w = jax.nn.softmax(scores, axis=1)
    w = jnp.where(mask, w, 0.0)
    return jnp.einsum("bl,bld->bd", w, keys)


# ----------------------------------------------------------------- GRU / AUGRU


def gru_init(key, in_dim: int, hid: int) -> Params:
    k1, k2, k3 = jax.random.split(key, 3)
    return {
        "wz": _glorot(k1, (in_dim + hid, hid)),
        "wr": _glorot(k2, (in_dim + hid, hid)),
        "wh": _glorot(k3, (in_dim + hid, hid)),
        "bz": jnp.zeros((hid,)),
        "br": jnp.zeros((hid,)),
        "bh": jnp.zeros((hid,)),
    }


def _gru_cell(p, h, x, att: Optional[jnp.ndarray] = None):
    xh = jnp.concatenate([x, h], axis=-1)
    z = jax.nn.sigmoid(matmul(xh, p["wz"]) + p["bz"])
    r = jax.nn.sigmoid(matmul(xh, p["wr"]) + p["br"])
    xrh = jnp.concatenate([x, r * h], axis=-1)
    hh = jnp.tanh(matmul(xrh, p["wh"]) + p["bh"])
    if att is not None:
        # AUGRU: attention scales the update gate (DIEN,
        # modelzoo/dien/train.py "augru")
        z = att[:, None] * z
    return (1.0 - z) * h + z * hh


def gru_apply(p: Params, xs, mask, att=None):
    """Run a (AU)GRU over [B, L, D] with [B, L] mask via lax.scan.

    Returns final hidden state [B, H] and all hidden states [B, L, H].
    Masked positions carry the previous state through (standard padded-seq
    handling, compiler-friendly — no dynamic lengths).
    """
    B, L, D = xs.shape
    H = p["bz"].shape[0]
    h0 = jnp.zeros((B, H), jnp.float32)

    def step(h, inp):
        x, m, a = inp
        h_new = _gru_cell(p, h, x, a)
        h = jnp.where(m[:, None], h_new, h)
        return h, h

    xs_t = jnp.moveaxis(xs, 1, 0)  # [L, B, D]
    mask_t = jnp.moveaxis(mask, 1, 0)
    att_t = (
        jnp.moveaxis(att, 1, 0)
        if att is not None
        else jnp.ones((L, B), jnp.float32)
    )
    h_final, hs = jax.lax.scan(step, h0, (xs_t, mask_t, att_t))
    return h_final, jnp.moveaxis(hs, 0, 1)


# ------------------------------------------------------------ transformer (BST)


def transformer_block_init(key, dim: int, heads: int, ff: int) -> Params:
    # NB: `heads` stays static config (apply arg), NOT a params leaf — ints in
    # the differentiated pytree would crash jax.grad.
    k1, k2, k3, k4 = jax.random.split(key, 4)
    return {
        "qkv": _glorot(k1, (dim, 3 * dim)),
        "proj": _glorot(k2, (dim, dim)),
        "ff1": dense_init(k3, dim, ff),
        "ff2": dense_init(k4, ff, dim),
        "ln1": layernorm_init(dim),
        "ln2": layernorm_init(dim),
    }


def transformer_block_apply(p: Params, x, mask, heads: int, flash: bool = False):
    """Post-LN transformer encoder block with padding mask: x [B, L, D].

    flash=True routes attention through the Pallas flash kernel (O(L·block)
    memory — for long behavior histories; L must be a multiple of 128)."""
    B, L, D = x.shape
    H = heads
    qkv = matmul(x, p["qkv"]).reshape(B, L, 3, H, D // H)
    q, k, v = qkv[:, :, 0], qkv[:, :, 1], qkv[:, :, 2]  # [B, L, H, Dh]
    if flash:
        from deeprec_tpu.ops.flash_attention import flash_attention

        blk = 128
        Lp = ((L + blk - 1) // blk) * blk
        pad = Lp - L
        qh = jnp.moveaxis(q, 2, 1)  # [B, H, L, Dh]
        kh = jnp.moveaxis(k, 2, 1)
        vh = jnp.moveaxis(v, 2, 1)
        if pad:
            zeros = ((0, 0), (0, 0), (0, pad), (0, 0))
            qh = jnp.pad(qh, zeros)
            kh = jnp.pad(kh, zeros)
            vh = jnp.pad(vh, zeros)
            fmask = jnp.pad(mask, ((0, 0), (0, pad)))
        else:
            fmask = mask
        out = jnp.moveaxis(flash_attention(qh, kh, vh, fmask), 1, 2)
        out = out[:, :L].reshape(B, L, D)
    else:
        from deeprec_tpu.ops.flash_attention import attention_reference

        out = attention_reference(
            jnp.moveaxis(q, 2, 1), jnp.moveaxis(k, 2, 1), jnp.moveaxis(v, 2, 1),
            mask,
        )
        out = jnp.moveaxis(out, 1, 2).reshape(B, L, D)
    x = layernorm_apply(p["ln1"], x + matmul(out, p["proj"]))
    ff = dense_apply(p["ff2"], jax.nn.relu(dense_apply(p["ff1"], x)))
    x = layernorm_apply(p["ln2"], x + ff)
    return jnp.where(mask[..., None], x, 0.0)


# -------------------------------------------------------------- DCN cross net


def crossnet_init(key, dim: int, depth: int) -> Params:
    keys = jax.random.split(key, depth)
    return {
        "layers": [
            {"w": _glorot(k, (dim, dim)), "b": jnp.zeros((dim,))} for k in keys
        ]
    }


def crossnet_apply(p: Params, x0):
    """DCNv2 cross layer: x_{l+1} = x0 * (W x_l + b) + x_l
    (modelzoo/dcnv2/train.py)."""
    x = x0
    for layer in p["layers"]:
        x = x0 * (matmul(x, layer["w"]) + layer["b"]) + x
    return x


def crossnet_v1_init(key, dim: int, depth: int) -> Params:
    keys = jax.random.split(key, depth)
    return {
        "layers": [
            {"w": _glorot(k, (dim, 1))[:, 0], "b": jnp.zeros((dim,))}
            for k in keys
        ]
    }


def crossnet_v1_apply(p: Params, x0):
    """Original DCN cross layer with VECTOR weights:
    x_{l+1} = x0 * (x_l . w) + b + x_l  (modelzoo/dcn/train.py) —
    rank-1 feature crossing, O(dim) params per layer vs v2's O(dim^2)."""
    x = x0
    for layer in p["layers"]:
        x = x0 * (x @ layer["w"])[:, None] + layer["b"] + x
    return x


# ------------------------------------------------------------------- FM / dot


def fm_apply(emb_stack):
    """Second-order FM interaction over [B, F, D] field embeddings
    (DeepFM, modelzoo/deepfm): 0.5 * ((Σv)² − Σv²) summed over D."""
    s = jnp.sum(emb_stack, axis=1)
    sq = jnp.sum(emb_stack * emb_stack, axis=1)
    return 0.5 * jnp.sum(s * s - sq, axis=1, keepdims=True)


def dot_interaction(emb_stack, keep_diag: bool = False):
    """DLRM pairwise dot interactions over [B, F, D] -> [B, F*(F-1)/2]."""
    B, F, D = emb_stack.shape
    z = jnp.einsum("bfd,bgd->bfg", emb_stack, emb_stack)
    i, j = jnp.triu_indices(F, k=0 if keep_diag else 1)
    return z[:, i, j]


# ------------------------------------------------- sample-aware compression


def group_compress(group_ids, num_groups: int):
    """Dedup rows by a group id (user id) for sample-aware compression.

    The general form of the reference's Sample-awared Graph Compression
    (docs/docs_en/Sample-awared-Graph-Compression.md): ranking batches are
    packed as <user, N candidate items>, so user-side compute repeated N
    times is waste. `num_groups` is the static maximum distinct groups per
    batch (the packer's G).

    Returns (first_ix [G], inverse [B], ok [B]): `x[first_ix]` is one
    representative row per group, `out[inverse]` broadcasts per-group
    results back to the batch, and `ok` marks rows whose group made the
    cut — rows of overflow groups (a packer bug) have ok=False and MUST
    NOT silently receive another group's output.
    """
    group_ids = group_ids.reshape(-1)
    uids, first_ix, inverse = jnp.unique(
        group_ids, size=num_groups, return_index=True, return_inverse=True,
        fill_value=group_ids[0],
    )
    inverse = inverse.reshape(-1)
    ok = inverse < num_groups
    return first_ix, jnp.where(ok, inverse, 0), ok


def apply_grouped(fn, inputs, group_ids, num_groups: int):
    """Run `fn` once per distinct group and broadcast results to the batch:
    fn(tree with leading dim G) on rows deduped by group_ids [B]; output
    leaves regain leading dim B. Equal to fn(full batch) row-for-row when
    fn is row-independent — with G/B of the compute.

    Rows whose group overflowed num_groups come back as NaN: a packer that
    violates its G must fail loudly, not serve one user's scores to
    another."""
    first_ix, inverse, ok = group_compress(group_ids, num_groups)
    compact = jax.tree.map(lambda a: a[first_ix], inputs)
    out = fn(compact)

    def broadcast(a):
        rows = a[inverse]
        mask = ok.reshape(ok.shape + (1,) * (rows.ndim - 1))
        return jnp.where(mask, rows, jnp.nan)

    return jax.tree.map(broadcast, out)
