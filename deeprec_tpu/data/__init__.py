from deeprec_tpu.data.synthetic import (
    CriteoStats,
    SyntheticBehaviorSequence,
    SyntheticCriteo,
    SyntheticMultiTask,
    SyntheticTwoTower,
)
from deeprec_tpu.data.readers import (
    CriteoCSVReader,
    ParquetReader,
    criteo_block_parse,
    criteo_hash_salts,
)
from deeprec_tpu.data.pipeline import ParallelInputPipeline, plan_shards
from deeprec_tpu.data.prefetch import Prefetcher, staged
from deeprec_tpu.data.work_queue import WorkQueue, parse_slice
from deeprec_tpu.data.stream import FileStreamServer, FileTailReader, TCPStreamReader
from deeprec_tpu.data.kafka import KafkaClient, KafkaStreamReader
