"""WorkQueue: dynamic work-item sharding with checkpointable state.

Parity with DeepRec's WorkQueue (python/ops/work_queue.py, spec
docs/docs_en/WorkQueue.md): a global queue of work items (file names, file
slices) that workers `take()` from dynamically — slow workers take fewer
items, which is the straggler mitigation and the elasticity primitive
(workers can join/leave between takes). Supports epochs, shuffling, slicing
and save/restore.

Two modes:
  * in-process (default): plain thread-safe queue.
  * file-coordinated: a shared JSON state file + lockfile lets N independent
    host processes (multi-host TPU workers on a shared FS) take disjoint
    items — the TPU stand-in for the PS-hosted queue resource.
"""
from __future__ import annotations

import fcntl
import json
import os
import random
import tempfile
import threading
from typing import Callable, Iterator, List, Optional, Sequence


class WorkQueue:
    def __init__(
        self,
        works: Sequence[str],
        num_epochs: int = 1,
        shuffle: bool = True,
        seed: int = 0,
        num_slices: int = 1,
        coordination_file: Optional[str] = None,
    ):
        """num_slices > 1 splits each work item into `item#slice/total` —
        DeepRec's sliced-file sharding for large files."""
        items: List[str] = []
        for epoch in range(num_epochs):
            epoch_items = []
            for w in works:
                for s in range(num_slices):
                    epoch_items.append(
                        f"{w}#{s}/{num_slices}" if num_slices > 1 else w
                    )
            if shuffle:
                rng = random.Random(seed + epoch)
                rng.shuffle(epoch_items)
            items.extend(epoch_items)
        self._items = items
        self._cursor = 0
        self._lock = threading.Lock()
        self._coord = coordination_file
        # Test seam: called with (file_object, serialized_json) INSTEAD of
        # the final write inside the atomic commit — lets fault tests
        # emulate a worker killed mid-write (write partial bytes, raise)
        # and pin that concurrent takers never observe a torn file.
        self.on_coord_write: Optional[Callable] = None
        if self._coord and not os.path.exists(self._coord):
            self._write_coord({"cursor": 0, "items": items})

    # ------------------------------------------------------------ in-process

    def take(self) -> Optional[str]:
        """Next work item, or None when exhausted."""
        if self._coord:
            return self._take_coordinated()
        with self._lock:
            if self._cursor >= len(self._items):
                return None
            item = self._items[self._cursor]
            self._cursor += 1
            return item

    def size(self) -> int:
        if self._coord:
            st = self._read_coord()
            return len(st["items"]) - st["cursor"]
        with self._lock:
            return len(self._items) - self._cursor

    def __iter__(self) -> Iterator[str]:
        while True:
            item = self.take()
            if item is None:
                return
            yield item

    # ------------------------------------------------------- save / restore

    def save(self) -> dict:
        """Checkpointable state (WorkQueueSave parity)."""
        if self._coord:
            return self._read_coord()
        with self._lock:
            return {"cursor": self._cursor, "items": self._items}

    def restore(self, state: dict) -> None:
        if self._coord:
            self._write_coord(state)
            return
        with self._lock:
            self._items = list(state["items"])
            self._cursor = int(state["cursor"])

    # ----------------------------------------------------------- datasets

    def input_dataset(self, batch_size: int = 2048, reader_cls=None,
                      **reader_kw):
        """Stream parsed batches from taken work items — the
        `WorkQueue.input_dataset()` analog (work_queue.py API,
        docs/docs_en/WorkQueue.md): each `take()` yields a file (or a
        `path#k/n` slice), read with CriteoCSVReader (or `reader_cls`).
        Sliced items read only their byte range's complete lines."""
        from deeprec_tpu.data.readers import CriteoCSVReader

        reader_cls = reader_cls or CriteoCSVReader
        # Slices are usually smaller than a batch; a per-slice reader that
        # drops remainders could silently deliver NOTHING. Deliver every
        # row unless the caller explicitly asks otherwise.
        reader_kw.setdefault("drop_remainder", False)

        def gen():
            for item in self:
                path, k, n = parse_slice(item)
                if n == 1:
                    yield from reader_cls([path], batch_size, **reader_kw)
                else:
                    yield from reader_cls(
                        [path], batch_size,
                        byte_range=self._slice_range(path, k, n), **reader_kw
                    )

        return gen()

    @staticmethod
    def _slice_range(path, k, n):
        """Line-snapped byte range of the k-th of n slices: boundaries snap
        forward to line starts so each line belongs to exactly one slice."""
        size = os.path.getsize(path)
        lo = size * k // n
        hi = size * (k + 1) // n
        with open(path, "rb") as f:
            if lo:
                f.seek(lo - 1)
                f.readline()  # consume the partial line (previous slice's)
                lo = f.tell()
            if hi:
                f.seek(hi - 1)
                f.readline()
                hi = f.tell()
        return lo, hi

    # ------------------------------------------------- file-coordinated mode

    def _with_lock(self, fn):
        lock_path = self._coord + ".lock"
        with open(lock_path, "w") as lf:
            fcntl.flock(lf, fcntl.LOCK_EX)
            try:
                return fn()
            finally:
                fcntl.flock(lf, fcntl.LOCK_UN)

    def _read_coord(self) -> dict:
        def read():
            with open(self._coord) as f:
                return json.load(f)

        return self._with_lock(read)

    def _commit_coord(self, state: dict) -> None:
        """Atomically replace the shared cursor file. MUST be the only
        writer of `self._coord` (call under `_with_lock`).

        A worker killed at ANY point in here leaves the previous coord
        file intact: the new JSON lands in a uniquely named tempfile in
        the same directory, is fsync'd, and only then renamed over the
        target (rename is atomic on POSIX) — other workers either see the
        old complete state or the new complete state, never a torn JSON
        that would strand every taker on a parse error. Orphaned `.wq-*`
        temps from killed writers are inert (never matched by readers)."""
        dirname = os.path.dirname(self._coord) or "."
        fd, tmp = tempfile.mkstemp(dir=dirname, prefix=".wq-", suffix=".tmp")
        try:
            data = json.dumps(state)
            with os.fdopen(fd, "w") as f:
                if self.on_coord_write is not None:
                    self.on_coord_write(f, data)  # fault-injection seam
                else:
                    f.write(data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._coord)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise

    def _write_coord(self, state: dict) -> None:
        self._with_lock(lambda: self._commit_coord(state))

    def _take_coordinated(self) -> Optional[str]:
        def take():
            with open(self._coord) as f:
                st = json.load(f)
            if st["cursor"] >= len(st["items"]):
                return None
            item = st["items"][st["cursor"]]
            st["cursor"] += 1
            self._commit_coord(st)
            return item

        return self._with_lock(take)


def parse_slice(item: str):
    """'path#k/n' -> (path, k, n); plain items -> (item, 0, 1)."""
    if "#" not in item:
        return item, 0, 1
    path, frac = item.rsplit("#", 1)
    k, n = frac.split("/")
    return path, int(k), int(n)
