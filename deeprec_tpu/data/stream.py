"""Streaming input: follow an append-only log with resumable offsets.

The Kafka-analog (reference core/kernels/data/kafka_dataset_op.cc): DeepRec
consumes record streams with consumer offsets so training resumes where it
stopped. On a TPU pod the pragmatic stand-in is an append-only file (or a
directory of them) fed by a log shipper; this reader tails it, parses
complete newline-terminated lines into batches, and exposes offset
save/restore with Kafka-offset semantics: the offset only advances past rows
that have been YIELDED, so a checkpoint/crash/restore cycle is exactly-once
with respect to delivered batches.

Records must be '\n'-terminated; an incomplete trailing line is left
unconsumed until its newline arrives (or ignored at stop_at_eof).
"""
from __future__ import annotations

import os
import time
from typing import Callable, Dict, Iterator, Optional

import numpy as np


class FileTailReader:
    """Tail `path`, yielding batches of parsed lines.

    parser(lines: list[str]) -> batch dict (defaults to Criteo TSV with the
    same id hashing as data/readers.py). `poll_secs` controls the wait when
    caught up; `stop_at_eof` makes it behave like a bounded dataset."""

    def __init__(
        self,
        path: str,
        batch_size: int = 2048,
        parser: Optional[Callable] = None,
        poll_secs: float = 0.5,
        stop_at_eof: bool = False,
        num_dense: int = 13,
        num_cat: int = 26,
    ):
        self.path = path
        self.B = batch_size
        self.parser = parser or self._default_parser
        self.poll_secs = poll_secs
        self.stop_at_eof = stop_at_eof
        self.num_dense = num_dense
        self.num_cat = num_cat
        self.offset = 0  # byte offset of the next un-YIELDED record

    # ------------------------------------------------------------- offsets

    def save(self) -> dict:
        """Checkpointable consumer position (Kafka offset analog)."""
        return {"path": self.path, "offset": self.offset}

    def restore(self, state: dict, allow_path_mismatch: bool = False) -> None:
        if not allow_path_mismatch and state.get("path") not in (None, self.path):
            raise ValueError(
                f"offset checkpoint is for {state['path']!r}, reader tails "
                f"{self.path!r}; a byte offset is meaningless across files "
                "(pass allow_path_mismatch=True to force)"
            )
        self.offset = int(state["offset"])

    # -------------------------------------------------------------- parser

    def _default_parser(self, lines):
        from deeprec_tpu.data.readers import _hash_strings

        n = len(lines)
        labels = np.zeros(n, np.float32)
        dense = np.zeros((n, self.num_dense), np.float32)
        cat_cols = [np.empty(n, object) for _ in range(self.num_cat)]
        for r, line in enumerate(lines):
            parts = line.split("\t")
            labels[r] = float(parts[0] or 0)
            for i in range(self.num_dense):
                v = parts[1 + i] if len(parts) > 1 + i else ""
                dense[r, i] = float(v) if v else 0.0
            for i in range(self.num_cat):
                j = 1 + self.num_dense + i
                cat_cols[i][r] = parts[j] if len(parts) > j else ""
        out: Dict[str, np.ndarray] = {"label": labels}
        for i in range(self.num_dense):
            out[f"I{i+1}"] = dense[:, i : i + 1]
        for i in range(self.num_cat):
            # same hash as the batch readers: ids stay interchangeable
            out[f"C{i+1}"] = _hash_strings(
                cat_cols[i], salt=(i + 1) * 0x9E3779B9 & 0x7FFFFFFF
            )
        return out

    # ------------------------------------------------------------- iterate

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        CHUNK = max(1 << 20, self.B * 512)
        chunk = CHUNK
        while True:
            size = os.path.getsize(self.path) if os.path.exists(self.path) else 0
            made_progress = False
            if size > self.offset:
                with open(self.path, "rb") as f:
                    f.seek(self.offset)
                    data = f.read(min(chunk, size - self.offset))
                last_nl = data.rfind(b"\n")
                if last_nl >= 0:
                    rows = data[: last_nl + 1].split(b"\n")[:-1]
                    at_end = self.offset + len(data) >= size
                    i = 0
                    while i < len(rows):
                        batch_rows = rows[i : i + self.B]
                        full = len(batch_rows) == self.B
                        final_flush = (
                            self.stop_at_eof and at_end and i + self.B >= len(rows)
                        )
                        if not full and not final_flush:
                            break  # wait for more data; offset stays put
                        nbytes = sum(len(r) + 1 for r in batch_rows)
                        # Advance BEFORE yielding (generator suspension would
                        # otherwise leave save() not covering a batch the
                        # consumer already holds): offsets mean "everything
                        # handed out so far", Kafka consumer semantics.
                        self.offset += nbytes
                        made_progress = True
                        i += len(batch_rows)
                        yield self.parser(
                            [r.decode(errors="replace") for r in batch_rows]
                        )
                if made_progress:
                    chunk = CHUNK
                elif self.offset + len(data) < size:
                    # Window exhausted without yielding a batch while more
                    # bytes already sit on disk — a record (or whole batch)
                    # longer than the window. Widen and retry instead of
                    # re-reading the same bytes forever.
                    chunk *= 2
                    continue
            if self.stop_at_eof and not made_progress:
                # nothing (more) consumable: either fully drained or only an
                # unterminated partial line remains — stop either way.
                return
            if not made_progress:
                time.sleep(self.poll_secs)  # no busy loop on partial lines