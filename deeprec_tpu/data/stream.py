"""Streaming input: record streams with resumable consumer offsets.

The Kafka-analog (reference core/kernels/data/kafka_dataset_op.cc): DeepRec
consumes record streams with consumer offsets so training resumes where it
stopped. Two transports, one offset contract:

  * `FileTailReader` — tail an append-only file on a shared FS (the common
    TPU-pod deployment: a log shipper lands records on GCS/NFS).
  * `TCPStreamReader` — consume a newline-framed TCP stream from a broker
    (`FileStreamServer` is the bundled broker: it serves a file from any
    requested offset and follows appends, so crash/resume is testable with
    real sockets).

Offset semantics (both): the offset only advances past rows that have been
YIELDED, so a checkpoint/crash/restore cycle is exactly-once with respect
to delivered batches. Records must be '\n'-terminated; an incomplete
trailing line is left unconsumed until its newline arrives.
"""
from __future__ import annotations

import os
import random
import socket
import socketserver
import threading
import time
from typing import Callable, Dict, Iterator, Optional

import numpy as np

from deeprec_tpu.utils import backoff


def criteo_line_parser(num_dense: int = 13, num_cat: int = 26,
                       errors=None) -> Callable:
    """Default record parser shared by the stream readers: Criteo TSV lines
    -> batch dict, with the same id hashing as data/readers.py.

    Garbage-tolerant by contract (the firewall's first line): an
    unparseable label/float clamps to 0, a non-finite value clamps to 0,
    and every clamp counts into `errors` (data/readers.py RecordErrors)
    by kind — one bad field must never kill the reader thread that
    feeds a live training loop."""

    def parse(lines):
        from deeprec_tpu.data.readers import _hash_strings

        n = len(lines)
        labels = np.zeros(n, np.float32)
        dense = np.zeros((n, num_dense), np.float32)
        cat_cols = [np.empty(n, object) for _ in range(num_cat)]
        for r, line in enumerate(lines):
            parts = line.split("\t")
            try:
                labels[r] = float(parts[0] or 0)  # noqa: DRT002 — host text parse, pre-device
            except (TypeError, ValueError):
                labels[r] = 0.0
                if errors is not None:
                    errors.count("bad_label")
            for i in range(num_dense):
                v = parts[1 + i] if len(parts) > 1 + i else ""
                try:
                    dense[r, i] = float(v) if v else 0.0  # noqa: DRT002 — host text parse, pre-device
                except (TypeError, ValueError):
                    dense[r, i] = 0.0
                    if errors is not None:
                        errors.count("bad_float")
            for i in range(num_cat):
                j = 1 + num_dense + i
                cat_cols[i][r] = parts[j] if len(parts) > j else ""
        bad_label = ~np.isfinite(labels)
        if bad_label.any():
            labels[bad_label] = 0.0
            if errors is not None:
                errors.count("nonfinite_float", int(bad_label.sum()))  # noqa: DRT002 — host numpy count, pre-device
        bad = ~np.isfinite(dense)
        if bad.any():
            dense[bad] = 0.0
            if errors is not None:
                errors.count("nonfinite_float", int(bad.sum()))  # noqa: DRT002 — host numpy count, pre-device
        out: Dict[str, np.ndarray] = {"label": labels}
        for i in range(num_dense):
            out[f"I{i+1}"] = dense[:, i : i + 1]
        for i in range(num_cat):
            out[f"C{i+1}"] = _hash_strings(
                cat_cols[i], salt=(i + 1) * 0x9E3779B9 & 0x7FFFFFFF
            )
        return out

    return parse


class FileTailReader:
    """Tail `path`, yielding batches of parsed lines.

    parser(lines: list[str]) -> batch dict (defaults to Criteo TSV with the
    same id hashing as data/readers.py). `poll_secs` controls the wait when
    caught up; `stop_at_eof` makes it behave like a bounded dataset."""

    def __init__(
        self,
        path: str,
        batch_size: int = 2048,
        parser: Optional[Callable] = None,
        poll_secs: float = 0.5,
        stop_at_eof: bool = False,
        num_dense: int = 13,
        num_cat: int = 26,
    ):
        self.path = path
        self.B = batch_size
        self.parser = parser or criteo_line_parser(num_dense, num_cat)
        self.poll_secs = poll_secs
        self.stop_at_eof = stop_at_eof
        self.num_dense = num_dense
        self.num_cat = num_cat
        self.offset = 0  # byte offset of the next un-YIELDED record

    # ------------------------------------------------------------- offsets

    def save(self) -> dict:
        """Checkpointable consumer position (Kafka offset analog)."""
        return {"path": self.path, "offset": self.offset}

    def restore(self, state: dict, allow_path_mismatch: bool = False) -> None:
        if not allow_path_mismatch and state.get("path") not in (None, self.path):
            raise ValueError(
                f"offset checkpoint is for {state['path']!r}, reader tails "
                f"{self.path!r}; a byte offset is meaningless across files "
                "(pass allow_path_mismatch=True to force)"
            )
        self.offset = int(state["offset"])

    # ------------------------------------------------------------- iterate

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        CHUNK = max(1 << 20, self.B * 512)
        chunk = CHUNK
        while True:
            size = os.path.getsize(self.path) if os.path.exists(self.path) else 0
            made_progress = False
            if size > self.offset:
                with open(self.path, "rb") as f:
                    f.seek(self.offset)
                    data = f.read(min(chunk, size - self.offset))
                last_nl = data.rfind(b"\n")
                if last_nl >= 0:
                    rows = data[: last_nl + 1].split(b"\n")[:-1]
                    at_end = self.offset + len(data) >= size
                    i = 0
                    while i < len(rows):
                        batch_rows = rows[i : i + self.B]
                        full = len(batch_rows) == self.B
                        final_flush = (
                            self.stop_at_eof and at_end and i + self.B >= len(rows)
                        )
                        if not full and not final_flush:
                            break  # wait for more data; offset stays put
                        nbytes = sum(len(r) + 1 for r in batch_rows)
                        # Advance BEFORE yielding (generator suspension would
                        # otherwise leave save() not covering a batch the
                        # consumer already holds): offsets mean "everything
                        # handed out so far", Kafka consumer semantics.
                        self.offset += nbytes
                        made_progress = True
                        i += len(batch_rows)
                        yield self.parser(
                            [r.decode(errors="replace") for r in batch_rows]
                        )
                if made_progress:
                    chunk = CHUNK
                elif self.offset + len(data) < size:
                    # Window exhausted without yielding a batch while more
                    # bytes already sit on disk — a record (or whole batch)
                    # longer than the window. Widen and retry instead of
                    # re-reading the same bytes forever.
                    chunk *= 2
                    continue
            if self.stop_at_eof and not made_progress:
                # nothing (more) consumable: either fully drained or only an
                # unterminated partial line remains — stop either way.
                return
            if not made_progress:
                time.sleep(self.poll_secs)  # no busy loop on partial lines

# --------------------------------------------------------------- TCP stream


class TCPStreamReader:
    """Consume a newline-framed record stream over TCP with offset resume.

    Protocol (see FileStreamServer): on connect the consumer sends one
    header line ``OFFSET <n>\\n``; the broker replies with the stream from
    byte offset n onward and keeps the connection open for appended
    records. Offsets advance only past YIELDED rows (the FileTailReader
    contract), so `save()`/`restore()` give exactly-once delivery across
    reconnects and process restarts — the consumer-group-offset semantics
    of the reference's KafkaDataset (kafka_dataset_op.cc), over a socket
    this environment can actually open.

    Broker outages are survived, not raised (unless `stop_at_eof`):
    reconnects use jittered exponential backoff from `reconnect_secs` up
    to `reconnect_max_secs`, and `connect_attempts` / `reconnects` /
    `consecutive_connect_failures` surface the churn to supervisors.

    Frame hygiene (the firewall's first line, docs/fault-tolerance.md
    "Semantic faults"): a frame larger than `max_record_bytes` with no
    newline is a wedged/garbage stream segment — it is SKIPPED up to the
    next newline (bounded resync, counted in `oversized_frames` +
    `record_errors`) instead of growing the buffer without bound or
    killing the reader thread; an undecodable record clamps field-wise
    inside the default parser (`criteo_line_parser(errors=...)`), also
    counted — one poisoned frame must cost one frame, never a reconnect
    cycle or the reader.
    """

    def __init__(
        self,
        host: str,
        port: int,
        batch_size: int = 2048,
        parser: Optional[Callable] = None,
        stop_at_eof: bool = False,
        reconnect_secs: float = 1.0,
        reconnect_max_secs: float = 30.0,
        num_dense: int = 13,
        num_cat: int = 26,
        max_record_bytes: int = 1 << 20,
    ):
        from deeprec_tpu.data.readers import RecordErrors

        self.host = host
        self.port = port
        self.B = batch_size
        self.record_errors = RecordErrors()
        self.max_record_bytes = int(max_record_bytes)
        self.oversized_frames = 0
        self._skipping = False  # inside an oversized frame, seeking \n
        self.parser = parser or criteo_line_parser(
            num_dense, num_cat, errors=self.record_errors)
        self.stop_at_eof = stop_at_eof
        # Reconnect policy: jittered exponential backoff from
        # `reconnect_secs` (the base, kept for back-compat) capped at
        # `reconnect_max_secs` — a dead broker costs O(cap) polling, a
        # flapping one isn't hammered by every consumer in lockstep.
        self.reconnect_secs = reconnect_secs
        self.reconnect_max_secs = reconnect_max_secs
        self.offset = 0
        # Attempt counters (surfaced by TrainLoop heartbeats and the
        # freshness bench): consecutive_connect_failures resets on a
        # successful connect; reconnects counts broker-initiated drops;
        # connect_attempts counts every dial.
        self.connect_attempts = 0
        self.reconnects = 0
        self.consecutive_connect_failures = 0
        self._rng = random.Random(
            (hash((host, port)) ^ os.getpid()) & 0xFFFFFFFF
        )

    def save(self) -> dict:
        return {"host": self.host, "port": self.port, "offset": self.offset}

    def restore(self, state: dict) -> None:
        self.offset = int(state["offset"])

    def backoff_delay(self, attempt: int) -> float:
        """Capped exponential reconnect delay BEFORE jitter: the k-th
        consecutive failure waits base * 2^(k-1), never above
        reconnect_max_secs. Pure — pinned by tests without sleeping
        (the shared `utils/backoff.py` policy)."""
        return backoff.backoff_delay(
            attempt, self.reconnect_secs, self.reconnect_max_secs)

    def _backoff_sleep(self) -> None:
        d = self.backoff_delay(self.consecutive_connect_failures)
        time.sleep(backoff.jittered(d, self._rng))

    def _connect(self) -> socket.socket:
        self.connect_attempts += 1
        s = socket.create_connection((self.host, self.port), timeout=30)
        s.settimeout(None)  # the 30s budget is for CONNECT only: a quiet
        s.sendall(f"OFFSET {self.offset}\n".encode())  # follow-mode broker
        self.consecutive_connect_failures = 0
        return s  # must not look like an EOF after a lull

    def _pop_batch(self, entries, count: int):
        """Pop `count` real rows off the entry queue, folding EVERY
        popped entry's bytes (skip markers included) into the offset —
        skipped frames are consumed stream positions, or a reconnect
        would replay them forever."""
        batch_rows = []
        nbytes = 0
        while len(batch_rows) < count and entries:
            payload, nb = entries.pop(0)
            nbytes += nb
            if payload is not None:
                batch_rows.append(payload)
        self.offset += nbytes
        return batch_rows

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        buf = b""
        # [(payload | None, nbytes)] — None marks a skipped (oversized)
        # frame whose bytes still advance the offset in stream order.
        entries: list = []
        nreal = 0
        sock = None
        try:
            while True:
                if sock is None:
                    try:
                        sock = self._connect()
                    except OSError:
                        if self.stop_at_eof:
                            # a bounded consume expects the broker to be
                            # there: an empty iterator would masquerade as
                            # an empty stream
                            raise
                        self.consecutive_connect_failures += 1
                        self._backoff_sleep()
                        continue
                try:
                    data = sock.recv(1 << 20)
                except OSError:
                    data = b""
                if not data:  # broker closed: flush or reconnect
                    sock.close()
                    sock = None
                    if self.stop_at_eof:
                        break  # keep entries: the final drain yields them
                    # Drop un-yielded partials: the reconnect replays from
                    # self.offset, which covers exactly the yielded rows —
                    # keeping buf/entries would deliver them twice and
                    # splice a corrupt record out of the old partial line.
                    buf = b""
                    entries = []
                    nreal = 0
                    self._skipping = False
                    self.reconnects += 1
                    self.consecutive_connect_failures += 1
                    self._backoff_sleep()
                    continue
                if self._skipping:
                    # bounded resync: discard until the oversized frame's
                    # terminating newline, counting the bytes (the frame
                    # itself was counted when the skip began — it may
                    # never see its newline before EOF)
                    nl = data.find(b"\n")
                    if nl < 0:
                        entries.append((None, len(data)))
                        continue
                    entries.append((None, nl + 1))
                    self._skipping = False
                    data = data[nl + 1:]
                buf += data
                nl = buf.rfind(b"\n")
                if nl >= 0:
                    for r in buf[: nl + 1].split(b"\n")[:-1]:
                        if len(r) > self.max_record_bytes:
                            # a complete-but-absurd frame: skip it whole
                            entries.append((None, len(r) + 1))
                            self.oversized_frames += 1
                            self.record_errors.count("oversized_frame")
                            continue
                        entries.append((r, len(r) + 1))
                        nreal += 1
                    buf = buf[nl + 1:]
                if len(buf) > self.max_record_bytes:
                    # an unterminated frame larger than any legal record:
                    # consume what's buffered and skip to the next newline
                    # (counted NOW — at EOF it may never get one)
                    entries.append((None, len(buf)))
                    buf = b""
                    self._skipping = True
                    self.oversized_frames += 1
                    self.record_errors.count("oversized_frame")
                while nreal >= self.B:
                    batch_rows = self._pop_batch(entries, self.B)
                    nreal -= len(batch_rows)
                    yield self.parser(
                        [r.decode(errors="replace") for r in batch_rows]
                    )
            # drain the final partial batch at EOF
            if nreal:
                batch_rows = self._pop_batch(entries, nreal)
                yield self.parser(
                    [r.decode(errors="replace") for r in batch_rows]
                )
            # trailing skip markers are consumed stream positions even at
            # EOF: fold them into the offset so a checkpointed position
            # never points back into skipped garbage
            for _, nb in entries:
                self.offset += nb
            entries = []
        finally:
            if sock is not None:
                sock.close()


class FileStreamServer:
    """Minimal broker: serve a file's records over TCP from any offset.

    Speaks the TCPStreamReader protocol. `follow=True` keeps connections
    open and streams appended bytes (the log-broker behavior);
    `follow=False` closes after the current contents (bounded replay).
    Test/demo-grade by design — production pods read through a real broker
    or the shared-FS FileTailReader.
    """

    def __init__(self, path: str, host: str = "127.0.0.1", port: int = 0,
                 follow: bool = False, poll_secs: float = 0.05):
        outer = self

        class Handler(socketserver.StreamRequestHandler):
            def handle(self):
                header = self.rfile.readline().decode().split()
                offset = int(header[1]) if header[:1] == ["OFFSET"] else 0
                try:
                    with open(outer.path, "rb") as f:
                        f.seek(offset)
                        while not outer._stop.is_set():
                            chunk = f.read(1 << 20)
                            if chunk:
                                self.wfile.write(chunk)
                                self.wfile.flush()
                            elif outer.follow:
                                time.sleep(outer.poll_secs)
                            else:
                                return
                except (BrokenPipeError, ConnectionResetError):
                    return  # consumer went away; it will resume by offset

        class Server(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self.path = path
        self.follow = follow
        self.poll_secs = poll_secs
        self._stop = threading.Event()
        self._srv = Server((host, port), Handler)
        self.port = self._srv.server_address[1]
        self._thread: Optional[threading.Thread] = None

    def start(self) -> "FileStreamServer":
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        self._srv.shutdown()
        self._srv.server_close()
        if self._thread:
            self._thread.join(timeout=2)
