"""Kafka wire-protocol consumer (no librdkafka, no external deps).

The reference trains from Kafka through librdkafka
(core/kernels/data/kafka_dataset_op.cc — KafkaDataset with
"topic:partition:offset[:limit]" strings, consumer-group offsets, eof /
timeout semantics; contrib/kafka wraps the same). This module speaks the
actual Kafka protocol over a plain socket so the framework can consume
from a real broker: big-endian framed requests, ApiVersions(18) /
Metadata(3) / ListOffsets(2) / Fetch(1) / OffsetCommit(8) /
OffsetFetch(9), with both on-wire record encodings parsed — the legacy
MessageSet (message format v0/v1, what brokers down-convert to for old
fetch versions) and the v2 RecordBatch (varint records). Compression is
not supported (attributes must be 0) — DeepRec's training pipelines run
uncompressed topics; a compressed batch raises rather than corrupting.

Offset semantics match the rest of data/stream.py: `save()` returns the
offset of the next UN-yielded record, so checkpoint/crash/restore is
exactly-once with respect to delivered batches. `commit()` additionally
stores the position broker-side under a consumer group (OffsetCommit),
and a reader constructed with offset -1 resumes from the group's stored
offset (OffsetFetch), mirroring the reference's group semantics.

Protocol versions are pinned low on purpose: v0/v1 requests have stable,
simple encodings, every broker since 0.10 answers them, and ApiVersions
is consulted only to fail loudly when a future broker drops one.
"""
from __future__ import annotations

import logging
import socket
import struct
import time
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import numpy as np

# api keys
API_PRODUCE = 0
API_FETCH = 1
API_LIST_OFFSETS = 2
API_METADATA = 3
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_VERSIONS = 18

# error codes we special-case
ERR_NONE = 0
ERR_OFFSET_OUT_OF_RANGE = 1
ERR_UNKNOWN_TOPIC_OR_PARTITION = 3
ERR_NOT_LEADER = 6

_ERR_NAMES = {
    1: "OFFSET_OUT_OF_RANGE",
    3: "UNKNOWN_TOPIC_OR_PARTITION",
    6: "NOT_LEADER_FOR_PARTITION",
    7: "REQUEST_TIMED_OUT",
    15: "COORDINATOR_NOT_AVAILABLE",
    16: "NOT_COORDINATOR",
}


class KafkaError(RuntimeError):
    def __init__(self, code: int, where: str):
        self.code = code
        super().__init__(
            f"{where}: kafka error {code} ({_ERR_NAMES.get(code, 'unknown')})"
        )


class KafkaOffsetGapError(RuntimeError):
    """A restored/requested offset no longer exists on the broker — the
    topic's retention (or compaction) outran the checkpoint. Restart with
    offset_reset="earliest" to accept the data loss and resume from the
    oldest retained record, or re-point the reader at a fresh offset."""


# ------------------------------------------------------------ primitives


class _Writer:
    __slots__ = ("buf",)

    def __init__(self):
        self.buf = bytearray()

    def i8(self, v):
        self.buf += struct.pack(">b", v)
        return self

    def i16(self, v):
        self.buf += struct.pack(">h", v)
        return self

    def i32(self, v):
        self.buf += struct.pack(">i", v)
        return self

    def i64(self, v):
        self.buf += struct.pack(">q", v)
        return self

    def string(self, s: Optional[str]):
        if s is None:
            return self.i16(-1)
        b = s.encode()
        self.i16(len(b))
        self.buf += b
        return self

    def bytes_(self, b: Optional[bytes]):
        if b is None:
            return self.i32(-1)
        self.i32(len(b))
        self.buf += b
        return self

    def array(self, items, fn):
        self.i32(len(items))
        for it in items:
            fn(self, it)
        return self


class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def _take(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise ValueError("truncated kafka frame")
        b = self.buf[self.pos:self.pos + n]
        self.pos += n
        return b

    def i8(self):
        return struct.unpack(">b", self._take(1))[0]

    def i16(self):
        return struct.unpack(">h", self._take(2))[0]

    def i32(self):
        return struct.unpack(">i", self._take(4))[0]

    def i64(self):
        return struct.unpack(">q", self._take(8))[0]

    def u32(self):
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.i16()
        return None if n < 0 else self._take(n).decode("utf-8", "replace")

    def bytes_(self) -> Optional[bytes]:
        n = self.i32()
        return None if n < 0 else bytes(self._take(n))

    def varint(self) -> int:
        """Zigzag varint (record batch v2 encoding)."""
        result = 0
        shift = 0
        while True:
            b = self._take(1)[0]
            result |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift >= 70:
                raise ValueError("varint too long")
        return (result >> 1) ^ -(result & 1)

    def varbytes(self) -> Optional[bytes]:
        n = self.varint()
        return None if n < 0 else bytes(self._take(n))


# --------------------------------------------------------- record parsing


def _parse_message_set(r: _Reader, end: int) -> List[Tuple[int, bytes, bytes]]:
    """Legacy MessageSet (magic 0/1): [(offset, key, value)].

    A fetch response may end with a partial message (the broker truncates
    at max_bytes) — stop cleanly there.
    """
    out = []
    while r.pos + 12 <= end:
        offset = r.i64()
        size = r.i32()
        if r.pos + size > end:
            break  # trailing partial message
        body = _Reader(r.buf, r.pos)
        r.pos += size
        body.u32()  # crc (not verified; TCP already checksums)
        magic = body.i8()
        attrs = body.i8()
        if attrs & 0x07:
            raise ValueError(
                "compressed kafka message (attrs=%d): compression is not "
                "supported, produce uncompressed" % attrs
            )
        if magic >= 1:
            body.i64()  # timestamp
        key = body.bytes_()
        value = body.bytes_()
        out.append((offset, key or b"", value or b""))
    return out


def _parse_record_batch(r: _Reader, end: int) -> List[Tuple[int, bytes, bytes]]:
    """Record batch v2: [(offset, key, value)]."""
    out = []
    while r.pos + 61 <= end:  # batch header is 61 bytes
        base_offset = r.i64()
        batch_len = r.i32()
        batch_end = r.pos + batch_len
        if batch_end > end:
            break  # partial trailing batch
        r.i32()  # partition leader epoch
        magic = r.i8()
        if magic != 2:
            raise ValueError(f"unexpected magic {magic} in record batch")
        r.u32()  # crc32c (not verified)
        attrs = r.i16()
        if attrs & 0x07:
            raise ValueError(
                "compressed kafka record batch (attrs=%d): compression is "
                "not supported, produce uncompressed" % attrs
            )
        if attrs & 0x20:  # control batch (transaction markers): no data
            r.pos = batch_end
            continue
        r.i32()  # last offset delta
        r.i64()  # first timestamp
        r.i64()  # max timestamp
        r.i64()  # producer id
        r.i16()  # producer epoch
        r.i32()  # base sequence
        n_records = r.i32()
        for _ in range(n_records):
            rec_len = r.varint()
            rec_end = r.pos + rec_len
            r.i8()  # record attributes
            r.varint()  # timestamp delta
            off_delta = r.varint()
            key = r.varbytes()
            value = r.varbytes()
            n_headers = r.varint()
            for _ in range(n_headers):
                r.varbytes()  # header key
                r.varbytes()  # header value
            r.pos = rec_end  # defensive: trust the record length
            out.append((base_offset + off_delta, key or b"", value or b""))
        r.pos = batch_end
    return out


def parse_records(buf: bytes) -> List[Tuple[int, bytes, bytes]]:
    """Parse a fetch-response record blob in either on-wire encoding."""
    if not buf:
        return []
    # magic byte sits at offset 16 in both encodings
    if len(buf) > 16 and buf[16] >= 2:
        return _parse_record_batch(_Reader(buf), len(buf))
    return _parse_message_set(_Reader(buf), len(buf))


# --------------------------------------------------------------- client


class KafkaClient:
    """One broker connection, correlation-id matched request/response."""

    def __init__(self, host: str, port: int, client_id: str = "deeprec-tpu",
                 timeout: float = 30.0):
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout = timeout
        self._corr = 0
        self._sock: Optional[socket.socket] = None

    # -- framing

    def _ensure(self):
        if self._sock is None:
            self._sock = socket.create_connection(
                (self.host, self.port), timeout=self.timeout
            )

    def close(self):
        if self._sock is not None:
            try:
                self._sock.close()
            finally:
                self._sock = None

    def _roundtrip(self, api_key: int, api_version: int,
                   payload: bytes) -> _Reader:
        self._ensure()
        self._corr += 1
        hdr = _Writer()
        hdr.i16(api_key).i16(api_version).i32(self._corr).string(self.client_id)
        frame = bytes(hdr.buf) + payload
        msg = struct.pack(">i", len(frame)) + frame
        try:
            self._sock.sendall(msg)
            raw = self._recv_frame()
        except OSError:
            self.close()
            raise
        r = _Reader(raw)
        corr = r.i32()
        if corr != self._corr:
            self.close()
            raise ValueError(
                f"correlation id mismatch: sent {self._corr}, got {corr}"
            )
        return r

    def _recv_frame(self) -> bytes:
        size_b = self._recv_exact(4)
        (size,) = struct.unpack(">i", size_b)
        if size < 0 or size > 1 << 30:
            raise ValueError(f"bad kafka frame size {size}")
        return self._recv_exact(size)

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        got = 0
        while got < n:
            c = self._sock.recv(n - got)
            if not c:
                raise OSError("broker closed connection")
            chunks.append(c)
            got += len(c)
        return b"".join(chunks)

    # -- apis (versions pinned; see module docstring)

    def api_versions(self) -> Dict[int, Tuple[int, int]]:
        r = self._roundtrip(API_VERSIONS, 0, b"")
        err = r.i16()
        if err:
            raise KafkaError(err, "ApiVersions")
        out = {}
        for _ in range(r.i32()):
            k, lo, hi = r.i16(), r.i16(), r.i16()
            out[k] = (lo, hi)
        return out

    def metadata(self, topics: List[str]):
        w = _Writer()
        w.array(topics, lambda w, t: w.string(t))
        r = self._roundtrip(API_METADATA, 0, bytes(w.buf))
        brokers = {}
        for _ in range(r.i32()):
            node, host, port = r.i32(), r.string(), r.i32()
            brokers[node] = (host, port)
        topics_out = {}
        for _ in range(r.i32()):
            terr = r.i16()
            tname = r.string()
            parts = {}
            for _ in range(r.i32()):
                perr = r.i16()
                pid = r.i32()
                leader = r.i32()
                for _ in range(r.i32()):
                    r.i32()  # replicas
                for _ in range(r.i32()):
                    r.i32()  # isr
                parts[pid] = {"error": perr, "leader": leader}
            topics_out[tname] = {"error": terr, "partitions": parts}
        return brokers, topics_out

    def list_offsets(self, topic: str, partition: int, when: int) -> int:
        """when: -1 latest, -2 earliest (ListOffsets v0 semantics)."""
        w = _Writer()
        w.i32(-1)  # replica_id
        w.array([None], lambda w, _: (
            w.string(topic),
            w.array([None], lambda w2, _2: (
                w2.i32(partition), w2.i64(when), w2.i32(1)))))
        r = self._roundtrip(API_LIST_OFFSETS, 0, bytes(w.buf))
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition id
                err = r.i16()
                n = r.i32()
                offs = [r.i64() for _ in range(n)]
                if err:
                    raise KafkaError(err, "ListOffsets")
                return offs[0] if offs else 0
        raise ValueError("empty ListOffsets response")

    def fetch(self, topic: str, partition: int, offset: int,
              max_wait_ms: int = 500, min_bytes: int = 1,
              max_bytes: int = 1 << 22) -> Tuple[int, List[Tuple[int, bytes, bytes]]]:
        """Returns (high_watermark, [(offset, key, value), ...])."""
        w = _Writer()
        w.i32(-1)  # replica_id
        w.i32(max_wait_ms)
        w.i32(min_bytes)
        w.array([None], lambda w, _: (
            w.string(topic),
            w.array([None], lambda w2, _2: (
                w2.i32(partition), w2.i64(offset), w2.i32(max_bytes)))))
        r = self._roundtrip(API_FETCH, 0, bytes(w.buf))
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()  # partition id
                err = r.i16()
                hw = r.i64()
                blob = r.bytes_() or b""
                if err:
                    raise KafkaError(err, "Fetch")
                return hw, parse_records(blob)
        raise ValueError("empty Fetch response")

    def offset_commit(self, group: str, topic: str, partition: int,
                      offset: int, metadata: str = "") -> None:
        """OffsetCommit v2 — the Kafka-side (__consumer_offsets) store,
        the SAME store OffsetFetch v1+ reads (v0 would write the
        ZooKeeper-era store and a later offset_fetch would miss it).
        Simple-consumer path: generation -1, empty member, no retention."""
        w = _Writer()
        w.string(group)
        w.i32(-1)       # generation id (simple consumer)
        w.string("")    # member id
        w.i64(-1)       # retention time (broker default)
        w.array([None], lambda w, _: (
            w.string(topic),
            w.array([None], lambda w2, _2: (
                w2.i32(partition), w2.i64(offset), w2.string(metadata)))))
        r = self._roundtrip(API_OFFSET_COMMIT, 2, bytes(w.buf))
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                err = r.i16()
                if err:
                    raise KafkaError(err, "OffsetCommit")

    def offset_fetch(self, group: str, topic: str, partition: int) -> int:
        """OffsetFetch v1 (broker-stored group offset; -1 = none)."""
        w = _Writer()
        w.string(group)
        w.array([None], lambda w, _: (
            w.string(topic),
            w.array([None], lambda w2, _2: w2.i32(partition))))
        r = self._roundtrip(API_OFFSET_FETCH, 1, bytes(w.buf))
        for _ in range(r.i32()):
            r.string()
            for _ in range(r.i32()):
                r.i32()
                off = r.i64()
                r.string()  # metadata
                err = r.i16()
                if err:
                    raise KafkaError(err, "OffsetFetch")
                return off
        raise ValueError("empty OffsetFetch response")


# ---------------------------------------------------------------- reader


class KafkaStreamReader:
    """Batch reader over one topic:partition via the real Kafka protocol.

    The KafkaDataset analog (kafka_dataset_op.cc): construct from a
    reference-style ``"topic:partition:offset[:limit]"`` string or
    explicit args. Offsets are Kafka record offsets; `save()`/`restore()`
    carry the next UN-yielded offset (exactly-once across restarts), and
    `commit()` stores it broker-side under `group` like the reference's
    consumer group. offset -1 means resume from the group's stored
    offset, falling back to earliest.

    `stop_at_eof=True` mirrors the reference's eof attr: drain up to the
    high watermark (or `limit`) and stop; otherwise follow forever.
    """

    def __init__(
        self,
        servers: str,
        topic_spec: str = None,
        *,
        topic: str = None,
        partition: int = 0,
        offset: int = -2,
        limit: int = -1,
        group: str = "deeprec",
        batch_size: int = 2048,
        parser: Optional[Callable] = None,
        stop_at_eof: bool = False,
        max_wait_ms: int = 500,
        reconnect_secs: float = 1.0,
        num_dense: int = 13,
        num_cat: int = 26,
        offset_reset: str = "error",
    ):
        if topic_spec is not None:
            parts = topic_spec.split(":")
            topic = parts[0]
            if len(parts) > 1 and parts[1]:
                partition = int(parts[1])
            if len(parts) > 2 and parts[2]:
                offset = int(parts[2])
            if len(parts) > 3 and parts[3]:
                limit = int(parts[3])
        if topic is None:
            raise ValueError("topic required (topic_spec or topic=)")
        if offset_reset not in ("error", "earliest"):
            raise ValueError(
                f"offset_reset must be 'error' or 'earliest', got "
                f"{offset_reset!r}"
            )
        self.servers = [s.strip() for s in servers.split(",") if s.strip()]
        if not self.servers:
            raise ValueError("at least one bootstrap server required")
        self.client: Optional[KafkaClient] = None  # leader, connected lazily
        self.offset_reset = offset_reset
        self.topic = topic
        self.partition = partition
        self.group = group
        self.B = batch_size
        self.limit = limit
        self.stop_at_eof = stop_at_eof
        self.max_wait_ms = max_wait_ms
        self.reconnect_secs = reconnect_secs
        from deeprec_tpu.data.stream import criteo_line_parser

        self.parser = parser or criteo_line_parser(num_dense, num_cat)
        self._start = offset
        self.offset: Optional[int] = None  # resolved lazily

    # -- broker connection (leader-aware)

    def _ensure_client(self) -> KafkaClient:
        if self.client is None:
            self.client = self._connect_leader()
        return self.client

    def _connect_leader(self) -> KafkaClient:
        """Locate the partition leader via Metadata — what the reference
        gets for free from librdkafka (kafka_dataset_op.cc's consumer
        follows leader redirects). Falls back to the bootstrap connection
        itself when metadata is unhelpful (single-broker/dev setups)."""
        last: Optional[Exception] = None
        for srv in self.servers:
            host, _, port = srv.partition(":")
            cand = KafkaClient(host, int(port or 9092))
            try:
                brokers, topics = cand.metadata([self.topic])
            except (OSError, ValueError, KafkaError) as e:
                cand.close()
                last = e
                continue
            info = (
                topics.get(self.topic, {})
                .get("partitions", {})
                .get(self.partition)
            )
            if info and not info.get("error") and info.get("leader") in brokers:
                lh, lp = brokers[info["leader"]]
                if (lh, int(lp)) != (cand.host, cand.port):
                    cand.close()
                    return KafkaClient(lh, int(lp))
            return cand
        assert last is not None
        raise last

    # -- offsets

    def _resolve_start(self) -> int:
        if self._start >= 0:
            return self._start
        client = self._ensure_client()
        if self._start == -1:  # group offset, else earliest
            stored = client.offset_fetch(
                self.group, self.topic, self.partition
            )
            if stored >= 0:
                return stored
            return client.list_offsets(self.topic, self.partition, -2)
        return client.list_offsets(self.topic, self.partition, -2)

    def save(self) -> dict:
        return {
            "topic": self.topic,
            "partition": self.partition,
            "offset": self.offset if self.offset is not None else self._start,
        }

    def restore(self, state: dict) -> None:
        if state.get("topic") not in (None, self.topic) or int(
            state.get("partition", self.partition)
        ) != self.partition:
            raise ValueError(
                f"offset checkpoint is for "
                f"{state.get('topic')}:{state.get('partition')}, reader "
                f"consumes {self.topic}:{self.partition}"
            )
        self._start = int(state["offset"])
        self.offset = None

    def commit(self) -> None:
        """Store the next-unyielded offset broker-side (consumer group)."""
        off = self.offset if self.offset is not None else self._start
        if off >= 0:
            self._ensure_client().offset_commit(
                self.group, self.topic, self.partition, off
            )

    def close(self) -> None:
        if self.client is not None:
            self.client.close()
            self.client = None

    # -- iterate

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        if self.offset is None:
            self.offset = self._resolve_start()
        rows: List[Tuple[int, bytes]] = []  # (offset, value) not yet yielded
        # Two positions: `fetch_pos` walks ahead as records are buffered;
        # `self.offset` (the save()/commit() contract) advances only when
        # a batch is HANDED OUT, so a crash re-fetches buffered rows
        # instead of dropping them.
        fetch_pos = self.offset
        leader_retries = 0
        while True:
            try:
                hw, records = self._ensure_client().fetch(
                    self.topic, self.partition, fetch_pos,
                    max_wait_ms=self.max_wait_ms,
                )
                leader_retries = 0
            except ValueError:
                # Permanent (unparseable/compressed data): retrying the
                # same offset would stall training silently. Always raise.
                self.close()
                raise
            except KafkaError as e:
                self.close()
                if e.code == ERR_NOT_LEADER and leader_retries < 8:
                    # Leadership moved (rebalance/broker restart): re-resolve
                    # via Metadata and retry the same position — librdkafka's
                    # automatic leader redirect, bounded so a sick cluster
                    # surfaces instead of spinning forever.
                    leader_retries += 1
                    time.sleep(self.reconnect_secs)
                    continue
                if e.code == ERR_OFFSET_OUT_OF_RANGE:
                    if self.offset_reset == "earliest":
                        earliest = self._ensure_client().list_offsets(
                            self.topic, self.partition, -2
                        )
                        logging.getLogger(__name__).warning(
                            "kafka %s:%d: offset %d is outside the broker's "
                            "retained range; resetting to earliest=%d "
                            "(offset_reset='earliest') — records in between "
                            "are lost",
                            self.topic, self.partition, fetch_pos, earliest,
                        )
                        fetch_pos = earliest
                        self.offset = max(self.offset, earliest)
                        continue
                    raise KafkaOffsetGapError(
                        f"kafka {self.topic}:{self.partition}: offset "
                        f"{fetch_pos} no longer exists on the broker (topic "
                        "retention or compaction outran this checkpoint). "
                        "Pass offset_reset='earliest' to resume from the "
                        "oldest retained record, accepting the gap."
                    ) from e
                raise
            except OSError:
                self.close()
                if self.stop_at_eof:
                    raise
                time.sleep(self.reconnect_secs)
                continue
            for off, _key, value in records:
                if off < fetch_pos:
                    continue  # broker resent below our position
                if self.limit >= 0 and off >= self.limit:
                    fetch_pos = self.limit  # done even on a sparse topic
                    break
                rows.append((off, value))
                fetch_pos = off + 1
            # Checkpoint offsets come from the RECORDS (last yielded + 1),
            # not a dense counter — compacted topics and transaction
            # markers leave holes a counter would re-deliver through.
            while len(rows) >= self.B:
                batch, rows = rows[: self.B], rows[self.B:]
                self.offset = batch[-1][0] + 1
                yield self.parser(
                    [v.decode(errors="replace") for _, v in batch]
                )
            done = (self.limit >= 0 and fetch_pos >= self.limit) or (
                self.stop_at_eof and not records and fetch_pos >= hw
            )
            if done:
                if rows:  # final partial batch (bounded-dataset flush)
                    self.offset = rows[-1][0] + 1
                    yield self.parser(
                        [v.decode(errors="replace") for _, v in rows]
                    )
                return
