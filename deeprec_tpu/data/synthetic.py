"""Synthetic click-log generators with learnable structure.

Used by tests and the benchmark harness when no real dataset is mounted: ids
are zipf-distributed (recommendation workloads are heavy-tailed — this is
what exercises admission filters, caches and all2all skew), and the label is
a noisy logistic function of hidden per-id weights, so a correct trainer
demonstrably lifts AUC above 0.5.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


def zipf_ids(rng: np.random.Generator, vocab: int, a: float, shape):
    """Bounded zipf(a) via inverse-CDF over a fixed vocab: a=1 is the
    log-uniform limit; larger a concentrates mass on head ids."""
    u = rng.random(shape)
    if abs(a - 1.0) < 1e-6:
        ranks = np.floor(np.exp(u * np.log(vocab))).astype(np.int64)
    else:
        v = vocab ** (1.0 - a)
        ranks = np.floor((u * (v - 1.0) + 1.0) ** (1.0 / (1.0 - a))).astype(
            np.int64
        )
    return np.clip(ranks, 1, vocab) - 1


class SyntheticCriteo:
    """Batches shaped like Criteo: I1-I13 floats [B,1], C1-C26 int ids [B],
    label [B].

    `zipf_a` is either ONE exponent covering every categorical column
    (legacy, bit-identical draw stream) or a per-table sequence of
    `num_cat` exponents — real workloads have wide variance in per-table
    skew/unique fractions (ROADMAP), and the placement bench needs tables
    whose heads differ to show hot-key balancing.

    `zipf_rotate_every=N` is the DRIFTING-skew mode (flash sales,
    diurnal cycles — the workload Placement v2's replanner exists for):
    after every N batches the hot-key set rotates to a different region
    of the id space (rank r maps to id (r + k·stride) % vocab for
    rotation k = batches_drawn // N), so a placement plan tuned on one
    window becomes stale mid-stream. Deterministic — the rotation is a
    pure function of the batch index, the RNG draw stream is untouched —
    and the labels follow the rotated ids (a newly-hot id brings its own
    hidden weight, like a new product going viral). Off (None, the
    default) the generator is stream-identical to before the knob
    existed."""

    def __init__(
        self,
        batch_size: int = 2048,
        num_cat: int = 26,
        num_dense: int = 13,
        vocab: int = 100_000,
        zipf_a=1.2,
        seed: int = 0,
        dtype=np.int32,
        offset_ids: bool = True,
        zipf_rotate_every: Optional[int] = None,
        zipf_rotate_stride: Optional[int] = None,
    ):
        self.B = batch_size
        self.num_cat = num_cat
        self.num_dense = num_dense
        self.vocab = vocab
        self.zipf_a = zipf_a
        if np.ndim(zipf_a) != 0:
            if len(zipf_a) != num_cat:
                raise ValueError(
                    f"per-table zipf_a needs {num_cat} exponents, "
                    f"got {len(zipf_a)}"
                )
            self._zipf_per_table = np.asarray(zipf_a, np.float64)
        else:
            self._zipf_per_table = None
        # offset_ids=False keeps every column in ONE raw id space (hashed
        # shared-vocab features): each table's zipf head is the SAME raw
        # ids, so under uniform hash_shard every table hammers the same
        # owner shards — the correlated-head case the placement plan's
        # owner-offset rotation exists for.
        self.offset_ids = offset_ids
        if zipf_rotate_every is not None and zipf_rotate_every <= 0:
            raise ValueError(
                f"zipf_rotate_every must be positive, got {zipf_rotate_every}"
            )
        self.zipf_rotate_every = zipf_rotate_every
        # Default stride lands each rotation's head deep inside the
        # previous tail (≈ a third of the vocab, offset so consecutive
        # rotations never re-overlap a small head region); any stride
        # coprime-ish with vocab works, it only has to MOVE the head.
        self.zipf_rotate_stride = (
            zipf_rotate_stride
            if zipf_rotate_stride is not None
            else vocab // 3 + 1
        )
        self._batches_drawn = 0
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        # hidden ground-truth weights giving the label structure
        wrng = np.random.default_rng(12345)
        self.id_weight = wrng.normal(0, 1.0, size=(num_cat, vocab)).astype(np.float32)
        self.dense_weight = wrng.normal(0, 0.5, size=(num_dense,)).astype(np.float32)

    def _zipf_ids(self, shape):
        return zipf_ids(self.rng, self.vocab, self.zipf_a, shape)

    def _cat_ids(self) -> np.ndarray:
        """[num_cat, B] categorical draw: one shared-exponent call on the
        legacy scalar path (stream-identical to before per-table knobs
        existed), else one bounded-zipf draw per column at its own a."""
        if self._zipf_per_table is None:
            return self._zipf_ids((self.num_cat, self.B))
        return np.stack([
            zipf_ids(self.rng, self.vocab, float(a), (self.B,))
            for a in self._zipf_per_table
        ])

    def rotation_at(self, batch_index: int) -> int:
        """Hot-set rotation index in force for batch `batch_index` (0
        when rotation is off) — pure, so tests and the bench can locate
        the drift boundary without consuming the stream."""
        if not self.zipf_rotate_every:
            return 0
        return batch_index // self.zipf_rotate_every

    def batch(self) -> Dict[str, np.ndarray]:
        cats = self._cat_ids()
        if self.zipf_rotate_every:
            # Drifting skew: shift the rank->id mapping so the zipf head
            # occupies a different id region each rotation. Applied
            # BEFORE the label logit, so the task rotates with the ids.
            k = self.rotation_at(self._batches_drawn)
            if k:
                cats = (cats + k * self.zipf_rotate_stride) % self.vocab
        self._batches_drawn += 1
        dense = self.rng.lognormal(0.0, 1.0, size=(self.B, self.num_dense)).astype(
            np.float32
        )
        logit = np.zeros((self.B,), np.float32)
        for c in range(self.num_cat):
            logit += self.id_weight[c, cats[c]] * 0.3
        logit += np.log1p(dense) @ self.dense_weight * 0.3
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(self.B) < prob).astype(np.float32)
        out: Dict[str, np.ndarray] = {"label": label}
        for i in range(self.num_dense):
            out[f"I{i+1}"] = dense[:, i : i + 1]
        for c in range(self.num_cat):
            # offset ids per-feature so tables see disjoint key spaces
            # (offset_ids=False: shared raw space, correlated zipf heads)
            off = c * self.vocab if self.offset_ids else 0
            out[f"C{c+1}"] = (cats[c] + off).astype(self.dtype)
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()


class SyntheticMultiTask(SyntheticCriteo):
    """Adds correlated ctr/cvr/ctcvr labels for the multi-task models
    (ESMM/MMoE/PLE/DBMTL/SimpleMultiTask). cvr is only observable given a
    click — the entire-space structure ESMM exploits."""

    def batch(self) -> Dict[str, np.ndarray]:
        out = super().batch()
        click = out.pop("label")
        # conversion correlates with the same hidden structure, rarer
        conv_noise = self.rng.random(self.B)
        conv_given_click = (conv_noise < 0.3).astype(np.float32)
        out["label_ctr"] = click
        out["label_cvr"] = click * conv_given_click
        out["label_ctcvr"] = click * conv_given_click
        return out


# ---------------------------------------------------------------------------
# Criteo-statistics-matched deterministic generator
#
# Public summary statistics of the Kaggle Criteo display-advertising dataset
# (the dataset behind the reference's modelzoo AUC tables,
# /root/reference/modelzoo/wide_and_deep/README.md:195-215): per-column
# categorical cardinalities (as published with the DLRM reference
# implementation's preprocessing), overall CTR ~= 0.2562, and approximate
# per-column missing-value rates for the 13 integer features. The generator
# matches these MARGINALS; the label function is a synthetic logistic model
# whose Bayes-optimal AUC is computable (`bayes_auc`), so trained-AUC results
# can be reported as "x of the achievable ceiling" with explicit provenance
# instead of dressing synthetic numbers up as real-Criteo parity.

CRITEO_KAGGLE_CARDINALITIES = (
    1460, 583, 10131227, 2202608, 305, 24, 12517, 633, 3, 93145, 5683,
    8351593, 3194, 27, 14992, 5461306, 10, 5652, 2173, 4, 7046547, 18,
    15, 286181, 105, 142572,
)
CRITEO_KAGGLE_CTR = 0.2562
# Fraction of empty values per integer column I1-I13 (approximate public
# summary; empties are imputed to 0, the common Criteo convention).
CRITEO_DENSE_MISSING = (
    0.45, 0.00, 0.21, 0.21, 0.03, 0.22, 0.04, 0.00, 0.04,
    0.45, 0.04, 0.77, 0.21,
)

_U64 = np.uint64
_MASK = _U64(0xFFFFFFFFFFFFFFFF)


def _mix64(x: np.ndarray) -> np.ndarray:
    """splitmix64 finalizer — a vectorized stateless uint64 mixer."""
    with np.errstate(over="ignore"):
        x = (x + _U64(0x9E3779B97F4A7C15)) & _MASK
        x = ((x ^ (x >> _U64(30))) * _U64(0xBF58476D1CE4E5B9)) & _MASK
        x = ((x ^ (x >> _U64(27))) * _U64(0x94D049BB133111EB)) & _MASK
        return x ^ (x >> _U64(31))


def _hash_normal(key: np.ndarray, salt: int) -> np.ndarray:
    """Deterministic standard normal per uint64 key (Box-Muller on two
    hash-derived uniforms). O(1) memory — the weight 'tables' for 33M
    Criteo-scale ids are never materialized."""
    key = key.astype(_U64)
    h1 = _mix64(key ^ _U64(salt * 2 + 1))
    h2 = _mix64(key ^ _U64(salt * 2 + 2))
    u1 = (h1 >> _U64(11)).astype(np.float64) * (2.0 ** -53) + 1e-300
    u2 = (h2 >> _U64(11)).astype(np.float64) * (2.0 ** -53)
    return (np.sqrt(-2.0 * np.log(u1)) * np.cos(2.0 * np.pi * u2)).astype(
        np.float32
    )


class CriteoStats:
    """Deterministic Criteo-marginal-matched click-log stream.

    * **Cardinalities**: column c draws ids from the published Kaggle
      cardinality (capped by `cardinality_cap` for bounded-table runs —
      the hashed-vocab convention every Criteo trainer applies anyway).
    * **Frequency spectra**: per-column bounded zipf; exponents spread
      deterministically over [1.05, 1.30] (real columns vary in skew).
    * **CTR**: intercept calibrated at init so mean(label) matches 0.2562.
    * **Determinism**: `batch_at(i)` is a pure function of
      (seed, split, i) — any worker can generate any batch, streams
      restart exactly, and train/"eval" splits are disjoint by salt.
    * **Ceiling**: labels are Bernoulli(sigmoid(hidden logit)); the hidden
      per-id weights come from a stateless hash, so `bayes_auc()` scores
      the TRUE click probability on a held-out sample — the AUC no model
      can beat, the honest comparison point for trained AUC.
    """

    def __init__(self, batch_size: int = 2048, seed: int = 0,
                 split: str = "train", num_cat: int = 26,
                 num_dense: int = 13, cardinality_cap: int = 1 << 22,
                 dtype=np.int32):
        if num_cat > len(CRITEO_KAGGLE_CARDINALITIES):
            raise ValueError(f"num_cat <= {len(CRITEO_KAGGLE_CARDINALITIES)}")
        self.B = batch_size
        self.seed = seed
        self.split = split
        self.num_cat = num_cat
        self.num_dense = num_dense
        self.dtype = dtype
        self.cards = tuple(
            min(c, cardinality_cap)
            for c in CRITEO_KAGGLE_CARDINALITIES[:num_cat]
        )
        # Per-column zipf exponents and signal strengths, deterministic in
        # the column index alone (shared by every split/seed: the TASK is
        # fixed, only the sampled stream varies). A few strong columns +
        # a long weak tail mirrors real CTR feature importance.
        idx = np.arange(num_cat)
        self.zipf_a = 1.05 + 0.25 * (
            (_mix64(idx.astype(_U64) ^ _U64(0xC0FFEE)) >> _U64(40)).astype(
                np.float64
            )
            / 2.0 ** 24
        )
        order = (_mix64(idx.astype(_U64) ^ _U64(0xBEEF)) >> _U64(40)).argsort()
        rank = np.empty(num_cat, np.int64)
        rank[order] = idx
        # 0.62 puts the Bayes ceiling at ~0.80 — the regime real Criteo
        # models live in (reference WDL 0.774, Kaggle-winning ~0.81).
        self.strength = (0.62 / np.sqrt(1.0 + rank)).astype(np.float32)
        self.dense_missing = np.asarray(
            CRITEO_DENSE_MISSING[:num_dense], np.float64
        )
        dseed = np.arange(num_dense).astype(_U64)
        self.dense_sigma = 0.5 + 1.5 * (
            (_mix64(dseed ^ _U64(0xD00D)) >> _U64(40)).astype(np.float64)
            / 2.0 ** 24
        )
        self.dense_weight = 0.25 * _hash_normal(dseed, salt=0xDA7A)
        self._index = 0  # producer position: next batch batch() will emit
        # Consumer position: next batch the TRAIN LOOP has yet to receive.
        # Under a prefetch ring the producer runs `depth` batches ahead, so
        # checkpointing `_index` would silently skip the in-flight batches
        # on restore; once a staging layer wires `mark_consumed`, save()
        # reports this counter instead (exactly-once replay).
        self._consumed = 0
        self._consumer_attached = False
        self.intercept = self._calibrate_intercept()

    # ------------------------------------------------------------ internals

    def _stream_rng(self, index: int,
                    split: Optional[str] = None) -> np.random.Generator:
        """Stream generator for batch `index` of `split` (default: this
        instance's split). The split rides as a PARAMETER — never mutated
        on the instance — so `_calibrate_intercept`/`bayes_auc` can draw
        from the calib/eval streams while a concurrent prefetch thread
        keeps generating train batches from the train salt."""
        salt = {"train": 1, "eval": 2, "calib": 3}.get(split or self.split, 99)
        return np.random.default_rng((self.seed, salt, index))

    def _raw_logit(self, rng: np.random.Generator, n: int):
        """Sample (cats [num_cat, n], dense [n, num_dense], centered logit)."""
        cats = np.empty((self.num_cat, n), np.int64)
        logit = np.zeros(n, np.float32)
        for c in range(self.num_cat):
            ids = zipf_ids(rng, self.cards[c], float(self.zipf_a[c]), (n,))
            cats[c] = ids
            # weight of (column, id): stateless hash -> N(0, strength_c^2)
            key = ids.astype(_U64) | (_U64(c) << _U64(40))
            logit += self.strength[c] * _hash_normal(key, salt=0x5EED)
        missing = rng.random((n, self.num_dense)) < self.dense_missing
        dense = rng.lognormal(
            0.0, 1.0, (n, self.num_dense)
        ) * self.dense_sigma
        dense = np.where(missing, 0.0, dense).astype(np.float32)
        logit += np.log1p(dense) @ self.dense_weight
        return cats, dense, logit

    def _calibrate_intercept(self) -> float:
        """Solve sigmoid-intercept so mean click prob == the Kaggle CTR
        (deterministic: fixed calib stream, bisection on the sample)."""
        rng = self._stream_rng(0, split="calib")
        _, _, logit = self._raw_logit(rng, 100_000)
        lo, hi = -12.0, 12.0
        for _ in range(50):
            mid = (lo + hi) / 2
            if np.mean(1.0 / (1.0 + np.exp(-(logit + mid)))) < CRITEO_KAGGLE_CTR:
                lo = mid
            else:
                hi = mid
        return float((lo + hi) / 2)

    # -------------------------------------------------------------- public

    def probs_at(self, index: int, n: Optional[int] = None,
                 split: Optional[str] = None):
        """(batch dict, true click probs) — the generator's oracle view,
        used by bayes_auc and the generator's own tests. `split` overrides
        this instance's stream (thread-safe: no instance mutation)."""
        n = n or self.B
        rng = self._stream_rng(index, split=split)
        cats, dense, logit = self._raw_logit(rng, n)
        prob = 1.0 / (1.0 + np.exp(-(logit + self.intercept)))
        label = (rng.random(n) < prob).astype(np.float32)
        out: Dict[str, np.ndarray] = {"label": label}
        for i in range(self.num_dense):
            out[f"I{i + 1}"] = dense[:, i:i + 1]
        for c in range(self.num_cat):
            out[f"C{c + 1}"] = cats[c].astype(self.dtype)
        return out, prob.astype(np.float32)

    def batch_at(self, index: int) -> Dict[str, np.ndarray]:
        """Batch `index` of this (seed, split) stream — pure function."""
        return self.probs_at(index)[0]

    def batch(self) -> Dict[str, np.ndarray]:
        out = self.batch_at(self._index)
        self._index += 1
        return out

    def attach_consumer(self) -> None:
        """Declare that a staging ring decouples production from
        consumption (call at WIRING time, before the ring's producer runs
        ahead): from here on save() reports the consumed position. Without
        this, a save taken after staging but before the first delivery —
        e.g. immediately after a restore — would still report the
        ran-ahead producer index and skip the in-flight batches."""
        self._consumer_attached = True

    def mark_consumed(self) -> None:
        """One batch DELIVERED to the train loop (call from the staging
        layer's consumer side — Prefetcher(on_consume=...))."""
        self._consumer_attached = True
        self._consumed += 1

    def save(self) -> Dict:
        # Unstaged iteration (produce == consume) keeps the legacy producer
        # index so direct batch() users checkpoint exactly as before.
        return {
            "index": self._consumed if self._consumer_attached else self._index
        }

    def restore(self, state: Dict) -> None:
        self._index = int(state["index"])
        self._consumed = int(state["index"])
        self._consumer_attached = False

    def bayes_auc(self, n: int = 500_000) -> float:
        """AUC of the TRUE click probability on a held-out sample — the
        ceiling no trained model can exceed (up to sampling noise)."""
        out, prob = self.probs_at(10_000_000, n, split="eval")
        return float(_auc(out["label"], prob))

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()


def _auc(label: np.ndarray, score: np.ndarray) -> float:
    """Exact rank AUC; tied scores get their midrank (without it the
    result is input-order-dependent for discrete scores)."""
    _, inv, cnt = np.unique(score, return_inverse=True, return_counts=True)
    starts = np.concatenate([[0], np.cumsum(cnt)[:-1]]) + 1.0
    ranks = (starts + (cnt - 1) / 2.0)[inv]
    npos = float(label.sum())
    nneg = float(len(label) - npos)
    if npos == 0 or nneg == 0:
        return 0.5
    return (ranks[label > 0.5].sum() - npos * (npos + 1) / 2) / (npos * nneg)


class SyntheticTwoTower:
    """User/item id features + label from hidden affinity, for DSSM."""

    def __init__(self, batch_size=512, num_user=4, num_item=4, vocab=10_000,
                 zipf_a: float = 1.2, seed=0, dtype=np.int32):
        self.B = batch_size
        self.num_user = num_user
        self.num_item = num_item
        self.vocab = vocab
        self.zipf_a = zipf_a
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        wrng = np.random.default_rng(4242)
        self.vec = wrng.normal(0, 1, size=(num_user + num_item, vocab, 4)).astype(
            np.float32
        )
        # Per-id popularity/propensity biases: real click logs are dominated
        # by these first-order effects, and they give the towers a gradient
        # signal learnable in O(100) steps — a PURELY bilinear label (the
        # old workload) needs both towers aligned before any AUC moves,
        # which is why DSSM smoke-tested at coin-flip.
        self.bias = wrng.normal(0, 1.0, size=(num_user + num_item, vocab)).astype(
            np.float32
        )

    def batch(self) -> Dict[str, np.ndarray]:
        # zipf ids: real interaction logs are heavy-tailed, and head mass is
        # what makes the workload learnable in a bounded smoke run — uniform
        # ids gave each id ~6 observations total and DSSM smoke-tested at
        # coin-flip.
        ids = zipf_ids(self.rng, self.vocab, self.zipf_a,
                       (self.num_user + self.num_item, self.B))
        u = sum(self.vec[i, ids[i]] for i in range(self.num_user))
        v = sum(
            self.vec[self.num_user + i, ids[self.num_user + i]]
            for i in range(self.num_item)
        )
        pop = sum(
            self.bias[i, ids[i]] for i in range(self.num_user + self.num_item)
        )
        logit = (u * v).sum(1) * 0.5 + pop * 0.5
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(self.B) < prob).astype(np.float32)
        out = {"label": label}
        for i in range(self.num_user):
            out[f"U{i}"] = ids[i].astype(self.dtype)
        for i in range(self.num_item):
            out[f"V{i}"] = (ids[self.num_user + i] + (i + 1) * self.vocab).astype(
                self.dtype
            )
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()


class SyntheticBehaviorSequence:
    """Taobao user-behavior layout for DIN/DIEN/BST (matches
    models/taobao.behavior_features): user, target_item/target_cat,
    variable-length hist_items/hist_cats (pad -1), label.

    Label structure: a click is more likely when the target item's hidden
    embedding aligns with the user's history — so attention models can
    demonstrably learn."""

    def __init__(
        self,
        batch_size: int = 512,
        vocab: int = 50_000,
        num_cats: int = 1000,
        seq_len: int = 50,
        seed: int = 0,
        dtype=np.int32,
    ):
        self.B = batch_size
        self.vocab = vocab
        self.num_cats = num_cats
        self.seq_len = seq_len
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        wrng = np.random.default_rng(777)
        self.item_vec = wrng.normal(0, 1, size=(vocab, 8)).astype(np.float32)
        # fixed item -> category mapping
        self.item_cat = wrng.integers(0, num_cats, size=(vocab,))
        # first-order target-item/category propensity (see SyntheticTwoTower:
        # makes the workload learnable fast; the history-affinity term still
        # rewards attention over the sequence)
        self.item_bias = wrng.normal(0, 1.0, size=(vocab,)).astype(np.float32)
        self.cat_bias = wrng.normal(0, 1.0, size=(num_cats,)).astype(np.float32)

    def _zipf_ids(self, shape):
        return zipf_ids(self.rng, self.vocab, 1.0, shape)

    def batch(self) -> Dict[str, np.ndarray]:
        B, L = self.B, self.seq_len
        hist = self._zipf_ids((B, L))
        lengths = self.rng.integers(1, L + 1, size=(B,))
        mask = np.arange(L)[None, :] < lengths[:, None]
        target = self._zipf_ids((B,))
        user = self._zipf_ids((B,))
        # label: affinity of target with mean history vector
        hvec = (self.item_vec[hist] * mask[..., None]).sum(1) / np.maximum(
            lengths[:, None], 1
        )
        logit = (
            (hvec * self.item_vec[target]).sum(1) * 1.5
            + self.item_bias[target]
            + self.cat_bias[self.item_cat[target]] * 0.5
        )
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(B) < prob).astype(np.float32)
        return {
            "label": label,
            "user": user.astype(self.dtype),
            "target_item": target.astype(self.dtype),
            "target_cat": self.item_cat[target].astype(self.dtype),
            "hist_items": np.where(mask, hist, -1).astype(self.dtype),
            "hist_cats": np.where(mask, self.item_cat[hist], -1).astype(self.dtype),
        }

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()
