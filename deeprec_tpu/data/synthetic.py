"""Synthetic click-log generators with learnable structure.

Used by tests and the benchmark harness when no real dataset is mounted: ids
are zipf-distributed (recommendation workloads are heavy-tailed — this is
what exercises admission filters, caches and all2all skew), and the label is
a noisy logistic function of hidden per-id weights, so a correct trainer
demonstrably lifts AUC above 0.5.
"""
from __future__ import annotations

from typing import Dict, Iterator, Optional

import numpy as np


def zipf_ids(rng: np.random.Generator, vocab: int, a: float, shape):
    """Bounded zipf(a) via inverse-CDF over a fixed vocab: a=1 is the
    log-uniform limit; larger a concentrates mass on head ids."""
    u = rng.random(shape)
    if abs(a - 1.0) < 1e-6:
        ranks = np.floor(np.exp(u * np.log(vocab))).astype(np.int64)
    else:
        v = vocab ** (1.0 - a)
        ranks = np.floor((u * (v - 1.0) + 1.0) ** (1.0 / (1.0 - a))).astype(
            np.int64
        )
    return np.clip(ranks, 1, vocab) - 1


class SyntheticCriteo:
    """Batches shaped like Criteo: I1-I13 floats [B,1], C1-C26 int ids [B],
    label [B]."""

    def __init__(
        self,
        batch_size: int = 2048,
        num_cat: int = 26,
        num_dense: int = 13,
        vocab: int = 100_000,
        zipf_a: float = 1.2,
        seed: int = 0,
        dtype=np.int32,
    ):
        self.B = batch_size
        self.num_cat = num_cat
        self.num_dense = num_dense
        self.vocab = vocab
        self.zipf_a = zipf_a
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        # hidden ground-truth weights giving the label structure
        wrng = np.random.default_rng(12345)
        self.id_weight = wrng.normal(0, 1.0, size=(num_cat, vocab)).astype(np.float32)
        self.dense_weight = wrng.normal(0, 0.5, size=(num_dense,)).astype(np.float32)

    def _zipf_ids(self, shape):
        return zipf_ids(self.rng, self.vocab, self.zipf_a, shape)

    def batch(self) -> Dict[str, np.ndarray]:
        cats = self._zipf_ids((self.num_cat, self.B))
        dense = self.rng.lognormal(0.0, 1.0, size=(self.B, self.num_dense)).astype(
            np.float32
        )
        logit = np.zeros((self.B,), np.float32)
        for c in range(self.num_cat):
            logit += self.id_weight[c, cats[c]] * 0.3
        logit += np.log1p(dense) @ self.dense_weight * 0.3
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(self.B) < prob).astype(np.float32)
        out: Dict[str, np.ndarray] = {"label": label}
        for i in range(self.num_dense):
            out[f"I{i+1}"] = dense[:, i : i + 1]
        for c in range(self.num_cat):
            # offset ids per-feature so tables see disjoint key spaces
            out[f"C{c+1}"] = (cats[c] + c * self.vocab).astype(self.dtype)
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()


class SyntheticMultiTask(SyntheticCriteo):
    """Adds correlated ctr/cvr/ctcvr labels for the multi-task models
    (ESMM/MMoE/PLE/DBMTL/SimpleMultiTask). cvr is only observable given a
    click — the entire-space structure ESMM exploits."""

    def batch(self) -> Dict[str, np.ndarray]:
        out = super().batch()
        click = out.pop("label")
        # conversion correlates with the same hidden structure, rarer
        conv_noise = self.rng.random(self.B)
        conv_given_click = (conv_noise < 0.3).astype(np.float32)
        out["label_ctr"] = click
        out["label_cvr"] = click * conv_given_click
        out["label_ctcvr"] = click * conv_given_click
        return out


class SyntheticTwoTower:
    """User/item id features + label from hidden affinity, for DSSM."""

    def __init__(self, batch_size=512, num_user=4, num_item=4, vocab=10_000,
                 zipf_a: float = 1.2, seed=0, dtype=np.int32):
        self.B = batch_size
        self.num_user = num_user
        self.num_item = num_item
        self.vocab = vocab
        self.zipf_a = zipf_a
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        wrng = np.random.default_rng(4242)
        self.vec = wrng.normal(0, 1, size=(num_user + num_item, vocab, 4)).astype(
            np.float32
        )
        # Per-id popularity/propensity biases: real click logs are dominated
        # by these first-order effects, and they give the towers a gradient
        # signal learnable in O(100) steps — a PURELY bilinear label (the
        # old workload) needs both towers aligned before any AUC moves,
        # which is why DSSM smoke-tested at coin-flip.
        self.bias = wrng.normal(0, 1.0, size=(num_user + num_item, vocab)).astype(
            np.float32
        )

    def batch(self) -> Dict[str, np.ndarray]:
        # zipf ids: real interaction logs are heavy-tailed, and head mass is
        # what makes the workload learnable in a bounded smoke run — uniform
        # ids gave each id ~6 observations total and DSSM smoke-tested at
        # coin-flip.
        ids = zipf_ids(self.rng, self.vocab, self.zipf_a,
                       (self.num_user + self.num_item, self.B))
        u = sum(self.vec[i, ids[i]] for i in range(self.num_user))
        v = sum(
            self.vec[self.num_user + i, ids[self.num_user + i]]
            for i in range(self.num_item)
        )
        pop = sum(
            self.bias[i, ids[i]] for i in range(self.num_user + self.num_item)
        )
        logit = (u * v).sum(1) * 0.5 + pop * 0.5
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(self.B) < prob).astype(np.float32)
        out = {"label": label}
        for i in range(self.num_user):
            out[f"U{i}"] = ids[i].astype(self.dtype)
        for i in range(self.num_item):
            out[f"V{i}"] = (ids[self.num_user + i] + (i + 1) * self.vocab).astype(
                self.dtype
            )
        return out

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()


class SyntheticBehaviorSequence:
    """Taobao user-behavior layout for DIN/DIEN/BST (matches
    models/taobao.behavior_features): user, target_item/target_cat,
    variable-length hist_items/hist_cats (pad -1), label.

    Label structure: a click is more likely when the target item's hidden
    embedding aligns with the user's history — so attention models can
    demonstrably learn."""

    def __init__(
        self,
        batch_size: int = 512,
        vocab: int = 50_000,
        num_cats: int = 1000,
        seq_len: int = 50,
        seed: int = 0,
        dtype=np.int32,
    ):
        self.B = batch_size
        self.vocab = vocab
        self.num_cats = num_cats
        self.seq_len = seq_len
        self.rng = np.random.default_rng(seed)
        self.dtype = dtype
        wrng = np.random.default_rng(777)
        self.item_vec = wrng.normal(0, 1, size=(vocab, 8)).astype(np.float32)
        # fixed item -> category mapping
        self.item_cat = wrng.integers(0, num_cats, size=(vocab,))
        # first-order target-item/category propensity (see SyntheticTwoTower:
        # makes the workload learnable fast; the history-affinity term still
        # rewards attention over the sequence)
        self.item_bias = wrng.normal(0, 1.0, size=(vocab,)).astype(np.float32)
        self.cat_bias = wrng.normal(0, 1.0, size=(num_cats,)).astype(np.float32)

    def _zipf_ids(self, shape):
        return zipf_ids(self.rng, self.vocab, 1.0, shape)

    def batch(self) -> Dict[str, np.ndarray]:
        B, L = self.B, self.seq_len
        hist = self._zipf_ids((B, L))
        lengths = self.rng.integers(1, L + 1, size=(B,))
        mask = np.arange(L)[None, :] < lengths[:, None]
        target = self._zipf_ids((B,))
        user = self._zipf_ids((B,))
        # label: affinity of target with mean history vector
        hvec = (self.item_vec[hist] * mask[..., None]).sum(1) / np.maximum(
            lengths[:, None], 1
        )
        logit = (
            (hvec * self.item_vec[target]).sum(1) * 1.5
            + self.item_bias[target]
            + self.cat_bias[self.item_cat[target]] * 0.5
        )
        prob = 1.0 / (1.0 + np.exp(-(logit - logit.mean())))
        label = (self.rng.random(B) < prob).astype(np.float32)
        return {
            "label": label,
            "user": user.astype(self.dtype),
            "target_item": target.astype(self.dtype),
            "target_cat": self.item_cat[target].astype(self.dtype),
            "hist_items": np.where(mask, hist, -1).astype(self.dtype),
            "hist_cats": np.where(mask, self.item_cat[hist], -1).astype(self.dtype),
        }

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        while True:
            yield self.batch()
