"""Columnar input readers — the ParquetDataset / CSV path of DeepRec
(core/kernels/data/parquet_dataset_ops.cc, arrow-based;
modelzoo train.py CSV readers). Host-side, feeding the staged prefetcher.

Criteo layout: label \\t I1..I13 \\t C1..C26 (categorical as hex strings).
Categorical values are hashed to the table key space with the same mix used
by the embedding engine, so readers and tables agree on id semantics.
"""
from __future__ import annotations

import zlib
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

CRITEO_COLUMNS = (
    ["label"] + [f"I{i}" for i in range(1, 14)] + [f"C{i}" for i in range(1, 27)]
)


class RecordErrors:
    """Structured per-record error counter — the first line of the
    model-quality firewall (guard/): malformed input is rejected or
    clamped HERE, counted by kind, instead of propagating NaN/garbage
    into the trainer where only the step sentinel can still catch it.

    Kinds are a BOUNDED set (DRT007 discipline — they also become the
    ``kind=`` label of ``deeprec_record_errors``): ``bad_label`` /
    ``bad_float`` (unparseable text), ``nonfinite_float`` (parsed but
    inf/NaN), ``bad_id`` (negative/out-of-range id clamped to pad),
    ``oversized_bag`` (id bag trimmed), ``oversized_frame`` (stream
    frame skipped by the bounded resync), ``undecodable`` (record
    dropped entirely)."""

    KINDS = ("bad_label", "bad_float", "nonfinite_float", "bad_id",
             "oversized_bag", "oversized_frame", "undecodable")

    def __init__(self, metrics: bool = True):
        self.counts: Dict[str, int] = {}
        self._metrics = metrics

    def count(self, kind: str, n: int = 1) -> None:
        if n <= 0:
            return
        self.counts[kind] = self.counts.get(kind, 0) + int(n)  # noqa: DRT002 — host error counter on host parse results
        if self._metrics:
            from deeprec_tpu.obs import metrics as obs_metrics

            if obs_metrics.metrics_enabled():
                obs_metrics.default_registry().counter(
                    "deeprec_record_errors",
                    "malformed input records rejected/clamped by kind",
                    {"kind": kind},
                ).inc(n)

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counts)


def sanitize_batch(batch: Dict[str, np.ndarray],
                   errors: Optional[RecordErrors] = None,
                   pad_value: int = -1,
                   max_id: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Clamp a parsed numpy batch in place of trusting it: non-finite
    floats become 0 (counted ``nonfinite_float``), negative ids other
    than the pad value — and ids past ``max_id`` when given — become the
    pad value (counted ``bad_id``). Label keys clamp non-finite to 0
    too. Returns the batch (arrays copied only when dirty)."""
    out = {}
    for k, v in batch.items():
        a = np.asarray(v)
        if np.issubdtype(a.dtype, np.floating):
            bad = ~np.isfinite(a)
            if bad.any():
                if errors is not None:
                    errors.count("nonfinite_float", int(bad.sum()))
                a = np.where(bad, np.zeros((), a.dtype), a)
        elif np.issubdtype(a.dtype, np.integer) and not k.startswith("label"):
            bad = (a < 0) & (a != pad_value)
            if max_id is not None:
                bad = bad | (a > max_id)
            if bad.any():
                if errors is not None:
                    errors.count("bad_id", int(bad.sum()))
                a = np.where(bad, np.asarray(pad_value, a.dtype), a)
        out[k] = a
    return out


def _hash_strings(col: "np.ndarray", salt: int) -> np.ndarray:
    """Vectorized string -> int32 id (crc32-based; stable across runs)."""
    out = np.empty(len(col), np.int32)
    for i, v in enumerate(col):
        if v is None or v == "" or (isinstance(v, float) and np.isnan(v)):
            out[i] = -1
        else:
            out[i] = (zlib.crc32(str(v).encode()) ^ salt) & 0x7FFFFFFF
    return out


class _RangeFile:
    """Read-only file-like view of bytes [lo, hi) of a file — lets pandas
    stream a byte-range slice chunk-by-chunk instead of materializing it."""

    def __init__(self, path: str, lo: int, hi: int):
        self._f = open(path, "rb")
        self._f.seek(lo)
        self._left = hi - lo

    def read(self, n: int = -1) -> bytes:
        if self._left <= 0:
            return b""
        if n is None or n < 0 or n > self._left:
            n = self._left
        data = self._f.read(n)
        self._left -= len(data)
        return data

    def readline(self, *a) -> bytes:  # pandas' python engine probes this
        if self._left <= 0:
            return b""
        line = self._f.readline(self._left)
        self._left -= len(line)
        return line

    def __iter__(self):
        while True:
            line = self.readline()
            if not line:
                return
            yield line

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class CriteoCSVReader:
    """Batched reader for Criteo-format TSV files.

    `byte_range=(lo, hi)` restricts reading to that line-aligned span of a
    SINGLE file (WorkQueue file-slice sharding: path#k/n items) — streamed
    in place, no copy of the slice."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int = 2048,
        num_dense: int = 13,
        num_cat: int = 26,
        drop_remainder: bool = True,
        byte_range: Optional[tuple] = None,
    ):
        self.paths = list(paths)
        self.B = batch_size
        self.num_dense = num_dense
        self.num_cat = num_cat
        self.drop_remainder = drop_remainder
        self.byte_range = byte_range
        # Firewall: every yielded batch passes sanitize_batch (non-finite
        # floats -> 0, negative ids -> pad), counted here by kind.
        self.errors = RecordErrors()
        if byte_range is not None and len(self.paths) != 1:
            raise ValueError("byte_range applies to exactly one file")

    def _frame_to_batches(self, df) -> Iterator[Dict[str, np.ndarray]]:
        import pandas as pd  # noqa

        n = len(df)
        for start in range(0, n, self.B):
            chunk = df.iloc[start : start + self.B]
            if len(chunk) < self.B and self.drop_remainder:
                return
            out: Dict[str, np.ndarray] = {
                "label": chunk["label"].to_numpy(np.float32)
            }
            for i in range(1, self.num_dense + 1):
                # raw values here; sanitize_batch clamps non-finite to 0
                # AND counts them (np.nan_to_num hid inf as 3.4e38 — an
                # extreme-magnitude poison, exactly what the firewall
                # exists to stop)
                out[f"I{i}"] = (
                    chunk[f"I{i}"].to_numpy(np.float32).reshape(-1, 1)
                )
            for i in range(1, self.num_cat + 1):
                out[f"C{i}"] = _hash_strings(
                    chunk[f"C{i}"].to_numpy(object), salt=i * 0x9E3779B9 & 0x7FFFFFFF
                )
            yield out

    def _iter_native(self) -> Optional[Iterator[Dict[str, np.ndarray]]]:
        """Stream batches through the C++ parser (native/csv_parser.cpp) —
        one pass over raw bytes, no DataFrame. Falls back to pandas when the
        native library is unavailable. Id hashing is identical either way."""
        from deeprec_tpu.native import criteo_parse_native, load_library

        if load_library() is None:
            return None

        def gen():
            CHUNK = max(1 << 20, self.B * 512)
            for path in self.paths:
                with open(path, "rb") as f:
                    remaining = None
                    if self.byte_range is not None:
                        lo, hi = self.byte_range
                        f.seek(lo)
                        remaining = hi - lo
                    pending = b""
                    while True:
                        want = (
                            CHUNK if remaining is None
                            else min(CHUNK, remaining)
                        )
                        fresh = f.read(want)
                        if remaining is not None:
                            remaining -= len(fresh)
                        data = pending + fresh
                        if not data:
                            break
                        at_eof = len(fresh) < CHUNK
                        if at_eof and not data.endswith(b"\n"):
                            # Terminate the final line so the native parser
                            # consumes it, matching the pandas fallback.
                            data += b"\n"
                        out = criteo_parse_native(
                            data, self.B, self.num_dense, self.num_cat
                        )
                        if out is None:
                            return
                        rows, labels, dense, cats, consumed = out
                        if rows < self.B and not at_eof:
                            pending = data  # need more bytes for a full batch
                            continue
                        pending = data[consumed:]
                        if rows == 0:
                            if at_eof:
                                break
                            continue
                        if rows < self.B and self.drop_remainder:
                            break
                        batch: Dict[str, np.ndarray] = {
                            "label": labels[:rows]
                        }
                        for i in range(self.num_dense):
                            batch[f"I{i+1}"] = dense[:rows, i : i + 1]
                        for i in range(self.num_cat):
                            batch[f"C{i+1}"] = cats[:rows, i]
                        yield batch

        return gen()

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        native = self._iter_native()
        if native is not None:
            for batch in native:
                yield sanitize_batch(batch, self.errors)
            return
        import contextlib

        import pandas as pd

        for path in self.paths:
            with contextlib.ExitStack() as stack:
                if self.byte_range is not None:
                    lo, hi = self.byte_range
                    src = stack.enter_context(_RangeFile(path, lo, hi))
                else:
                    src = path
                for df in pd.read_csv(
                    src,
                    sep="\t",
                    names=CRITEO_COLUMNS[: 1 + self.num_dense + self.num_cat],
                    chunksize=self.B * 16,
                    header=None,
                ):
                    for batch in self._frame_to_batches(df):
                        yield sanitize_batch(batch, self.errors)


class ParquetReader:
    """Arrow-backed parquet batch reader (ParquetDataset parity). Columns map
    1:1 to batch keys; string/categorical columns are hashed to int32 ids."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int = 2048,
        columns: Optional[Sequence[str]] = None,
        hash_columns: Sequence[str] = (),
        drop_remainder: bool = True,
    ):
        self.paths = list(paths)
        self.B = batch_size
        self.columns = list(columns) if columns else None
        self.hash_columns = set(hash_columns)
        self.drop_remainder = drop_remainder

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        import pyarrow.parquet as pq

        buf: Dict[str, List[np.ndarray]] = {}
        count = 0
        for path in self.paths:
            pf = pq.ParquetFile(path)
            for rb in pf.iter_batches(batch_size=self.B, columns=self.columns):
                cols = {}
                for name, col in zip(rb.schema.names, rb.columns):
                    arr = col.to_numpy(zero_copy_only=False)
                    if name in self.hash_columns or arr.dtype == object:
                        arr = _hash_strings(arr, salt=zlib.crc32(name.encode()))
                    cols[name] = arr
                for name, arr in cols.items():
                    buf.setdefault(name, []).append(arr)
                count += len(next(iter(cols.values())))
                while count >= self.B:
                    batch, buf, count = _take(buf, self.B)
                    yield batch
        if count and not self.drop_remainder:
            batch, buf, count = _take(buf, count)
            yield batch


def _take(buf, n):
    joined = {k: np.concatenate(v) for k, v in buf.items()}
    batch = {k: v[:n] for k, v in joined.items()}
    rest = {k: [v[n:]] for k, v in joined.items()}
    remaining = len(next(iter(rest.values()))[0])
    return batch, rest, remaining
