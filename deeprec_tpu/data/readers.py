"""Columnar input readers — the ParquetDataset / CSV path of DeepRec
(core/kernels/data/parquet_dataset_ops.cc, arrow-based;
modelzoo train.py CSV readers). Host-side, feeding the staged prefetcher.

Criteo layout: label \\t I1..I13 \\t C1..C26 (categorical as hex strings).
Categorical values are hashed to the table key space with the same mix used
by the embedding engine, so readers and tables agree on id semantics.
"""
from __future__ import annotations

import threading
import zlib
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

CRITEO_COLUMNS = (
    ["label"] + [f"I{i}" for i in range(1, 14)] + [f"C{i}" for i in range(1, 27)]
)


def criteo_hash_salts(num_cat: int = 26) -> Dict[str, int]:
    """The per-column id salts of the CSV/stream readers, keyed by column
    name. Pass to ``ParquetReader(hash_salts=...)`` so parquet-stored
    categorical strings hash to the SAME ids as the TSV path (the format
    parity gate, tests/test_input_pipeline.py)."""
    return {f"C{i}": i * 0x9E3779B9 & 0x7FFFFFFF
            for i in range(1, num_cat + 1)}


class RecordErrors:
    """Structured per-record error counter — the first line of the
    model-quality firewall (guard/): malformed input is rejected or
    clamped HERE, counted by kind, instead of propagating NaN/garbage
    into the trainer where only the step sentinel can still catch it.

    Kinds are a BOUNDED set (DRT007 discipline — they also become the
    ``kind=`` label of ``deeprec_record_errors``): ``bad_label`` /
    ``bad_float`` (unparseable text), ``nonfinite_float`` (parsed but
    inf/NaN), ``bad_id`` (negative/out-of-range id clamped to pad),
    ``oversized_bag`` (id bag trimmed), ``oversized_frame`` (stream
    frame skipped by the bounded resync), ``undecodable`` (record
    dropped entirely)."""

    KINDS = ("bad_label", "bad_float", "nonfinite_float", "bad_id",
             "oversized_bag", "oversized_frame", "undecodable")

    def __init__(self, metrics: bool = True):
        self.counts: Dict[str, int] = {}
        self._metrics = metrics
        # Parallel pipeline workers (data/pipeline.py) share one instance;
        # the read-modify-write below needs the lock to stay exact.
        self._lock = threading.Lock()

    def count(self, kind: str, n: int = 1) -> None:
        if n <= 0:
            return
        with self._lock:
            self.counts[kind] = self.counts.get(kind, 0) + int(n)  # noqa: DRT002 — host error counter on host parse results
        if self._metrics:
            from deeprec_tpu.obs import metrics as obs_metrics

            if obs_metrics.metrics_enabled():
                obs_metrics.default_registry().counter(
                    "deeprec_record_errors",
                    "malformed input records rejected/clamped by kind",
                    {"kind": kind},
                ).inc(n)

    @property
    def total(self) -> int:
        return sum(self.counts.values())

    def snapshot(self) -> Dict[str, int]:
        return dict(self.counts)


def sanitize_batch(batch: Dict[str, np.ndarray],
                   errors: Optional[RecordErrors] = None,
                   pad_value: int = -1,
                   max_id: Optional[int] = None) -> Dict[str, np.ndarray]:
    """Clamp a parsed numpy batch in place of trusting it: non-finite
    floats become 0 (counted ``nonfinite_float``), negative ids other
    than the pad value — and ids past ``max_id`` when given — become the
    pad value (counted ``bad_id``). Label keys clamp non-finite to 0
    too. Returns the batch (arrays copied only when dirty)."""
    out = {}
    for k, v in batch.items():
        a = np.asarray(v)  # noqa: DRT002 — host batch sanitize (numpy reader output), never a device array
        if np.issubdtype(a.dtype, np.floating):
            bad = ~np.isfinite(a)
            if bad.any():
                if errors is not None:
                    errors.count("nonfinite_float", int(bad.sum()))  # noqa: DRT002 — host error counter on a numpy batch
                a = np.where(bad, np.zeros((), a.dtype), a)
        elif np.issubdtype(a.dtype, np.integer) and not k.startswith("label"):
            bad = (a < 0) & (a != pad_value)
            if max_id is not None:
                bad = bad | (a > max_id)
            if bad.any():
                if errors is not None:
                    errors.count("bad_id", int(bad.sum()))  # noqa: DRT002 — host error counter on a numpy batch
                a = np.where(bad, np.asarray(pad_value, a.dtype), a)  # noqa: DRT002 — host batch sanitize, never a device array
        out[k] = a
    return out


def _hash_strings(col: "np.ndarray", salt: int) -> np.ndarray:
    """String -> int32 id (crc32-based; stable across runs). Memoized per
    call: real id columns are heavily repeated (zipf), so the crc is paid
    once per DISTINCT value. The block parser goes further (np.unique over
    an S-dtype column); this path keeps exact semantics for object arrays
    with None/NaN holes."""
    out = np.empty(len(col), np.int32)
    cache: Dict[str, int] = {}
    for i, v in enumerate(col):
        if v is None or v == "" or (isinstance(v, float) and np.isnan(v)):
            out[i] = -1
        else:
            s = str(v)
            h = cache.get(s)
            if h is None:
                cache[s] = h = (zlib.crc32(s.encode()) ^ salt) & 0x7FFFFFFF
            out[i] = h
    return out


def _parse_float_col(col: np.ndarray, errors: Optional[RecordErrors],
                     kind: str) -> np.ndarray:
    """One S-dtype text column -> float32, with `criteo_line_parser` float
    semantics: empty -> 0.0 silently, unparseable -> 0.0 counted under
    `kind`. Non-finite values pass through (the caller clamps + counts
    them block-wide, same as the line parser's post-loop sweep)."""
    filled = np.where(col == b"", b"0", col)
    try:
        vals = filled.astype(np.float64)  # numpy's parser == float() here
    except ValueError:
        vals = np.empty(len(filled), np.float64)
        nbad = 0
        for i, v in enumerate(filled):
            try:
                vals[i] = float(v)  # noqa: DRT002 — host text parse, pre-device
            except (TypeError, ValueError):
                vals[i] = 0.0
                nbad += 1
        if errors is not None:
            errors.count(kind, nbad)
    return vals.astype(np.float32)


def _crc_table() -> np.ndarray:
    t = np.zeros(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ (0xEDB88320 if c & 1 else 0)
        t[i] = np.uint32(c)
    return t


_CRC_T = _crc_table()  # the zlib crc32 polynomial table, vectorizable


def _hash_bytes_col(col: np.ndarray, salt: int) -> np.ndarray:
    """S-dtype column -> int32 ids via np.unique: crc paid once per
    DISTINCT value, scatter back through the inverse index. Matches
    `_hash_strings` bit-for-bit on utf-8-clean, NUL-free bytes (the block
    parser falls back to the per-line path otherwise)."""
    u, inv = np.unique(col, return_inverse=True)
    hu = np.empty(len(u), np.int32)
    for k, v in enumerate(u):
        hu[k] = -1 if v == b"" else (zlib.crc32(v) ^ salt) & 0x7FFFFFFF
    return hu[inv.reshape(col.shape)]


def _cube_parse_into(arr: np.ndarray, n: int, F: int, num_dense: int,
                     num_cat: int, labels, dense, cats,
                     errors: Optional[RecordErrors]) -> bool:
    """The no-Python-objects fast lane of `criteo_block_parse`: field
    boundaries from one separator scan, every field gathered into a
    fixed-width [n, F, w] byte cube, float columns bulk-astype'd through
    an S-dtype view, id columns hashed by a table-driven crc32 that
    iterates over BYTE POSITIONS (w of them) instead of rows. Requires
    uniform arity (the caller checked tabs == F-1 per line). Declines
    (returns False) when the widest field would make the cube silly —
    the caller then takes the S-matrix route."""
    sep = np.flatnonzero((arr == 9) | (arr == 10))
    ends = sep.reshape(n, F)
    starts = np.empty_like(ends)
    starts[:, 1:] = ends[:, :-1] + 1
    starts[0, 0] = 0
    starts[1:, 0] = ends[:-1, -1] + 1
    lens = ends - starts
    w = int(lens.max()) if n else 0  # noqa: DRT002 — host field-width scan over file bytes, never a device value
    if w == 0 or w > 128:
        return w == 0  # all-empty parses trivially; huge fields decline
    idx = starts[..., None] + np.arange(w)
    cube = arr[np.minimum(idx, len(arr) - 1)]
    cube[~(np.arange(w)[None, None, :] < lens[..., None])] = 0
    nf = 1 + num_dense
    fcols = np.ascontiguousarray(cube[:, :nf, :]).reshape(
        n * nf, w).view(f"|S{w}").reshape(n, nf)
    labels[:] = _parse_float_col(fcols[:, 0], errors, "bad_label")
    try:  # one astype for the whole dense block; per-column on garbage
        filled = np.where(fcols[:, 1:] == b"", b"0", fcols[:, 1:])
        dense[:] = filled.astype(np.float64).astype(np.float32)
    except ValueError:
        for i in range(num_dense):
            dense[:, i] = _parse_float_col(fcols[:, 1 + i], errors,
                                           "bad_float")
    cc = cube[:, nf:, :].reshape(n * num_cat, w)
    clens = lens[:, nf:].reshape(-1)
    crc = np.full(n * num_cat, 0xFFFFFFFF, np.uint32)
    for j in range(w):
        nxt = (crc >> np.uint32(8)) ^ _CRC_T[(crc ^ cc[:, j])
                                             & np.uint32(0xFF)]
        crc = np.where(clens > j, nxt, crc)
    crc = (crc ^ np.uint32(0xFFFFFFFF)).reshape(n, num_cat)
    salts = (np.arange(1, num_cat + 1, dtype=np.uint64) * 0x9E3779B9
             & 0x7FFFFFFF).astype(np.uint32)
    ids = ((crc ^ salts[None, :]) & np.uint32(0x7FFFFFFF)).astype(np.int32)
    ids[lens[:, nf:] == 0] = -1
    for c in range(num_cat):
        cats[c][:] = ids[:, c]
    return True


def criteo_block_parse(data: bytes, num_dense: int = 13, num_cat: int = 26,
                       errors: Optional[RecordErrors] = None
                       ) -> Dict[str, np.ndarray]:
    """Vectorized Criteo block parser — the parallel pipeline's hot loop.

    Takes a buffer of '\\n'-terminated TSV lines and produces the column
    dict (label [n] f32, I* [n,1] f32, C* [n] i32) in a handful of numpy
    ops: one split into an [n, F] S-dtype field matrix, bulk astype for
    the float columns, np.unique + crc32-of-distinct for the id columns.
    Bit-identical to `criteo_line_parser` applied to the decoded lines —
    including the RecordErrors clamp accounting, now counted per block —
    pinned by tests/test_input_pipeline.py. Lines that can't take the
    fast path (wrong field count, NUL bytes, non-utf8) are parsed
    per-line with the exact line-parser semantics and scattered back by
    row index, so one garbage record never slows the block around it."""
    if data and not data.endswith(b"\n"):
        data = data + b"\n"
    n = data.count(b"\n")
    F = 1 + num_dense + num_cat
    labels = np.zeros(n, np.float32)
    dense = np.zeros((n, num_dense), np.float32)
    cats = [np.full(n, -1, np.int32) for _ in range(num_cat)]
    if n == 0:
        return _criteo_assemble(labels, dense, cats, num_dense, num_cat)

    clean = b"\x00" not in data
    if clean:
        try:  # raw-bytes crc == crc of str.encode() only for valid utf-8
            data.decode("utf-8")
        except UnicodeDecodeError:
            clean = False

    arr = np.frombuffer(data, np.uint8)
    nl = np.flatnonzero(arr == 10)
    ctab = np.cumsum(arr == 9, dtype=np.int64)
    tabs_at_end = ctab[nl]
    tabs = np.diff(tabs_at_end, prepend=0)
    good = (tabs == F - 1) if clean else np.zeros(n, bool)

    if good.all() and _cube_parse_into(arr, n, F, num_dense, num_cat,
                                       labels, dense, cats, errors):
        m = None
        good_rows = np.empty(0, np.intp)
        good = np.ones(n, bool)
    elif good.all():
        fields = data[:-1].replace(b"\n", b"\t").split(b"\t")
        m = np.array(fields, dtype="S").reshape(n, F)  # noqa: DRT002 — host parse of file bytes, never a device array
        good_rows = None
    elif good.any():
        lines = data.split(b"\n")[:-1]
        good_rows = np.flatnonzero(good)
        gdata = b"\n".join([lines[i] for i in good_rows])
        fields = gdata.replace(b"\n", b"\t").split(b"\t")
        m = np.array(fields, dtype="S").reshape(len(good_rows), F)  # noqa: DRT002 — host parse of file bytes, never a device array
    else:
        m = None
        good_rows = np.empty(0, np.intp)

    if m is not None:
        rows = slice(None) if good_rows is None else good_rows
        labels[rows] = _parse_float_col(m[:, 0], errors, "bad_label")
        for i in range(num_dense):
            dense[rows, i] = _parse_float_col(m[:, 1 + i], errors,
                                              "bad_float")
        for c in range(num_cat):
            cats[c][rows] = _hash_bytes_col(
                m[:, 1 + num_dense + c],
                salt=(c + 1) * 0x9E3779B9 & 0x7FFFFFFF)

    if not good.all():
        lines = data.split(b"\n")[:-1]
        for r in np.flatnonzero(~good):
            _criteo_parse_line_into(
                lines[r].decode("utf-8", errors="replace"), r,
                labels, dense, cats, num_dense, num_cat, errors)

    # Non-finite sweep, block-wide — same ordering/kinds as the line
    # parser's post-loop clamp ("1e999" parses to inf, then clamps here).
    bad_label = ~np.isfinite(labels)
    if bad_label.any():
        labels[bad_label] = 0.0
        if errors is not None:
            errors.count("nonfinite_float", int(bad_label.sum()))  # noqa: DRT002 — host numpy count, pre-device
    bad = ~np.isfinite(dense)
    if bad.any():
        dense[bad] = 0.0
        if errors is not None:
            errors.count("nonfinite_float", int(bad.sum()))  # noqa: DRT002 — host numpy count, pre-device
    return _criteo_assemble(labels, dense, cats, num_dense, num_cat)


def _criteo_parse_line_into(line: str, r: int, labels, dense, cats,
                            num_dense: int, num_cat: int,
                            errors: Optional[RecordErrors]) -> None:
    """Exact `criteo_line_parser` semantics for ONE line (the block
    parser's slow lane): missing fields read as "", unparseable text
    clamps to 0 and counts, extra fields are ignored."""
    parts = line.split("\t")
    try:
        labels[r] = float(parts[0] or 0)  # noqa: DRT002 — host text parse, pre-device
    except (TypeError, ValueError):
        labels[r] = 0.0
        if errors is not None:
            errors.count("bad_label")
    for i in range(num_dense):
        v = parts[1 + i] if len(parts) > 1 + i else ""
        try:
            dense[r, i] = float(v) if v else 0.0  # noqa: DRT002 — host text parse, pre-device
        except (TypeError, ValueError):
            dense[r, i] = 0.0
            if errors is not None:
                errors.count("bad_float")
    for c in range(num_cat):
        j = 1 + num_dense + c
        v = parts[j] if len(parts) > j else ""
        salt = (c + 1) * 0x9E3779B9 & 0x7FFFFFFF
        cats[c][r] = (
            -1 if v == "" else (zlib.crc32(v.encode()) ^ salt) & 0x7FFFFFFF
        )


def _criteo_assemble(labels, dense, cats, num_dense, num_cat
                     ) -> Dict[str, np.ndarray]:
    out: Dict[str, np.ndarray] = {"label": labels}
    for i in range(num_dense):
        out[f"I{i+1}"] = dense[:, i:i + 1]
    for c in range(num_cat):
        out[f"C{c+1}"] = cats[c]
    return out


class _RangeFile:
    """Read-only file-like view of bytes [lo, hi) of a file — lets pandas
    stream a byte-range slice chunk-by-chunk instead of materializing it."""

    def __init__(self, path: str, lo: int, hi: int):
        self._f = open(path, "rb")
        self._f.seek(lo)
        self._left = hi - lo

    def read(self, n: int = -1) -> bytes:
        if self._left <= 0:
            return b""
        if n is None or n < 0 or n > self._left:
            n = self._left
        data = self._f.read(n)
        self._left -= len(data)
        return data

    def readline(self, *a) -> bytes:  # pandas' python engine probes this
        if self._left <= 0:
            return b""
        line = self._f.readline(self._left)
        self._left -= len(line)
        return line

    def __iter__(self):
        while True:
            line = self.readline()
            if not line:
                return
            yield line

    def close(self):
        self._f.close()

    def __enter__(self):
        return self

    def __exit__(self, *a):
        self.close()


class CriteoCSVReader:
    """Batched reader for Criteo-format TSV files.

    `byte_range=(lo, hi)` restricts reading to that line-aligned span of a
    SINGLE file (WorkQueue file-slice sharding: path#k/n items) — streamed
    in place, no copy of the slice."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int = 2048,
        num_dense: int = 13,
        num_cat: int = 26,
        drop_remainder: bool = True,
        byte_range: Optional[tuple] = None,
    ):
        self.paths = list(paths)
        self.B = batch_size
        self.num_dense = num_dense
        self.num_cat = num_cat
        self.drop_remainder = drop_remainder
        self.byte_range = byte_range
        # Firewall: every yielded batch passes sanitize_batch (non-finite
        # floats -> 0, negative ids -> pad), counted here by kind.
        self.errors = RecordErrors()
        if byte_range is not None and len(self.paths) != 1:
            raise ValueError("byte_range applies to exactly one file")

    def _frame_to_batches(self, df) -> Iterator[Dict[str, np.ndarray]]:
        import pandas as pd  # noqa

        n = len(df)
        for start in range(0, n, self.B):
            chunk = df.iloc[start : start + self.B]
            if len(chunk) < self.B and self.drop_remainder:
                return
            out: Dict[str, np.ndarray] = {
                "label": chunk["label"].to_numpy(np.float32)
            }
            for i in range(1, self.num_dense + 1):
                # raw values here; sanitize_batch clamps non-finite to 0
                # AND counts them (np.nan_to_num hid inf as 3.4e38 — an
                # extreme-magnitude poison, exactly what the firewall
                # exists to stop)
                out[f"I{i}"] = (
                    chunk[f"I{i}"].to_numpy(np.float32).reshape(-1, 1)
                )
            for i in range(1, self.num_cat + 1):
                out[f"C{i}"] = _hash_strings(
                    chunk[f"C{i}"].to_numpy(object), salt=i * 0x9E3779B9 & 0x7FFFFFFF
                )
            yield out

    def _iter_native(self) -> Optional[Iterator[Dict[str, np.ndarray]]]:
        """Stream batches through the C++ parser (native/csv_parser.cpp) —
        one pass over raw bytes, no DataFrame. Falls back to pandas when the
        native library is unavailable. Id hashing is identical either way."""
        from deeprec_tpu.native import criteo_parse_native, load_library

        if load_library() is None:
            return None

        def gen():
            CHUNK = max(1 << 20, self.B * 512)
            for path in self.paths:
                with open(path, "rb") as f:
                    remaining = None
                    if self.byte_range is not None:
                        lo, hi = self.byte_range
                        f.seek(lo)
                        remaining = hi - lo
                    pending = b""
                    while True:
                        want = (
                            CHUNK if remaining is None
                            else min(CHUNK, remaining)
                        )
                        fresh = f.read(want)
                        if remaining is not None:
                            remaining -= len(fresh)
                        data = pending + fresh
                        if not data:
                            break
                        at_eof = len(fresh) < CHUNK
                        if at_eof and not data.endswith(b"\n"):
                            # Terminate the final line so the native parser
                            # consumes it, matching the pandas fallback.
                            data += b"\n"
                        out = criteo_parse_native(
                            data, self.B, self.num_dense, self.num_cat
                        )
                        if out is None:
                            return
                        rows, labels, dense, cats, consumed = out
                        if rows < self.B and not at_eof:
                            pending = data  # need more bytes for a full batch
                            continue
                        pending = data[consumed:]
                        if rows == 0:
                            if at_eof:
                                break
                            continue
                        if rows < self.B and self.drop_remainder:
                            break
                        batch: Dict[str, np.ndarray] = {
                            "label": labels[:rows]
                        }
                        for i in range(self.num_dense):
                            batch[f"I{i+1}"] = dense[:rows, i : i + 1]
                        for i in range(self.num_cat):
                            batch[f"C{i+1}"] = cats[:rows, i]
                        yield batch

        return gen()

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        native = self._iter_native()
        if native is not None:
            for batch in native:
                yield sanitize_batch(batch, self.errors)
            return
        import contextlib

        import pandas as pd

        for path in self.paths:
            with contextlib.ExitStack() as stack:
                if self.byte_range is not None:
                    lo, hi = self.byte_range
                    src = stack.enter_context(_RangeFile(path, lo, hi))
                else:
                    src = path
                for df in pd.read_csv(
                    src,
                    sep="\t",
                    names=CRITEO_COLUMNS[: 1 + self.num_dense + self.num_cat],
                    chunksize=self.B * 16,
                    header=None,
                ):
                    for batch in self._frame_to_batches(df):
                        yield sanitize_batch(batch, self.errors)


class ParquetReader:
    """Arrow-backed parquet batch reader (ParquetDataset parity). Columns map
    1:1 to batch keys; string/categorical columns are hashed to int32 ids."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int = 2048,
        columns: Optional[Sequence[str]] = None,
        hash_columns: Sequence[str] = (),
        drop_remainder: bool = True,
        hash_salts: Optional[Dict[str, int]] = None,
    ):
        """hash_salts: per-column salt override for the id hashing. The
        default (crc32 of the column NAME) is self-describing but does
        not match the positional salts of the CSV/stream readers — pass
        `criteo_hash_salts()` when the parquet files hold the same
        records as a TSV path and the id streams must be bit-identical
        (the pipeline's format parity gate)."""
        self.paths = list(paths)
        self.B = batch_size
        self.columns = list(columns) if columns else None
        self.hash_columns = set(hash_columns)
        self.drop_remainder = drop_remainder
        self.hash_salts = dict(hash_salts or {})

    def __iter__(self) -> Iterator[Dict[str, np.ndarray]]:
        import pyarrow.parquet as pq

        buf: Dict[str, List[np.ndarray]] = {}
        count = 0
        for path in self.paths:
            pf = pq.ParquetFile(path)
            for rb in pf.iter_batches(batch_size=self.B, columns=self.columns):
                cols = {}
                for name, col in zip(rb.schema.names, rb.columns):
                    arr = col.to_numpy(zero_copy_only=False)
                    if name in self.hash_columns or arr.dtype == object:
                        salt = self.hash_salts.get(
                            name, zlib.crc32(name.encode()))
                        arr = _hash_strings(arr, salt=salt)
                    cols[name] = arr
                for name, arr in cols.items():
                    buf.setdefault(name, []).append(arr)
                count += len(next(iter(cols.values())))
                while count >= self.B:
                    batch, buf, count = _take(buf, self.B)
                    yield batch
        if count and not self.drop_remainder:
            batch, buf, count = _take(buf, count)
            yield batch


def _take(buf, n):
    joined = {k: np.concatenate(v) for k, v in buf.items()}
    batch = {k: v[:n] for k, v in joined.items()}
    rest = {k: [v[n:]] for k, v in joined.items()}
    remaining = len(next(iter(rest.values()))[0])
    return batch, rest, remaining
