"""Staged input pipeline: host prefetch + async device transfer.

DeepRec overlaps input with compute through graph surgery — tf.staged buffers
+ a background PrefetchRunner (python/ops/prefetch.py, prefetch_runner.cc) and
the SmartStagePass that auto-carves the IO subgraph
(core/graph/smart_stage_pass.cc). On TPU none of that graph machinery is
needed: the same overlap is an async host thread that (a) pulls batches from
the reader, (b) starts the host→HBM transfer early (jax.device_put is async),
(c) keeps a small ring of in-flight batches while the train step consumes the
previous one. XLA's async dispatch does the rest.
"""
from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Iterator, Optional

import jax
import numpy as np


class Prefetcher:
    """Wrap a host batch iterator; keep `depth` batches in flight on device.

    The equivalent of tf.staged(..., num_threads=) + make_prefetch_hook —
    one object, no session hooks.
    """

    def __init__(
        self,
        source: Iterator[Dict[str, np.ndarray]],
        depth: int = 2,
        transform: Optional[Callable] = None,
        on_consume: Optional[Callable] = None,
        sharding=None,
        peek: Optional[Callable] = None,
    ):
        """on_consume: invoked (in the CONSUMER thread) each time a batch is
        delivered from __next__. The ring runs `depth` batches ahead of the
        train loop, so producer-side positions (a reader's internal index)
        overstate progress by the in-flight count; stream-position
        checkpoints must track deliveries, not productions — wire the
        reader's `mark_consumed` here (CriteoStats, Trainer.stage).

        sharding: placement for the DEFAULT transform (a jax.sharding
        .Sharding, e.g. NamedSharding(mesh, P("data"))). The bare
        `jax.device_put` default lands every batch on device 0 — feeding a
        sharded trainer that way transfers twice (host->dev0, then dev0->
        mesh inside the step). Pass the mesh sharding (or use
        Trainer.stage, whose transform already places mesh-wide) so the
        staged transfer lands split across devices. Ignored when an
        explicit `transform` is given.

        peek: invoked (in the PRODUCER thread) on each RAW host batch
        before `transform` runs — i.e. while the batch still sits in the
        host queue, before any `device_put`. This is the tier-paging tap
        (TierPrefetcher.observe probes upcoming ids against the host/disk
        key indexes while the batch waits). Must be cheap and must not
        raise: an exception here surfaces to the consumer as a reader
        error."""
        self.source = iter(source)
        self.depth = max(1, depth)
        if transform is None:
            transform = (
                (lambda b: jax.device_put(b, sharding))
                if sharding is not None
                else (lambda b: jax.device_put(b))
            )
        self.transform = transform
        self.on_consume = on_consume
        self.peek = peek
        self.q: "queue.Queue" = queue.Queue(maxsize=self.depth)
        self.stall_seconds = 0.0  # consumer wait on an empty ring (total)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _put(self, item) -> bool:
        """Enqueue with a timed put that re-checks the stop flag, so a full
        queue can never strand the worker after close() (a plain q.put
        blocks forever once the consumer is gone). True = delivered."""
        while not self._stop.is_set():
            try:
                self.q.put(item, timeout=0.1)
                return True
            except queue.Full:
                continue
        return False

    def _run(self):
        try:
            for batch in self.source:
                if self._stop.is_set():
                    return
                if self.peek is not None:
                    self.peek(batch)
                # device_put returns immediately; the transfer overlaps the
                # consumer's compute.
                if not self._put(self.transform(batch)):
                    return
            self._put(None)
        except Exception as e:  # surface reader errors to the consumer
            # Only chase the exception with the end-of-stream marker if the
            # exception itself was delivered — unconditionally enqueueing
            # both could block on a full queue (and double-signal a
            # consumer that already stopped reading).
            if self._put(e):
                self._put(None)

    def __iter__(self):
        return self

    def __next__(self):
        try:
            item = self.q.get_nowait()
        except queue.Empty:
            # Empty ring = the producer (reader/parse/device_put) is the
            # bottleneck right now; the wait is the input stall the obs
            # plane reports per dispatch (docs/data.md).
            t0 = time.perf_counter()
            item = self.q.get()
            wait = time.perf_counter() - t0
            self.stall_seconds += wait
            from deeprec_tpu.data.pipeline import record_stall

            record_stall("staged", wait)
        if item is None:
            raise StopIteration
        if isinstance(item, Exception):
            raise item
        if self.on_consume is not None:
            self.on_consume()
        return item

    def close(self):
        """Stop the worker and release anything blocked: sets the stop flag
        (the worker's timed put observes it within its timeout), drains the
        queue so an in-flight put can land, and joins the thread."""
        self._stop.set()
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=2.0)
        # A put that raced the drain may have landed afterwards; clear it
        # so close() leaves nothing referencing device buffers.
        try:
            while True:
                self.q.get_nowait()
        except queue.Empty:
            pass


def staged(source, depth: int = 2, transform=None,
           on_consume=None, sharding=None, peek=None) -> Prefetcher:
    """tf.staged analog: `for batch in staged(reader): ...`. Pass
    `sharding` when feeding a sharded trainer without a custom transform
    so batches land mesh-split instead of on device 0."""
    return Prefetcher(source, depth=depth, transform=transform,
                      on_consume=on_consume, sharding=sharding, peek=peek)
