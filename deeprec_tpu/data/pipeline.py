"""Parallel host input pipeline: sharded readers + vectorized block parse
+ worker-side pack, feeding the staged prefetcher.

The device side of the step-time budget is pipelined end to end (dedup,
traffic diet, in-step overlap, fused Pallas step, tier paging); this
module does the same for the HOST side, which was still the seed's
single-threaded Python — one thread parsing Criteo text line by line
(`criteo_line_parser`) can't feed a fused step. The DeepRec analog is the
fused reader + Stage/SmartStage op stack; here it is N worker threads and
three contracts:

  * **Record-aligned shards.** A newline-counting plan pass
    (`plan_shards`) snaps every shard boundary to a multiple of
    `batch_size * k_stack` records, and shards never span files — so any
    batch (and any K-group fed to `Trainer.train_steps`) lives entirely
    inside one shard, and the N-worker stream can be reassembled
    bit-identically to the serial reader's, for ANY worker count.
  * **Deterministic reorder.** Workers claim shards in plan order, parse
    each with the vectorized `criteo_block_parse` (readers.py), sanitize
    + pack final fixed-shape arrays (the `stack_batches` K-stack happens
    HERE, on the worker), and push into a bounded reorder buffer keyed by
    global sequence number. The consumer pops strictly in order; a slow
    worker delays but never reorders. The producer of the
    next-to-emit sequence always passes the bound, so the window can
    never deadlock.
  * **Exactly-once resume.** `mark_consumed()` / `attach_consumer()`
    extend the CriteoStats contract (data/synthetic.py): under a staging
    ring, `save()` reports the CONSUMED position — as a unit count plus
    per-shard consumed byte offsets — and `restore()` seeks workers
    straight to those offsets, so a SIGKILL + restart replays each
    record exactly once across any number of workers.

Observability: `deeprec_input_batches` / `_records` / `_bytes` counters,
and the pipeline-stall gauge `deeprec_input_stall_seconds{site=}` (the
training-thread wait per dispatch; sites are the bounded set
pipeline|staged|train_loop). `stats()` feeds tools/bench_input.py, whose
JSON is gated by `roofline.py --assert-input` (≥2x block-parse win, bit
parity, no training-thread regression). See docs/data.md.
"""
from __future__ import annotations

import collections
import threading
import time
from typing import Dict, Iterator, List, NamedTuple, Optional, Sequence

import numpy as np

from deeprec_tpu.data.readers import (
    RecordErrors,
    criteo_block_parse,
    sanitize_batch,
)

_STALL_SITES = ("pipeline", "staged", "train_loop")  # bounded (DRT007)


def record_stall(site: str, seconds: float) -> None:
    """One consumer-side wait-for-input, `seconds` long, at `site` (one of
    pipeline|staged|train_loop). Gauge = the last per-dispatch wait (what
    a scrape sees as 'how input-bound is the training thread right now'),
    counter = cumulative stall. No-ops when the metrics plane is off."""
    from deeprec_tpu.obs import metrics as obs_metrics

    if not obs_metrics.metrics_enabled():
        return
    reg = obs_metrics.default_registry()
    reg.gauge(
        "deeprec_input_stall_seconds",
        "training-thread wait for input on the last dispatch",
        {"site": site},
    ).set(seconds)
    reg.counter(
        "deeprec_input_stall_seconds_total",
        "cumulative consumer wait for input",
        {"site": site},
    ).inc(seconds)


class Shard(NamedTuple):
    """One record-aligned unit of work: bytes [lo, hi) of `path`, holding
    `units` emission units (1 unit = k_stack batches) starting at global
    unit sequence `first_unit`. `records` counts parseable records in the
    span (the tail remainder past the last full unit is dropped by the
    drop_remainder contract, same as the serial reader's per-file drop)."""

    sid: int
    path: str
    lo: int
    hi: int
    records: int
    units: int
    first_unit: int


def _scan_file(path: str, stride: int):
    """One pass over `path`: total record count, byte offsets of the
    record starts at multiples of `stride` records, and the file size.
    An unterminated final line counts as a record (the serial readers
    terminate it on read)."""
    offs: List[int] = []
    rc = 0
    pos = 0
    target = stride
    last_byte = 10
    with open(path, "rb") as f:
        while True:
            chunk = f.read(4 << 20)
            if not chunk:
                break
            a = np.frombuffer(chunk, np.uint8)
            nl = np.flatnonzero(a == 10)
            cnt = len(nl)
            while target <= rc + cnt:
                offs.append(pos + int(nl[target - rc - 1]) + 1)  # noqa: DRT002 — host newline scan (numpy on file bytes), never a device value
                target += stride
            rc += cnt
            pos += len(chunk)
            last_byte = chunk[-1]
    if pos and last_byte != 10:
        rc += 1
    return rc, offs, pos


def plan_shards(paths: Sequence[str], batch_size: int, k_stack: int = 1,
                shard_batches: int = 16, drop_remainder: bool = True
                ) -> List[Shard]:
    """Record-aligned shard plan. Deterministic in (paths, batch_size,
    k_stack, shard_batches) — restore() replans and the unit sequence
    numbers line up exactly with the interrupted run's."""
    k = max(1, k_stack)
    per_unit = batch_size * k
    shard_batches = max(k, (shard_batches + k - 1) // k * k)
    stride = batch_size * shard_batches
    shards: List[Shard] = []
    unit = 0
    for path in paths:
        rc, offs, size = _scan_file(path, stride)
        bounds = [0] + offs + ([size] if (not offs or offs[-1] < size) else [])
        counts = [stride] * (len(bounds) - 2) + [rc - stride * (len(bounds) - 2)]
        for lo, hi, records in zip(bounds[:-1], bounds[1:], counts):
            if drop_remainder:
                units = records // per_unit
                records = units * per_unit
            else:
                units = -(-records // per_unit)
            if units <= 0:
                continue
            shards.append(Shard(len(shards), path, lo, hi, records, units,
                                unit))
            unit += units
    return shards


class ParallelInputPipeline:
    """Multi-worker Criteo input pipeline — iterate it like any reader
    (`for batch in pipeline`), or hand it to `Trainer.stage` /
    `staged()`, whose ring, `sharding=` transform, and `peek=` tier tap
    it feeds unchanged. Emits one item per unit: a batch dict when
    `k_stack` is None/1, else a [K, ...]-stacked pytree ready for
    `Trainer.train_steps` — the training thread's only host work is the
    queue pop.

    fmt="csv" (Criteo TSV, the `criteo_line_parser` semantics) or
    "parquet" (ParquetReader routed through the same shard/reorder/resume
    machinery — one shard per file; pass the TSV `criteo_hash_salts()`
    via hash_salts for bit-exact format parity)."""

    def __init__(
        self,
        paths: Sequence[str],
        batch_size: int = 2048,
        num_workers: int = 4,
        num_dense: int = 13,
        num_cat: int = 26,
        k_stack: Optional[int] = None,
        shard_batches: int = 16,
        drop_remainder: bool = True,
        reorder_window: Optional[int] = None,
        fmt: str = "csv",
        hash_columns: Sequence[str] = (),
        hash_salts: Optional[Dict[str, int]] = None,
        criteo_layout: bool = True,
        metrics: bool = True,
    ):
        if fmt not in ("csv", "parquet"):
            raise ValueError(f"unknown format {fmt!r}")
        self.paths = list(paths)
        self.B = batch_size
        self.num_workers = max(1, num_workers)
        self.num_dense = num_dense
        self.num_cat = num_cat
        self.k = max(1, k_stack or 1)
        self.stacked = k_stack is not None and k_stack > 1
        if self.stacked and not drop_remainder:
            raise ValueError("k_stack > 1 requires drop_remainder")
        self.drop_remainder = drop_remainder
        self.format = fmt
        self.hash_columns = tuple(hash_columns)
        self.hash_salts = dict(hash_salts or {})
        self.criteo_layout = criteo_layout
        self.errors = RecordErrors(metrics=metrics)
        self._metrics = metrics
        if fmt == "csv":
            self._shards = plan_shards(self.paths, batch_size, self.k,
                                       shard_batches, drop_remainder)
        else:
            self._shards = self._plan_parquet()
        self._total = sum(s.units for s in self._shards)
        self.window = max(4, reorder_window or 2 * self.num_workers)
        # reorder buffer state (one condition variable for producers and
        # the consumer; the bound counts buffered units, not batches)
        self._cond = threading.Condition()
        self._buf: Dict[int, tuple] = {}
        self._next_claim = 0
        self._next_emit = 0
        self._error: Optional[BaseException] = None
        self._stopped = False
        self._threads: List[threading.Thread] = []
        # consumed-position bookkeeping (CriteoStats contract + offsets)
        self._consume_lock = threading.Lock()
        self._pending = collections.deque()  # (unit, sid, end_offset)
        self._consumed = 0
        self._consumer_attached = False
        self._shard_consumed: Dict[int, int] = {}
        self._resume: Dict[int, tuple] = {}  # sid -> (offset, first_unit)
        # per-stage accounting for tools/bench_input.py
        self._stats_lock = threading.Lock()
        self._stage = {"read_s": 0.0, "parse_s": 0.0, "pack_s": 0.0,
                       "stall_s": 0.0, "bytes": 0, "records": 0,
                       "units": 0}

    # ---------------------------------------------------------------- plan

    def _plan_parquet(self) -> List[Shard]:
        import pyarrow.parquet as pq

        per_unit = self.B * self.k
        shards: List[Shard] = []
        unit = 0
        for path in self.paths:
            rows = pq.ParquetFile(path).metadata.num_rows
            if self.drop_remainder:
                units = rows // per_unit
                records = units * per_unit
            else:
                units = -(-rows // per_unit)
                records = rows
            if units <= 0:
                continue
            shards.append(Shard(len(shards), path, 0, rows, records, units,
                                unit))
            unit += units
        return shards

    @property
    def total_units(self) -> int:
        return self._total

    # ------------------------------------------------------------- workers

    def _start(self) -> None:
        if self._threads or self._stopped:
            return
        for w in range(self.num_workers):
            t = threading.Thread(target=self._worker, name=f"input-{w}",
                                 daemon=True)
            t.start()
            self._threads.append(t)

    def _worker(self) -> None:  # noqa: DRT004 — shard claim + reorder insert are lock-protected; parse state is worker-local
        try:
            while True:
                with self._cond:
                    if self._stopped or self._next_claim >= len(self._shards):
                        return
                    shard = self._shards[self._next_claim]
                    self._next_claim += 1
                if shard.units == 0:
                    continue
                if self.format == "csv":
                    self._run_csv_shard(shard)
                else:
                    self._run_parquet_shard(shard)
        except BaseException as e:  # surface to the consumer
            with self._cond:
                if self._error is None:
                    self._error = e
                self._cond.notify_all()

    def _acct(self, **kv) -> None:
        with self._stats_lock:
            for k, v in kv.items():
                self._stage[k] += v

    def _run_csv_shard(self, shard: Shard) -> None:
        lo, first_unit = shard.lo, shard.first_unit
        off, resumed_first = self._resume.get(shard.sid, (None, None))
        if off is not None:
            lo, first_unit = off, resumed_first
        t0 = time.perf_counter()
        with open(shard.path, "rb") as f:
            f.seek(lo)
            data = f.read(shard.hi - lo)
        t1 = time.perf_counter()
        cols = criteo_block_parse(data, self.num_dense, self.num_cat,
                                  self.errors)
        cols = sanitize_batch(cols, self.errors)
        t2 = time.perf_counter()
        # byte offset (absolute) after each record — the per-shard
        # consumed offsets of the save()/restore() contract
        ends = lo + np.flatnonzero(np.frombuffer(data, np.uint8) == 10) + 1
        if len(ends) < cols["label"].shape[0]:  # unterminated final line
            ends = np.append(ends, shard.hi)
        self._acct(read_s=t1 - t0, parse_s=t2 - t1, bytes=len(data))
        if self._metrics:
            from deeprec_tpu.obs import metrics as obs_metrics

            if obs_metrics.metrics_enabled():
                obs_metrics.default_registry().counter(
                    "deeprec_input_bytes",
                    "raw bytes read by the parallel input pipeline",
                ).inc(len(data))
        units = shard.units - (first_unit - shard.first_unit)
        per_unit = self.B * self.k
        for u in range(units):
            seq = first_unit + u
            t3 = time.perf_counter()
            r0 = u * per_unit
            r1 = min(r0 + per_unit, cols["label"].shape[0])
            item = self._pack(cols, r0, r1)
            end_off = int(ends[r1 - 1])  # noqa: DRT002 — host byte offset from the newline index, never a device value
            self._acct(pack_s=time.perf_counter() - t3, records=r1 - r0,
                       units=1)
            if not self._emit(seq, (item, shard.sid, end_off)):
                return

    def _run_parquet_shard(self, shard: Shard) -> None:
        from deeprec_tpu.data.readers import ParquetReader

        off, resumed_first = self._resume.get(shard.sid, (None, None))
        skip_units = 0 if off is None else int(off)  # noqa: DRT002 — resume bookkeeping (host int), never a device value
        first_unit = shard.first_unit if resumed_first is None \
            else resumed_first
        reader = ParquetReader(
            [shard.path], batch_size=self.B,
            hash_columns=self.hash_columns, hash_salts=self.hash_salts,
            drop_remainder=self.drop_remainder)
        group: List[Dict[str, np.ndarray]] = []
        unit = 0  # 0-based unit index within the file, skipped included
        t0 = time.perf_counter()
        for batch in reader:
            if self.criteo_layout:
                batch = self._criteo_shape(batch)
            batch = sanitize_batch(batch, self.errors)
            group.append(batch)
            if len(group) < self.k and batch["label"].shape[0] == self.B:
                continue
            t1 = time.perf_counter()
            if unit >= skip_units:
                seq = first_unit + (unit - skip_units)
                item = group[0] if not self.stacked else {
                    k: np.stack([b[k] for b in group])
                    for k in group[0]
                }
                n = sum(b["label"].shape[0] for b in group)
                self._acct(read_s=t1 - t0, records=n, units=1,
                           pack_s=time.perf_counter() - t1)
                # parquet "offsets" count consumed UNITS within the file
                # (a columnar file has no record byte offsets; resume
                # re-reads and skips, it never re-emits)
                if not self._emit(seq, (item, shard.sid, unit + 1)):
                    return
            group = []
            unit += 1
            if unit >= shard.units:
                return
            t0 = time.perf_counter()

    def _criteo_shape(self, batch: Dict[str, np.ndarray]
                      ) -> Dict[str, np.ndarray]:
        """Coerce parquet-stored columns to the exact CSV batch layout:
        label [n] f32, I* [n, 1] f32, C*/ids [n] i32 — so the two formats
        emit bit-identical streams for the same records."""
        out = {}
        for k, v in batch.items():
            if k.startswith("label"):
                out[k] = np.asarray(v, np.float32)  # noqa: DRT002 — worker-thread host pack of a parquet batch, never a device array
            elif k.startswith("I") and v.ndim == 1 and \
                    np.issubdtype(np.asarray(v).dtype, np.number):  # noqa: DRT002 — worker-thread host pack, never a device array
                out[k] = np.asarray(v, np.float32).reshape(-1, 1)  # noqa: DRT002 — worker-thread host pack, never a device array
            else:
                out[k] = v
        return out

    def _pack(self, cols: Dict[str, np.ndarray], r0: int, r1: int):
        """Final fixed-shape arrays for one unit. Copies the slice (the
        shard's parse buffer must not be pinned by emitted batches) and
        does the K-stack reshape worker-side."""
        if not self.stacked:
            return {k: np.ascontiguousarray(v[r0:r1]) for k, v in
                    cols.items()}
        # [K*B, ...] -> [K, B, ...] — equivalent to stack_batches over the
        # K consecutive B-slices, done with one reshape per column.
        return {
            k: np.ascontiguousarray(v[r0:r1]).reshape(
                (self.k, self.B) + v.shape[1:])
            for k, v in cols.items()
        }

    def _emit(self, seq: int, item) -> bool:
        with self._cond:
            while not self._stopped and seq >= self._next_emit + self.window:
                self._cond.wait(0.1)
            if self._stopped:
                return False
            self._buf[seq] = item
            self._cond.notify_all()
            return True

    # ------------------------------------------------------------ consumer

    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        self._start()
        waited = 0.0
        with self._cond:
            while True:
                if self._error is not None:
                    raise self._error
                if self._stopped or self._next_emit >= self._total:
                    raise StopIteration
                got = self._buf.pop(self._next_emit, None)
                if got is not None:
                    break
                t0 = time.perf_counter()
                self._cond.wait(0.1)
                waited += time.perf_counter() - t0
            unit = self._next_emit
            self._next_emit += 1
            self._cond.notify_all()
        item, sid, end_off = got
        if waited:
            self._acct(stall_s=waited)
            record_stall("pipeline", waited)
        with self._consume_lock:
            self._pending.append((unit, sid, end_off))
            if not self._consumer_attached:
                self._apply_pending_locked()
        if self._metrics:
            self._count_emit(item)
        return item

    def _count_emit(self, item) -> None:
        from deeprec_tpu.obs import metrics as obs_metrics

        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.default_registry()
        n = int(np.prod(item["label"].shape))
        reg.counter("deeprec_input_batches",
                    "batches emitted by the parallel input pipeline"
                    ).inc(self.k)
        reg.counter("deeprec_input_records",
                    "records emitted by the parallel input pipeline").inc(n)

    # ----------------------------------------------- exactly-once contract

    def attach_consumer(self) -> None:
        """Declare that a staging ring decouples production from
        consumption (CriteoStats contract): from here on save() reports
        the consumed position, advanced only by mark_consumed()."""
        with self._consume_lock:
            self._consumer_attached = True

    def mark_consumed(self) -> None:
        """One unit DELIVERED to the train loop (wire to
        Prefetcher(on_consume=...); Trainer.stage does this
        automatically)."""
        with self._consume_lock:
            self._consumer_attached = True
            if self._pending:
                unit, sid, end_off = self._pending.popleft()
                self._consumed = unit + 1
                self._shard_consumed[sid] = end_off

    def _apply_pending_locked(self) -> None:
        while self._pending:
            unit, sid, end_off = self._pending.popleft()
            self._consumed = unit + 1
            self._shard_consumed[sid] = end_off

    def save(self) -> Dict:
        """Resumable position: consumed unit count + per-shard consumed
        offsets (byte offsets for csv shards; consumed in-file units for
        parquet). Under a staging ring (attach_consumer/mark_consumed)
        this is the DELIVERED position, so in-flight ring batches replay
        after a crash — exactly once, never skipped."""
        with self._consume_lock:
            if not self._consumer_attached:
                self._apply_pending_locked()
            return {
                "consumed": self._consumed,
                "offsets": {str(sid): off for sid, off in
                            sorted(self._shard_consumed.items())},
            }

    def restore(self, state: Dict) -> None:
        """Seek the (not yet started) pipeline to a save() position: fully
        consumed shards are skipped, the partial shard's worker resumes at
        its consumed offset, and unit sequence numbers continue from the
        saved count — the emitted stream is the exact suffix of the
        uninterrupted run's."""
        if self._threads:
            raise RuntimeError("restore() must precede iteration")
        consumed = int(state.get("consumed", 0))  # noqa: DRT002 — checkpoint JSON field, never a device value
        offsets = {int(k): v for k, v in state.get("offsets", {}).items()}  # noqa: DRT002 — checkpoint JSON keys, never a device value
        self._next_emit = consumed
        self._consumed = consumed
        self._shard_consumed = dict(offsets)
        keep: List[Shard] = []
        for s in self._shards:
            if s.first_unit + s.units <= consumed:
                continue  # fully consumed
            if s.first_unit < consumed:
                done_units = consumed - s.first_unit
                if s.sid in offsets:
                    off = offsets[s.sid]
                else:  # no saved offset: re-derive by scanning records
                    off = self._skip_offset(s, done_units * self.B * self.k)
                if self.format == "parquet":
                    self._resume[s.sid] = (done_units, consumed)
                else:
                    self._resume[s.sid] = (int(off), consumed)  # noqa: DRT002 — saved byte offset (host int), never a device value
            keep.append(s)
        self._shards = keep

    def _skip_offset(self, s: Shard, records: int) -> int:
        with open(s.path, "rb") as f:
            f.seek(s.lo)
            data = f.read(s.hi - s.lo)
        ends = np.flatnonzero(np.frombuffer(data, np.uint8) == 10) + 1
        return s.lo + int(ends[records - 1])  # noqa: DRT002 — host newline scan for the resume offset, never a device value

    # ------------------------------------------------------------- plumbing

    def stats(self) -> Dict[str, float]:
        """Per-stage accounting snapshot (worker-seconds, not wall time):
        read_s/parse_s/pack_s, consumer stall_s, bytes/records/units."""
        with self._stats_lock:
            return dict(self._stage)

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        for t in self._threads:
            t.join(timeout=2.0)
        self._threads = []
