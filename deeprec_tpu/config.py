"""Unified typed configuration tree.

DeepRec spreads configuration over three mechanisms — ConfigProto extensions
(/root/reference/tensorflow/core/protobuf/config.proto), dozens of env vars,
and per-EV option objects (tensorflow/python/ops/variables.py:180-300:
EmbeddingVariableOption / InitializerOption / GlobalStepEvict / L2WeightEvict /
StorageOption / CounterFilter / CBFFilter / CheckpointOption). Here everything
is one tree of frozen dataclasses, hashable so they can be passed as jit
static arguments.
"""
from __future__ import annotations

import dataclasses
import enum
import math
from typing import Optional


class StorageType(enum.Enum):
    """Where a table's payload lives.

    Parity with the storage enum in
    /root/reference/tensorflow/core/framework/embedding/config.proto:10-25.
    On TPU the tiers collapse to: HBM (device arrays), DRAM (host store via
    the native KV lib), and HBM_DRAM (HBM working set + host overflow, the
    analog of DeepRec's HbmDramStorage). PMEM/SSD/LevelDB map onto the host
    tier's file-backed mode.
    """

    HBM = "hbm"
    DRAM = "dram"
    HBM_DRAM = "hbm_dram"
    # three-tier combo (hbm_dram_ssd_storage.h analog): device working set,
    # bounded host DRAM tier, log-structured disk tier below it
    HBM_DRAM_SSD = "hbm_dram_ssd"

    @classmethod
    def from_reference(cls, name) -> "StorageType":
        """Map any of the reference's 13 StorageType values — proto
        names OR field numbers (embedding/config.proto:5-27) — onto the
        TPU tiers, so configs
        written against DeepRec resolve without edits. The physical
        reality on a TPU-VM: compute reads come from HBM, the host has
        DRAM, and below that there is a filesystem — PMEM does not exist
        and LevelDB/SSDHASH are both \"a disk-backed log\", so
          * PMEM_* tiers map to the host DRAM tier,
          * SSDHASH / LEVELDB tiers map to the log-structured disk tier,
          * every multi-level combo keeps its LEVEL STRUCTURE with each
            level mapped as above (e.g. DRAM_PMEM -> HBM_DRAM: a fast
            working set over a larger colder store).
        """
        if isinstance(name, cls):
            return name
        # DeepRec's canonical config form is the proto ENUM VALUE (an int
        # in Python: config_pb2.StorageType.DRAM_SSDHASH == 12) — accept
        # the field numbers as well as the names.
        by_number = {
            0: "DEFAULT", 1: "DRAM", 2: "PMEM_MEMKIND", 3: "PMEM_LIBPMEM",
            4: "SSDHASH", 5: "LEVELDB", 6: "HBM", 11: "DRAM_PMEM",
            12: "DRAM_SSDHASH", 13: "HBM_DRAM", 14: "DRAM_LEVELDB",
            101: "DRAM_PMEM_SSDHASH", 102: "HBM_DRAM_SSDHASH",
        }
        if isinstance(name, int) and not isinstance(name, bool):
            if name not in by_number:
                raise ValueError(
                    f"unknown reference StorageType number {name}; known "
                    f"field numbers: {sorted(by_number)}"
                )
            name = by_number[name]
        key = str(name).strip().upper()
        table = {
            "DEFAULT": cls.HBM,
            "HBM": cls.HBM,
            "DRAM": cls.DRAM,
            "PMEM_MEMKIND": cls.DRAM,
            "PMEM_LIBPMEM": cls.DRAM,
            "SSDHASH": cls.HBM_DRAM_SSD,
            "LEVELDB": cls.HBM_DRAM_SSD,
            "DRAM_PMEM": cls.HBM_DRAM,
            "DRAM_SSDHASH": cls.HBM_DRAM_SSD,
            "HBM_DRAM": cls.HBM_DRAM,
            "DRAM_LEVELDB": cls.HBM_DRAM_SSD,
            "DRAM_PMEM_SSDHASH": cls.HBM_DRAM_SSD,
            "HBM_DRAM_SSDHASH": cls.HBM_DRAM_SSD,
        }
        if key in table:
            return table[key]
        try:  # our own value strings ("hbm_dram", ...)
            return cls(str(name).lower())
        except ValueError:
            raise ValueError(
                f"unknown storage type {name!r}; reference names "
                f"{sorted(table)} and native values "
                f"{[m.value for m in cls]} are accepted"
            ) from None


@dataclasses.dataclass(frozen=True)
class InitializerOption:
    """EV initializer semantics.

    DeepRec (docs/docs_en/Embedding-Variable.md "EV Initializer"): an
    initializer generates a [default_value_dim, dim] matrix; a new key k is
    assigned row (k % default_value_dim). `kind="stateless_normal"` is the
    TPU-native improvement: a per-key deterministic normal computed from the
    key hash — same statistical effect with no stored matrix and bitwise
    reproducibility across shards/restarts/growth.
    """

    kind: str = "stateless_normal"  # stateless_normal | matrix_normal | constant
    stddev: float = 0.05
    mean: float = 0.0
    constant: float = 0.0
    default_value_dim: int = 4096
    # Value served for keys blocked by an admission filter
    # (EmbeddingVariableOption.init.default_value_no_permission).
    default_value_no_permission: float = 0.0


@dataclasses.dataclass(frozen=True)
class CounterFilter:
    """Admit a feature only after it has been seen `filter_freq` times.

    Parity: tf.CounterFilter (variables.py:279) /
    counter_filter_policy.h. Until admission a key is tracked (frequency
    counter) but serves `default_value_no_permission` and receives no
    gradient updates.
    """

    filter_freq: int = 0


@dataclasses.dataclass(frozen=True)
class CBFFilter:
    """Counting-Bloom-filter admission: like CounterFilter but the counter
    lives in a compact sketch, and keys below threshold never occupy a table
    slot at all.

    Parity: tf.CBFFilter (variables.py:284) / bloom_filter_policy.h.
    """

    filter_freq: int = 0
    max_element_size: int = 1 << 20
    false_positive_probability: float = 0.01
    counter_bits: int = 16  # sketch counters saturate at 2^bits - 1

    def num_cells(self) -> int:
        # Standard Bloom sizing: m = -n ln p / (ln 2)^2, rounded up to pow2.
        m = -self.max_element_size * math.log(self.false_positive_probability) / (
            math.log(2.0) ** 2
        )
        return max(1024, 1 << int(math.ceil(math.log2(max(m, 1.0)))))

    def num_hashes(self) -> int:
        k = (self.num_cells() / max(self.max_element_size, 1)) * math.log(2.0)
        return max(1, min(8, int(round(k))))


@dataclasses.dataclass(frozen=True)
class GlobalStepEvict:
    """TTL eviction: drop keys not updated in the last `steps_to_live` steps.

    Parity: tf.GlobalStepEvict (variables.py:204) /
    globalstep_shrink_policy.h; spec docs/docs_en/Feature-Eviction.md.
    Runs at checkpoint/eviction time, not on the lookup hot path.
    """

    steps_to_live: int = 0


@dataclasses.dataclass(frozen=True)
class L2WeightEvict:
    """Drop keys whose embedding L2 norm is below threshold.

    Parity: tf.L2WeightEvict (variables.py:210) / l2weight_shrink_policy.h.
    """

    l2_weight_threshold: float = -1.0


@dataclasses.dataclass(frozen=True)
class StorageOption:
    """Multi-tier storage placement for one table.

    Parity: tf.StorageOption (variables.py:230). `capacity` bounds the HBM
    tier (slots); overflow keys spill to the host store when
    storage_type=HBM_DRAM (eviction by LFU/LRU on (freq, version)).
    """

    storage_type: StorageType = StorageType.HBM
    storage_path: Optional[str] = None
    cache_strategy: str = "lfu"  # lfu | lru
    # HBM_DRAM_SSD: max rows held in the host DRAM tier before the coldest
    # spill to the disk tier (0 = unbounded, disk tier unused)
    host_capacity: int = 0

    def __post_init__(self):
        # Accept reference StorageType names and plain strings (configs
        # written against DeepRec's enum resolve without edits).
        if not isinstance(self.storage_type, StorageType):
            object.__setattr__(
                self, "storage_type",
                StorageType.from_reference(self.storage_type),
            )


@dataclasses.dataclass(frozen=True)
class CheckpointOption:
    """Per-table checkpoint behavior — parity with tf.CheckpointOption
    (variables.py:217) / TF_EV_SAVE_FILTERED_FEATURES: full checkpoints
    normally keep sub-threshold (filter-blocked) keys so admission
    counters survive restarts; save_filtered_features=False drops them at
    save time (smaller serving-bound checkpoints, same effect as the
    shrink tool but at the source)."""

    save_filtered_features: bool = True


@dataclasses.dataclass(frozen=True)
class EmbeddingVariableOption:
    """Per-table feature bundle — parity with tf.EmbeddingVariableOption
    (variables.py:261)."""

    init: InitializerOption = InitializerOption()
    counter_filter: Optional[CounterFilter] = None
    cbf_filter: Optional[CBFFilter] = None
    global_step_evict: Optional[GlobalStepEvict] = None
    l2_weight_evict: Optional[L2WeightEvict] = None
    storage: StorageOption = StorageOption()
    ckpt: CheckpointOption = CheckpointOption()

    def __post_init__(self):
        if self.counter_filter is not None and self.cbf_filter is not None:
            raise ValueError("at most one admission filter per table")


def validate_unique_budget(ub, where: str) -> None:
    """Shared grammar check for the unique-budget knob — one definition
    for TableConfig and SparseFeature so the accepted forms can never
    diverge: None | "auto" | "off" | positive int."""
    if not (
        ub is None
        or ub in ("auto", "off")
        or (isinstance(ub, int) and not isinstance(ub, bool) and ub > 0)
    ):
        raise ValueError(
            f"{where}: unique_budget must be None, 'auto', 'off' or a "
            f"positive int, got {ub!r}"
        )


@dataclasses.dataclass(frozen=True)
class TableConfig:
    """Static configuration of one hash-embedding table.

    The analog of creating an EmbeddingVariable via tf.get_embedding_variable
    (variable_scope.py:2146): `dim` is the embedding width, `capacity` the
    fixed HBM slot count (power of two; DeepRec's tables grow dynamically —
    here growth is host-orchestrated rehash to a larger capacity, see
    table.grow()).
    """

    name: str
    dim: int
    capacity: int = 1 << 16
    key_dtype: str = "int32"  # int32 | int64 (int64 requires jax x64)
    # Residency dtype of the value rows. float32/bfloat16 are full
    # train+serve dtypes (bf16 writes stochastic-round). "int8" is a
    # SERVING-ONLY residency (train fp32, serve quantized): rows store as
    # int8 with a per-row fp32 scale (TableState.qscale), dequantized in
    # the lookup gather; checkpoint restore quantizes on import
    # (import_rows). Train-mode lookups on an int8 table raise — the
    # Predictor(quantize="int8") path is how this gets engaged.
    value_dtype: str = "float32"  # float32 | bfloat16 | int8 (serve-only)
    combiner: str = "mean"  # mean | sum | sqrtn
    max_probes: int = 64
    # Hot-path kernel choice: "xla" = plain gather/scatter ops, "pallas" =
    # the fused DMA kernels in ops/fused_lookup.py (row gather + stochastic-
    # rounded scatter), "auto" = whichever tools/bench_lookup.py crowned on
    # this hardware: pallas, measured faster on v5e wherever the kernels are
    # eligible (f32 tables, dim%128==0 — Mosaic HBM-tiling constraint); the
    # ops self-gate ineligible shapes back to XLA. Off-TPU every choice
    # falls back to identical-semantics XLA.
    kernel: str = "auto"  # auto | xla | pallas
    # Packed small-dim storage layout (ops/packed.py): "auto" packs only on
    # TPU, where the layout's rationale holds — XLA pads a [C, dim<128] f32
    # array's minor dim to 128 lanes, so packing saves 128/dim x HBM and
    # gather bandwidth. On CPU there is no lane padding and the pack/unpack
    # shuffle is pure overhead (measured: -36% DLRM train throughput, BENCH_r04
    # vs r03), so "auto" resolves to unpacked there. "on"/"off" force it
    # either way (tests exercise the packed path on CPU via "on").
    packed: str = "auto"  # auto | on | off
    # Unique-budget for the hash dedup engine (ops/dedup.py): per lookup,
    # ids dedup to at most `unique_budget` uniques and EVERY downstream op
    # (probe, gather, freq/version scatters, init, backward segment-sum,
    # the sharded a2a/allgather payload) is sized at the budget instead of
    # the full flattened batch. Ids past the budget serve the
    # admission-blocked default for that step and count in the table's
    # `dedup_overflow` (the a2a_overflow contract).
    #   int    — fixed budget (real unique ids per lookup)
    #   "auto" — trainer-derived: capacity-clamped slack over an EMA of
    #            measured unique fractions (Trainer.update_budgets /
    #            maintain()); until the first measurement the lookup runs
    #            at U = N and seeds the EMA counters
    #   None   — legacy U = N sort-unique (logged once per table so the
    #            waste is visible); "off" the same, silently.
    unique_budget: Optional[object] = None  # None | "off" | "auto" | int
    # Wire format of the sharded TRAIN exchanges (ShardedTable): the value
    # payload of the allgather/psum_scatter and a2a embedding returns, and
    # the gradient payload of the backward exchange, are cast to this dtype
    # on the wire. "bfloat16" (default) halves ICI/collective bytes; the
    # owner side always accumulates segment-sums in fp32, and EVAL/serving
    # exchanges always ride exact fp32 regardless of this knob (a read-only
    # pass must reproduce resident rows exactly). Id payloads are ints and
    # unaffected.
    exchange_dtype: str = "bfloat16"  # bfloat16 | float32
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        if self.capacity & (self.capacity - 1):
            raise ValueError(f"capacity must be a power of two, got {self.capacity}")
        if self.dim <= 0:
            raise ValueError("dim must be positive")
        if self.kernel not in ("auto", "xla", "pallas"):
            raise ValueError(f"unknown kernel {self.kernel!r}")
        if self.packed not in ("auto", "on", "off"):
            raise ValueError(f"unknown packed mode {self.packed!r}")
        if self.value_dtype not in ("float32", "bfloat16", "int8"):
            raise ValueError(
                f"table {self.name}: value_dtype must be 'float32', "
                f"'bfloat16' or 'int8', got {self.value_dtype!r}"
            )
        if self.exchange_dtype not in ("bfloat16", "float32"):
            raise ValueError(
                f"table {self.name}: exchange_dtype must be 'bfloat16' or "
                f"'float32', got {self.exchange_dtype!r}"
            )
        validate_unique_budget(self.unique_budget, f"table {self.name}")


@dataclasses.dataclass(frozen=True)
class MeshConfig:
    """Device mesh layout: `dp` replicates the dense model / splits the batch,
    `mp` shards embedding tables (DeepRec CollectiveStrategy.embedding_scope
    analog, group_embedding_collective_strategy.py:68-86)."""

    dp: int = 1
    mp: int = 1
    axis_dp: str = "dp"
    axis_mp: str = "mp"


@dataclasses.dataclass(frozen=True)
class CheckpointConfig:
    """Full + incremental checkpoint cadence — parity with
    MonitoredTrainingSession(save_checkpoint_secs=, save_incremental_checkpoint_secs=)
    (docs/docs_en/Incremental-Checkpoint.md)."""

    directory: str = "ckpt"
    save_steps: int = 1000
    incremental_save_steps: int = 0  # 0 disables incremental saves
    keep: int = 3
