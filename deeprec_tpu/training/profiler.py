"""Profiling/tracing — the timeline analog.

DeepRec exposes per-step timelines via RunOptions.trace_level +
StepStatsCollector and modelzoo --timeline flags (SURVEY.md §5). On TPU the
native equivalent is the XLA/JAX profiler: traces capture HLO-level device
timelines viewable in TensorBoard/Perfetto. One context manager + a
step-windowed helper matching the reference's "--timeline N" UX.
"""
from __future__ import annotations

import contextlib
import os
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/deeprec_tpu_trace") -> Iterator[str]:
    """Capture a device trace for the enclosed block."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


class StepWindowTracer:
    """Trace steps [start, stop) of a training loop — the
    START/STOP_NODE_STATS_STEP pattern (Executor-Optimization.md) without a
    cost-model executor to feed: the trace goes to the human/profiler."""

    def __init__(self, start_step: int, stop_step: int,
                 logdir: str = "/tmp/deeprec_tpu_trace"):
        self.start = start_step
        self.stop = stop_step
        self.logdir = logdir
        self._active = False

    def on_step(self, step: int) -> None:
        """Call BEFORE dispatching step `step`; traces steps in
        [start, stop). Range-based so a run resuming past `start` (e.g. from
        a checkpoint) still enters the window if any of it remains."""
        if self.start <= step < self.stop and not self._active:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
