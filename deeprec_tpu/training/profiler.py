"""Profiling/tracing — the timeline analog.

DeepRec exposes per-step timelines via RunOptions.trace_level +
StepStatsCollector and modelzoo --timeline flags (SURVEY.md §5). On TPU the
native equivalent is the XLA/JAX profiler: traces capture HLO-level device
timelines viewable in TensorBoard/Perfetto. One context manager + a
step-windowed helper matching the reference's "--timeline N" UX.
"""
from __future__ import annotations

import bisect
import contextlib
import os
import threading
import time
from typing import Dict, Iterator

import jax


@contextlib.contextmanager
def trace(logdir: str = "/tmp/deeprec_tpu_trace") -> Iterator[str]:
    """Capture a device trace for the enclosed block."""
    os.makedirs(logdir, exist_ok=True)
    jax.profiler.start_trace(logdir)
    try:
        yield logdir
    finally:
        jax.profiler.stop_trace()


def phase_scope(name: str):
    """`jax.named_scope("phase_<name>")` — the in-program half of phase
    attribution (see PhaseProfiler): ops emitted under it group per phase
    in device traces. The trainers wrap their step phases in it
    (lookup / route_next / dense_fwd_bwd / sparse_apply /
    finish_exchange), and the chunked exchange (`ShardedTable` with
    exchange_chunks > 1) scopes each column-chunk collective as
    `exchange_chunk<i>` so a trace shows the chunk pipeline instead of
    one opaque collective."""
    return jax.named_scope(f"phase_{name}")


class PhaseProfiler:
    """Named-phase step breakdown (lookup / exchange / dense fwd-bwd /
    sparse apply / metadata ...).

    Two halves, matching how phase attribution works on an async device:

      * Inside the compiled step the trainers wrap each phase in
        `jax.named_scope("phase_<name>")` (training/trainer.py), so device
        traces (StepWindowTracer / `trace()`) group the emitted ops per
        phase — that is where TPU per-phase DEVICE time comes from.
      * Host-side, `phase(name)` wraps a blocking call (e.g. a jitted
        sub-program of just the lookups, or lookup+apply) in a
        `jax.profiler.TraceAnnotation` plus a wall-clock accumulator;
        `phase_report()` returns {phase: {calls, total_ms, mean_ms}}.
        `bench.py --profile` uses this to time phase sub-programs and
        report where the step went — the measurement that verifies a hot-
        path diet actually moved engine time, without trace parsing.

    The two compose: annotations from (2) bracket the dispatches of (1) on
    the host timeline when a trace is being captured.
    """

    def __init__(self):
        self._times: Dict[str, list] = {}

    @contextlib.contextmanager
    def phase(self, name: str, block=None) -> Iterator[None]:
        """Time the enclosed block under `name`. Pass `block` (an array or
        pytree) to `jax.block_until_ready` before the clock stops so async
        dispatch doesn't attribute device time to the NEXT phase.

        When obs tracing is configured (DEEPREC_TRACE), each phase also
        lands as a timeline span in the obs JSONL — the training half of
        the train→delta→serve Perfetto timeline (tools/obs_trace.py)."""
        from deeprec_tpu.obs import trace as obs_trace

        t0 = time.perf_counter()
        t0w = time.time()
        with jax.profiler.TraceAnnotation(f"phase_{name}"):
            try:
                yield
            finally:
                if block is not None:
                    jax.block_until_ready(block)  # noqa: DRT002 — the profiler's purpose: phase attribution requires blocking
                self._times.setdefault(name, []).append(
                    time.perf_counter() - t0
                )
                obs_trace.phase_span(f"phase_{name}", t0w, time.time())

    def timed(self, name: str, fn, *args, **kwargs):
        """Run fn(*args, **kwargs), block on its result, record under
        `name`, return the result."""
        out = None
        with self.phase(name):
            out = fn(*args, **kwargs)
            jax.block_until_ready(out)
        return out

    def record(self, name: str, seconds: float) -> None:
        """Fold an externally measured duration into phase `name` — the
        entry point for HOST phases whose cost is accounted elsewhere:
        checkpoint stalls (CheckpointManager.last_save["stall_ms"]),
        multi-tier sync stalls (MultiTierTable.sync_stall_ms), writer
        drain time. These subsystems time themselves (their stalls span
        their own internal sync points), so the profiler takes the number
        instead of wrapping the call."""
        self._times.setdefault(name, []).append(float(seconds))

    def reset(self) -> None:
        self._times.clear()

    def phase_report(self) -> Dict[str, Dict[str, float]]:
        """{phase: {calls, total_ms, mean_ms, min_ms}} over everything
        recorded since the last reset()."""
        out = {}
        for name, ts in self._times.items():
            out[name] = {
                "calls": len(ts),
                "total_ms": round(sum(ts) * 1e3, 3),
                "mean_ms": round(sum(ts) / len(ts) * 1e3, 3),
                "min_ms": round(min(ts) * 1e3, 3),
            }
        return out


class LatencyHistogram:
    """Fixed-bucket log-scale latency histogram: O(1) record, bounded
    memory, mergeable — the accounting primitive behind serving's
    per-stage timers (serving/stats.py) and anything else that needs
    percentiles without keeping every sample.

    Buckets grow geometrically from `lo` seconds; values above the last
    bound land in an overflow bucket whose percentile estimate is the
    tracked exact max. Thread-safe (one small lock per record)."""

    GROWTH = 1.5

    def __init__(self, lo: float = 50e-6, hi: float = 120.0):
        bounds = []
        b = lo
        while b < hi:
            bounds.append(b)
            b *= self.GROWTH
        self._bounds = bounds  # upper edge of each bucket, seconds
        self._counts = [0] * (len(bounds) + 1)  # +1 overflow
        self._n = 0
        self._sum = 0.0
        self._max = 0.0
        self._lock = threading.Lock()

    def record(self, seconds: float) -> None:
        s = float(seconds)
        i = bisect.bisect_left(self._bounds, s)
        with self._lock:
            self._counts[i] += 1
            self._n += 1
            self._sum += s
            if s > self._max:
                self._max = s

    def merge(self, other: "LatencyHistogram") -> None:
        with other._lock:
            counts, n = list(other._counts), other._n
            tot, mx = other._sum, other._max
        with self._lock:
            for i, c in enumerate(counts):
                self._counts[i] += c
            self._n += n
            self._sum += tot
            self._max = max(self._max, mx)

    def percentile(self, q: float) -> float:
        """Upper-bucket-edge estimate of the q-quantile in seconds."""
        with self._lock:
            n, counts, mx = self._n, list(self._counts), self._max
        if n == 0:
            return 0.0
        target = min(int(q * n), n - 1)
        seen = 0
        for i, c in enumerate(counts):
            seen += c
            if seen > target:
                # clamp to the exact max: a coarse bucket's upper edge can
                # exceed every sample in it (p99 > max is self-contradictory)
                return min(self._bounds[i], mx) if i < len(self._bounds) else mx
        return mx

    def summary(self) -> Dict[str, float]:
        """{count, mean_ms, p50_ms, p90_ms, p99_ms, max_ms} — the shape
        `/v1/stats` and SERVING_BENCH.json report per stage."""
        with self._lock:
            n, tot, mx = self._n, self._sum, self._max
        return {
            "count": n,
            "mean_ms": round(tot / n * 1e3, 3) if n else 0.0,
            "p50_ms": round(self.percentile(0.50) * 1e3, 3),
            "p90_ms": round(self.percentile(0.90) * 1e3, 3),
            "p99_ms": round(self.percentile(0.99) * 1e3, 3),
            "max_ms": round(mx * 1e3, 3),
        }


class StepWindowTracer:
    """Trace steps [start, stop) of a training loop — the
    START/STOP_NODE_STATS_STEP pattern (Executor-Optimization.md) without a
    cost-model executor to feed: the trace goes to the human/profiler."""

    def __init__(self, start_step: int, stop_step: int,
                 logdir: str = "/tmp/deeprec_tpu_trace"):
        self.start = start_step
        self.stop = stop_step
        self.logdir = logdir
        self._active = False

    def on_step(self, step: int) -> None:
        """Call BEFORE dispatching step `step`; traces steps in
        [start, stop). Range-based so a run resuming past `start` (e.g. from
        a checkpoint) still enters the window if any of it remains."""
        if self.start <= step < self.stop and not self._active:
            os.makedirs(self.logdir, exist_ok=True)
            jax.profiler.start_trace(self.logdir)
            self._active = True
        elif step >= self.stop and self._active:
            jax.profiler.stop_trace()
            self._active = False

    def close(self) -> None:
        if self._active:
            jax.profiler.stop_trace()
            self._active = False
