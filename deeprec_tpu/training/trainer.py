"""Generic train/eval step over (hash tables + dense params).

The structural translation of DeepRec's session-run training (SURVEY.md §3.1):
one jitted function per step performs — sparse lookups (with insertion,
frequency, admission), the dense forward/backward, the fused sparse applies
and the dense optimizer update. XLA sees the whole step as one program, which
is what replaces DeepRec's executor/cost-model machinery
(docs/docs_en/Executor-Optimization.md) on TPU.

GroupEmbedding is built in: features whose tables share a config and id shape
are automatically *bundled* — their states stack along a leading table axis
and a single vmapped lookup/apply serves all of them, exactly the
N-lookups-in-one-kernel optimization of DeepRec's GroupEmbeddingVarLookup
(core/ops/kv_variable_ops.cc:404; docs/docs_en/Group-Embedding.md), and it
also keeps the compiled program small (one probe loop, not one per feature).

Models are plain objects exposing:
    features: Sequence[SparseFeature | DenseFeature]
    init(key) -> dense params (pytree)
    apply(params, inputs: ModelInputs, train: bool) -> logits [B] or
        {task: logits} for multi-task models (labels then come from
        batch['label_<task>']).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from flax import struct

from deeprec_tpu import features as fcol
from deeprec_tpu.embedding import combiners
from deeprec_tpu.embedding.table import EmbeddingTable, TableState
from deeprec_tpu.features import SparseFeature
from deeprec_tpu.optim.apply import apply_gradients, ensure_slots
from deeprec_tpu.optim.sparse import SparseOptimizer
from deeprec_tpu.training import metrics as M


@struct.dataclass
class TrainState:
    step: jnp.ndarray  # [] int32 global step
    tables: Dict[str, TableState]  # bundle name -> (stacked) table state
    dense: Any
    opt_state: Any


@struct.dataclass
class PipelineCarry:
    """TrainState plus a one-batch lookahead: the batch whose lookup has
    already been issued, its per-feature views and per-bundle lookup
    results. Two users share it:

      * the EXACT pipelined K-step scan (`pipeline_mode != "off"`): the
        carried lookup was finished AFTER the previous step's apply, so
        consuming it is bit-identical to the sequential step;
      * the stale-by-one async stage (parallel/async_stage.py, where it is
        exported as `AsyncState`): the carried lookup was finished BEFORE
        the previous apply — the documented one-step staleness.
    """

    inner: TrainState
    batch: Dict[str, jnp.ndarray]  # the prefetched batch (ids/dense/labels)
    views: Dict[str, Any]  # feature -> (embeddings, inverse, mask)
    bundle_res: Dict[str, Any]  # bundle -> lookup result for the backward
    # Step-sentinel carry ({"ema": f32[]} — guard/sentinel.py) threaded
    # through the pipelined scan exactly like the lookahead. None (an
    # empty pytree node) when the trainer has no sentinel, so existing
    # carriers (parallel/async_stage.py AsyncState) are structurally
    # unchanged.
    guard: Any = None


# `pipeline_mode`: how the K-step device loop schedules the embedding
# exchange relative to dense compute (docs/perf.md round 11).
#   "off"       — strictly sequential scan body (lookup -> dense -> apply).
#   "lookahead" — the scan carries a one-batch lookahead: batch t+1's
#                 routing (id dedup + id exchange) and owner resolve
#                 (probe/insert/meta/init) are issued BEFORE batch t's
#                 dense compute (no data dependency -> XLA's async
#                 collectives hide them behind the matmuls); the value
#                 gather + embedding exchange run after batch t's apply,
#                 which keeps the pipeline exact — bit-identical to "off".
#   "chunked"   — "lookahead" plus the value/grad exchanges split into
#                 `pipeline_chunks` column chunks (ShardedTable
#                 exchange_chunks): several smaller collectives whose wire
#                 time pipelines against the neighbouring gather /
#                 segment-sum compute. Also exact.
#   "nested"    — the 2-D-mesh form of "chunked" (docs/multihost.md):
#                 same rotated scan and chunked exchanges, intended for
#                 comm="hier" where route(t+1) contains BOTH tiers' id
#                 hops — the expensive inter-tier (DCN) exchange of t+1
#                 is issued a full dense fwd/bwd ahead, nesting the DCN
#                 pipeline inside the intra-host one. Same exact-no-
#                 staleness contract (prologue fill, last-iteration
#                 peel): bit-identical to "off".
PIPELINE_MODES = ("off", "lookahead", "chunked", "nested")


def validate_pipeline_mode(mode: str, where: str) -> None:
    if mode not in PIPELINE_MODES:
        raise ValueError(
            f"{where}: pipeline_mode must be one of {PIPELINE_MODES}, "
            f"got {mode!r}"
        )


@dataclasses.dataclass
class Bundle:
    """A set of features served by one (possibly stacked) table state.

    stacked=True: `table` holds the shared per-member config; state arrays
    carry a leading [T] table axis and lookups/applies are vmapped over it.
    stacked=False: a single table, optionally shared by several features
    (shared_embedding semantics) which then look up sequentially.
    """

    name: str
    table: EmbeddingTable
    features: List[SparseFeature]
    stacked: bool

    @property
    def salts(self):
        from deeprec_tpu.utils.hashing import name_salt

        return jnp.asarray([name_salt(f.name) for f in self.features], jnp.uint32)


def build_bundles(specs) -> Dict[str, Bundle]:
    """Group single-use tables by (config-sans-name, id rank/pad); keep
    shared tables as individual bundles."""
    sparse = fcol.sparse_features(specs)
    by_table: Dict[str, List[SparseFeature]] = {}
    for f in sparse:
        by_table.setdefault(fcol.resolve_table_name(f), []).append(f)
    cfgs = fcol.table_configs(specs)

    bundles: Dict[str, Bundle] = {}
    groups: Dict[tuple, List[SparseFeature]] = {}
    for tname, feats in by_table.items():
        cfg = cfgs[tname]
        if len(feats) > 1:
            bundles[tname] = Bundle(tname, EmbeddingTable(cfg), feats, False)
        else:
            f = feats[0]
            # Pooling kind + declared max_len separate sequence features
            # ([B, L] ids) from scalar bags so stacked shapes stay compatible
            # (a runtime shape check in _lookup_all backstops undeclared L).
            key = (dataclasses.replace(cfg, name="_"), f.pad_value, f.pooling,
                   f.max_len)
            groups.setdefault(key, []).append(f)
    for i, (key, feats) in enumerate(sorted(groups.items(), key=lambda kv: kv[1][0].name)):
        if len(feats) == 1:
            f = feats[0]
            tname = fcol.resolve_table_name(f)
            bundles[tname] = Bundle(tname, EmbeddingTable(cfgs[tname]), feats, False)
        else:
            cfg = dataclasses.replace(key[0], name=f"group{i}")
            bundles[cfg.name] = Bundle(cfg.name, EmbeddingTable(cfg), feats, True)
    return bundles


@struct.dataclass
class ModelInputs:
    """What the model's apply() receives each step (a pytree, so it can
    cross transform boundaries like jax.checkpoint)."""

    pooled: Dict[str, jnp.ndarray]  # feature -> [B, D]
    seq: Dict[str, Tuple[jnp.ndarray, jnp.ndarray]]  # feature -> ([B,L,D], [B,L] mask)
    dense: Dict[str, jnp.ndarray]  # feature -> [B, W]


def _prep_ids(ids):
    return ids[:, None] if ids.ndim == 1 else ids


def stack_batches(batches):
    """Stack K same-shape batch dicts into one pytree with a leading
    [K, ...] axis — the input layout of `Trainer.train_steps`. Host-side;
    for ShardedTrainer place the result with `shard_batch(..., stacked=True)`
    so the K axis stays unsharded and the batch axis splits over the mesh."""
    batches = list(batches)
    if len(batches) == 1:
        return jax.tree.map(lambda x: jnp.asarray(x)[None], batches[0])
    return jax.tree.map(lambda *xs: jnp.stack(xs), *batches)


# Module-level so repeated evaluate() calls hit one compile cache.
_jit_auc_update = jax.jit(M.auc_update)


class Trainer:
    def __init__(
        self,
        model,
        sparse_opt: SparseOptimizer,
        dense_opt: Optional[optax.GradientTransformation] = None,
        grad_averaging: bool = False,
        remat: bool = False,
        stage: str = "auto",
        unique_budget=None,
        pipeline_mode: str = "off",
        pipeline_chunks: int = 4,
        sentinel=None,
    ):
        self.model = model
        self.sparse_opt = sparse_opt
        self.dense_opt = dense_opt or optax.adam(1e-3)
        self.grad_averaging = grad_averaging
        # Step sentinel (guard/sentinel.py SentinelConfig): per-dispatch
        # model-quality flags fused into the jitted step and the K-step
        # scan body — one int32 scalar out per step, bit-exact no-op on
        # the update math while untripped. Base Trainer only: the
        # sharded step impls are separate programs (ShardedTrainer never
        # forwards the kwarg).
        if sentinel is not None:
            from deeprec_tpu.guard.sentinel import SentinelConfig

            if not isinstance(sentinel, SentinelConfig):
                raise TypeError(
                    "sentinel must be a guard.SentinelConfig, got "
                    f"{type(sentinel).__name__}"
                )
        self.sentinel = sentinel
        # In-step pipelining of the K-step device loop (train_steps): see
        # PIPELINE_MODES. Single-device trainers gain the restructured
        # scan (route/resolve hoisted over the dense compute); sharded
        # trainers additionally overlap the collectives it contains.
        validate_pipeline_mode(pipeline_mode, type(self).__name__)
        self.pipeline_mode = pipeline_mode
        self.pipeline_chunks = max(1, int(pipeline_chunks))
        # remat=True recomputes the dense forward in the backward pass
        # (jax.checkpoint): trades MXU FLOPs for HBM — the rematerialisation
        # lever for big towers / long sequences.
        self.remat = remat
        if stage not in ("auto", "off"):
            raise ValueError(f"unknown stage mode {stage!r}")
        self.stage_mode = stage
        # Trainer-wide unique-budget override (None = per-feature/table
        # configs decide): "auto" | "off" | int — see ops/dedup.py and
        # TableConfig.unique_budget. Same grammar check as the configs: an
        # unvalidated typo would fall through _resolve_budget's else-branch
        # and silently mean "auto".
        fcol.validate_unique_budget(unique_budget, "Trainer(unique_budget=)")
        self.unique_budget = unique_budget
        self.sparse_specs = fcol.sparse_features(model.features)
        self.dense_specs = fcol.dense_features(model.features)
        self.bundles = build_bundles(model.features)
        self._budget_modes = {
            bname: self._bundle_budget_mode(b)
            for bname, b in self.bundles.items()
        }
        self._auto_frac: Dict[str, float] = {}  # bundle -> budget fraction
        self._unique_ema: Dict[str, float] = {}  # bundle -> raw EMA
        self._make_jits()

    def _make_jits(self):
        """(Re)wrap the step functions in fresh jit caches. Budget
        resolution happens at TRACE time, so anything that changes a
        resolved budget (update_budgets moving an "auto" bucket) must
        rebuild these — an already-cached executable for the same input
        avals would silently keep its old unique sizes otherwise."""
        self._train_step = jax.jit(self._step_impl, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._train_step_accum = jax.jit(self._accum_impl, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        # K-step device loop: jit caches one executable per K (the stacked
        # batch's leading dim is part of the trace signature), so sweeping
        # or changing K recompiles once per value and then amortizes.
        self._train_steps = jax.jit(self._steps_impl, donate_argnums=0)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps
        self._eval_step = jax.jit(self._eval_impl)  # noqa: DRT001 — deliberate rebuild-on-budget/plan-change; one wrapper serves all steps

    # Back-compat/introspection: table object + state accessor per table name.
    @property
    def tables(self) -> Dict[str, EmbeddingTable]:
        out = {}
        for b in self.bundles.values():
            for f in b.features:
                out[fcol.resolve_table_name(f)] = b.table
        return out

    def table_state(self, state: TrainState, table_name: str) -> TableState:
        """Extract the (unstacked) state of one named table."""
        for b in self.bundles.values():
            for k, f in enumerate(b.features):
                if fcol.resolve_table_name(f) == table_name:
                    ts = state.tables[b.name]
                    return jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
        raise KeyError(table_name)

    # ------------------------------------------------------------------ init

    def init(self, seed: int = 0) -> TrainState:
        key = jax.random.PRNGKey(seed)
        dense = self.model.init(key)
        tables = {}
        for bname, b in self.bundles.items():
            local = ensure_slots(b.table, b.table.create(), self.sparse_opt)
            if b.stacked:
                T = len(b.features)
                local = jax.tree.map(lambda a: jnp.stack([a] * T), local)
            tables[bname] = local
        return TrainState(
            step=jnp.zeros((), jnp.int32),
            tables=tables,
            dense=dense,
            opt_state=self.dense_opt.init(dense),
        )

    # ------------------------------------------------------------- internals
    #
    # _lookup_one/_apply_one are the per-bundle primitives; ShardedTrainer
    # overrides just these two to swap in the collective path, so the
    # bundling/stacking control flow below exists exactly once.

    # ----------------------------------------------------- unique budgets

    def _bundle_budget_mode(self, b: Bundle):
        """Effective budget mode for one bundle: the trainer-wide override
        wins, then feature-level settings (largest int / any "auto"),
        then the table config. Returns None (legacy), "auto", or int."""
        mode = self.unique_budget
        if mode is None:
            feat = [
                f.unique_budget for f in b.features
                if f.unique_budget is not None
            ]
            if feat:
                ints = [m for m in feat if isinstance(m, int)]
                mode = (
                    max(ints) if ints
                    else ("auto" if any(m == "auto" for m in feat) else "off")
                )
            else:
                mode = b.table.cfg.unique_budget
        return mode  # None (legacy, logged) | "off" (legacy, silent) | "auto" | int

    def _resolve_budget(self, b: Bundle, n: int) -> Optional[int]:
        """Static uids-array size for an n-position lookup of bundle `b`,
        or None for the legacy U=N path. "auto" uses the quantized EMA
        fraction once `update_budgets` has measured one (clamped by the
        table capacity — more uniques than slots cannot land anyway);
        before the first measurement it runs at U=N through the hash
        engine so the counters seed the EMA without a sort."""
        from deeprec_tpu.ops import dedup

        mode = self._budget_modes.get(b.name)
        if mode is None or mode == "off":
            if mode is None:  # "off" is a deliberate choice: stay silent
                dedup.log_full_fallback(b.name, n)
            return None
        if isinstance(mode, int):
            return dedup.resolve_size(mode, n)
        frac = self._auto_frac.get(b.name)
        if frac is None:
            budget = n
        else:
            import math

            budget = min(int(math.ceil(frac * n)), self._budget_capacity(b))  # noqa: DRT002 — trace-time budget arithmetic on static shapes, no device value
        return dedup.resolve_size(budget, n)

    def _budget_capacity(self, b: Bundle) -> int:
        """Upper clamp for the auto budget: a batch cannot hold more
        RESIDENT uniques than the table has slots. ShardedTrainer overrides
        with the GLOBAL capacity — its bundle cfg is per-shard, but a local
        batch's ids hash across every shard."""
        return b.table.cfg.capacity

    def _budget_for_lookup(self, b: Bundle, ids, train: bool) -> Optional[int]:
        """Static unique size for one lookup — shared by the local and the
        sharded `_lookup_one`. Budgets apply to TRAIN lookups only: an
        eval/serving batch with more uniques than the (train-skew-derived)
        budget would silently serve defaults for resident keys — and the
        overflow counter only accumulates on train state, so it would be
        invisible. Eval runs exact at U = N."""
        import numpy as np

        if not train:
            return None
        return self._resolve_budget(b, int(np.prod(ids.shape)))  # noqa: DRT002 — np.prod of a static shape tuple, no device value

    def _bundle_plan_leaves(self, b: Bundle):
        """Per-bundle placement-plan device constants threaded through the
        lookup/route vmaps (parallel/placement.py). The base trainer has
        no placement — an empty dict means uniform hash routing and adds
        no vmap leaves; ShardedTrainer overrides with the active plan's
        arrays (leading [T] member axis for stacked bundles)."""
        return {}

    def _lookup_one(self, b: Bundle, state, ids, pad, salt, step, train,
                    plan=None):
        U = self._budget_for_lookup(b, ids, train)
        return b.table._lookup_unique_impl(
            state, ids, step, train, pad, U, salt=salt
        )

    def _bundle_reuse_rows(self, b: Bundle) -> bool:
        """Whether the apply may reuse the forward residual (res.rows)
        instead of re-gathering value rows. Shared-table bundles (several
        features on ONE unstacked table) apply sequentially — feature k's
        residual predates feature k-1's apply and overlapping rows would
        lose updates — so only they re-gather. Stacked (vmapped) members
        and single-feature tables see exactly one apply per step."""
        return b.stacked or len(b.features) == 1

    def _apply_one(self, b: Bundle, state, res, grad, step, lr):
        # Train hot path: opt into the traffic diet — reuse the forward
        # residual where the bundle allows it, and never re-stamp
        # version/dirty (the same-step train lookup's fused metadata
        # scatter already did, for a superset of the applied rows).
        return apply_gradients(
            b.table, state, self.sparse_opt, res, grad, step=step, lr=lr,
            grad_averaging=self.grad_averaging,
            reuse_rows=self._bundle_reuse_rows(b), stamp_meta=False,
        )

    def _stacked_ids(self, b: Bundle, batch) -> jnp.ndarray:
        """[T, B, L] id stack of a grouped bundle (shape-checked)."""
        shapes = {f.name: _prep_ids(batch[f.name]).shape for f in b.features}
        if len(set(shapes.values())) > 1:
            raise ValueError(
                f"grouped features have mismatched id shapes {shapes}; "
                "declare distinct SparseFeature.max_len values to keep "
                "them in separate embedding groups"
            )
        return jnp.stack([_prep_ids(batch[f.name]) for f in b.features])

    def _lookup_all(self, tables, batch, step, train):
        """Run every bundle's lookup. Returns (tables, per-feature views,
        per-bundle stacked results for the backward pass)."""
        views = {}  # feature -> (embeddings [U,D], inverse, mask)
        bundle_res = {}  # bundle -> stacked result
        for bname, b in self.bundles.items():
            plan = self._bundle_plan_leaves(b)
            if b.stacked:
                ids = self._stacked_ids(b, batch)
                pad = b.features[0].pad_value
                masks = ids != jnp.asarray(pad, ids.dtype)

                def one(s, i, sa, pl, b=b, pad=pad):
                    return self._lookup_one(b, s, i, pad, sa, step, train,
                                            plan=pl)

                tables[bname], res = jax.vmap(one)(
                    tables[bname], ids, b.salts, plan
                )
                bundle_res[bname] = res
                for k, f in enumerate(b.features):
                    views[f.name] = (
                        res.embeddings[k],
                        res.inverse[k],
                        masks[k],
                    )
            else:
                for f in b.features:
                    ids = _prep_ids(batch[f.name])
                    mask = ids != jnp.asarray(f.pad_value, ids.dtype)
                    tables[bname], res = self._lookup_one(
                        b, tables[bname], ids, f.pad_value, None, step, train,
                        plan=plan,
                    )
                    bundle_res.setdefault(bname, {})[f.name] = res
                    views[f.name] = (res.embeddings, res.inverse, mask)
        return tables, views, bundle_res

    # ------------------------------------------------- split-phase lookup
    #
    # The three-phase decomposition of _lookup_all the pipelined scan (and
    # the async stale-by-one stage) schedule around the dense compute:
    #   route   — id dedup (+ the id exchange, sharded): ids only, no
    #             table state, hoistable arbitrarily early;
    #   resolve — probe/insert, fused metadata, init scatter, admission:
    #             reads keys/meta, never the value rows an apply writes,
    #             so it commutes bit-exactly with the previous apply;
    #   finish  — value gather (+ the embedding exchange, sharded): reads
    #             the CURRENT values, so running it after the previous
    #             apply keeps the lookahead staleness-free.
    # route → resolve → finish composes to exactly _lookup_all.
    # ShardedTrainer overrides only the three *_one primitives.

    def _route_one(self, b: Bundle, ids, pad, train, plan=None):
        U = self._budget_for_lookup(b, ids, train)
        return b.table._route_ids(ids, pad, U)

    def _resolve_one(self, b: Bundle, state, route, salt, step, train):
        return b.table._resolve_routed(
            state, route, step=step, train=train, salt=salt
        )

    def _finish_one(self, b: Bundle, state, pending, train, keep_rows=True):
        return b.table._finish_resolved(state, pending, keep_rows=keep_rows)

    def _route_all(self, batch, train=True):
        """Phase 1 for every bundle: pure function of the id batch."""
        routes = {}
        for bname, b in self.bundles.items():
            plan = self._bundle_plan_leaves(b)
            if b.stacked:
                ids = self._stacked_ids(b, batch)
                pad = b.features[0].pad_value

                def one(i, pl, b=b, pad=pad):
                    return self._route_one(b, i, pad, train, plan=pl)

                routes[bname] = jax.vmap(one)(ids, plan)
            else:
                routes[bname] = {
                    f.name: self._route_one(
                        b, _prep_ids(batch[f.name]), f.pad_value, train,
                        plan=plan,
                    )
                    for f in b.features
                }
        return routes

    def _resolve_all(self, tables, routes, step, train=True):
        """Phase 2 for every bundle (same bundle/feature order as
        _lookup_all, so shared-table inserts chain identically)."""
        pending = {}
        for bname, b in self.bundles.items():
            if b.stacked:

                def one(s, r, sa, b=b):
                    return self._resolve_one(b, s, r, sa, step, train)

                tables[bname], pend = jax.vmap(one)(
                    tables[bname], routes[bname], b.salts
                )
                pending[bname] = pend
            else:
                for f in b.features:
                    tables[bname], pend = self._resolve_one(
                        b, tables[bname], routes[bname][f.name], None, step,
                        train,
                    )
                    pending.setdefault(bname, {})[f.name] = pend
        return tables, pending

    def _finish_all(self, tables, pending, batch, train=True, keep_rows=True):
        """Phase 3 for every bundle: gather (+ exchange) the value rows
        against the CURRENT tables. Returns (views, bundle_res) shaped
        exactly like _lookup_all's."""
        views = {}
        bundle_res = {}
        for bname, b in self.bundles.items():
            if b.stacked:
                ids = self._stacked_ids(b, batch)
                pad = b.features[0].pad_value
                masks = ids != jnp.asarray(pad, ids.dtype)

                def one(s, p, b=b):
                    return self._finish_one(b, s, p, train, keep_rows)

                res = jax.vmap(one)(tables[bname], pending[bname])
                bundle_res[bname] = res
                for k, f in enumerate(b.features):
                    views[f.name] = (
                        res.embeddings[k],
                        res.inverse[k],
                        masks[k],
                    )
            else:
                for f in b.features:
                    ids = _prep_ids(batch[f.name])
                    mask = ids != jnp.asarray(f.pad_value, ids.dtype)
                    res = self._finish_one(
                        b, tables[bname], pending[bname][f.name], train,
                        keep_rows,
                    )
                    bundle_res.setdefault(bname, {})[f.name] = res
                    views[f.name] = (res.embeddings, res.inverse, mask)
        return views, bundle_res

    def _build_inputs(self, embs, views, batch) -> ModelInputs:
        pooled, seq = {}, {}
        for f in self.sparse_specs:
            _, inverse, mask = views[f.name]
            e_u = embs[f.name]
            if f.pooling == "none":
                e = e_u[inverse]  # [B, L, D]
                seq[f.name] = (jnp.where(mask[..., None], e, 0.0), mask)
            else:
                pooled[f.name] = combiners.combine(e_u, inverse, mask, f.pooling)
        dense = {f.name: batch[f.name] for f in self.dense_specs}
        return ModelInputs(pooled=pooled, seq=seq, dense=dense)

    def _apply_all(self, tables, bundle_res, g_embs, step, lr):
        for bname, b in self.bundles.items():
            if b.stacked:
                res = bundle_res[bname]
                grads = jnp.stack([g_embs[f.name] for f in b.features])

                def one(s, r, g, b=b):
                    return self._apply_one(b, s, r, g, step, lr)

                tables[bname] = jax.vmap(one)(tables[bname], res, grads)
            else:
                for f in b.features:
                    tables[bname] = self._apply_one(
                        b, tables[bname], bundle_res[bname][f.name],
                        g_embs[f.name], step, lr,
                    )
        return tables

    def _loss_from_logits(self, out, batch):
        if isinstance(out, dict):
            losses = {
                task: M.bce_loss(logits, batch[f"label_{task}"])
                for task, logits in out.items()
            }
            return sum(losses.values()), out
        return M.bce_loss(out, batch["label"]), out

    def _micro_step(self, tables, dense, batch, step, lr):
        """Forward + backward + SPARSE applies for one (micro-)batch; returns
        updated tables, the dense-grad pytree (NOT applied) and metrics.

        Phases carry `jax.named_scope` annotations (training/profiler.py:
        the per-phase step breakdown) so device traces group the emitted
        ops under lookup / dense fwd-bwd / sparse apply."""
        with jax.named_scope("phase_lookup"):
            tables, views, bundle_res = self._lookup_all(
                tables, batch, step, True
            )
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, batch)
            apply = (
                jax.checkpoint(self.model.apply, static_argnums=(2,))
                if self.remat
                else self.model.apply
            )
            out = apply(dense, inputs, True)
            loss, out = self._loss_from_logits(out, batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(dense, embs)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, bundle_res, g_embs, step, lr)
        mets = {"loss": loss}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = M.accuracy(probs, batch["label"])
        else:
            mets["accuracy"] = jnp.zeros(())
        if self.sentinel is not None:
            with jax.named_scope("phase_sentinel"):
                tables, mets["_sentinel"] = self._sentinel_observe(
                    tables, bundle_res, loss, g_dense, g_embs, step
                )
        return tables, g_dense, mets

    # ------------------------------------------------------- step sentinel

    def _sentinel_observe(self, tables, bundle_res, loss, g_dense, g_embs,
                          step):
        """Device half of the step sentinel: fused reductions over the
        step's loss/grads plus a post-apply gather of exactly the rows
        this step updated (guard/rows.py — never a full-table scan).
        Returns (tables, obs dict); tables change only under the
        optional row clamp. Everything is a scalar reduction XLA fuses
        with the step — no host value, no extra dispatch."""
        from deeprec_tpu.guard import rows as guard_rows
        from deeprec_tpu.guard import sentinel as guard_sentinel

        cfg = self.sentinel
        finite, norm_sq = guard_sentinel.grad_observations(g_dense, g_embs)
        obs = {
            "loss": jnp.asarray(loss, jnp.float32),
            "grads_finite": finite,
            "grad_norm_sq": norm_sq,
        }
        want_rows = (
            cfg.row_norm_max is not None or cfg.row_clamp_norm is not None
        ) and not hasattr(self, "num_shards")
        if not want_rows:
            return tables, obs
        clamp = cfg.row_clamp_norm
        row_max = jnp.zeros((), jnp.float32)
        for bname, b in self.bundles.items():
            ts = tables[bname]
            if b.stacked:
                six = bundle_res[bname].slot_ix  # [T, U]

                def one(vals, ix, b=b):
                    n = guard_rows.touched_row_norms(b.table, vals, ix)
                    if clamp is not None:
                        vals = guard_rows.clamp_rows(
                            b.table, vals, ix, n, clamp, step
                        )
                    return vals, jnp.max(n)

                new_vals, maxes = jax.vmap(one)(ts.values, six)
                if clamp is not None:
                    tables[bname] = ts = ts.replace(values=new_vals)
                row_max = jnp.maximum(row_max, jnp.max(maxes))
            else:
                for f in b.features:
                    ts = tables[bname]
                    six = bundle_res[bname][f.name].slot_ix
                    n = guard_rows.touched_row_norms(b.table, ts.values, six)
                    if clamp is not None:
                        tables[bname] = ts.replace(
                            values=guard_rows.clamp_rows(
                                b.table, ts.values, six, n, clamp, step
                            )
                        )
                    row_max = jnp.maximum(row_max, jnp.max(n))
        obs["row_max"] = row_max
        return tables, obs

    def _sentinel_fold(self, mets, guard):
        """Combine a step's sentinel observations (popped from mets)
        with the guard carry into the per-dispatch flags scalar +
        advanced EMA, both riding out through mets."""
        from deeprec_tpu.guard import sentinel as guard_sentinel

        obs = mets.pop("_sentinel")
        if guard is None:
            guard = guard_sentinel.guard_init()
        flags, guard = guard_sentinel.step_flags(
            self.sentinel, obs["loss"], obs["grads_finite"],
            obs["grad_norm_sq"], obs.get("row_max"), guard,
        )
        mets["guard_flags"] = flags
        mets["guard_ema"] = guard["ema"]
        return mets, guard

    def _step_impl(self, state: TrainState, batch, lr, guard=None):
        step = state.step
        tables, g_dense, mets = self._micro_step(
            dict(state.tables), state.dense, batch, step, lr
        )
        if self.sentinel is not None:
            mets, guard = self._sentinel_fold(mets, guard)
        updates, opt_state = self.dense_opt.update(g_dense, state.opt_state,
                                                   state.dense)
        dense = optax.apply_updates(state.dense, updates)
        return TrainState(
            step=step + 1, tables=tables, dense=dense, opt_state=opt_state
        ), mets

    def _accum_impl(self, state: TrainState, batch, lr, guard=None):
        """Gradient micro-batching — the Auto-Micro-Batch analog
        (reference graph_execution_state.cc:635 PipelineGraph duplicates the
        compute graph N×; here it's a lax.scan over micro-batches): sparse
        tables apply per micro-batch (the reference's semantics), dense grads
        accumulate and apply once."""
        step = state.step
        A = next(iter(batch.values())).shape[0]

        def micro(carry, mb):
            tables, g_acc = carry
            tables, g_dense, mets = self._micro_step(
                tables, state.dense, mb, step, lr
            )
            g_acc = jax.tree.map(jnp.add, g_acc, g_dense)
            return (tables, g_acc), mets

        g0 = jax.tree.map(jnp.zeros_like, state.dense)
        (tables, g_acc), mets = jax.lax.scan(
            micro, (dict(state.tables), g0), batch
        )
        g_mean = jax.tree.map(lambda g: g / jnp.float32(A), g_acc)
        updates, opt_state = self.dense_opt.update(g_mean, state.opt_state,
                                                   state.dense)
        dense = optax.apply_updates(state.dense, updates)
        sen = mets.pop("_sentinel", None)  # [A]-stacked micro observations
        mets = jax.tree.map(jnp.mean, mets)
        if self.sentinel is not None and sen is not None:
            # The dispatch is the sentinel unit: micro-batch observations
            # reduce to one step-level record (ANY bad micro grad poisons
            # the step; norms take the worst micro-batch).
            mets["_sentinel"] = {
                "loss": jnp.mean(sen["loss"]),
                "grads_finite": jnp.all(sen["grads_finite"]),
                "grad_norm_sq": jnp.max(sen["grad_norm_sq"]),
            }
            if "row_max" in sen:
                mets["_sentinel"]["row_max"] = jnp.max(sen["row_max"])
            mets, guard = self._sentinel_fold(mets, guard)
        return TrainState(
            step=step + 1, tables=tables, dense=dense, opt_state=opt_state
        ), mets

    def _steps_impl(self, state: TrainState, batches, lr, guard=None):
        """Multi-step device loop — K full train steps per dispatch.

        DeepRec amortizes per-step host overhead with graph-level pipeline
        stages (Stage/SmartStage); in the functional world the same cure is
        a `lax.scan` over K steps inside ONE compiled program: the host
        dispatches once per K steps instead of once per step, which is the
        lever when the step is dispatch-overhead-bound (docs/perf.md). The
        scan threads the FULL TrainState — dense params, optimizer state
        and every hash-table TableState — so insertion, eviction counters,
        frequency/admission and version stamping behave exactly as K
        sequential `train_step` calls (tests/test_train_steps.py pins the
        equivalence, exact on table ints). With a sentinel configured the
        guard carry (loss EMA) rides the scan carry and the per-step
        flags stack [K] in the metrics — the host still reads ONE array
        per dispatch."""
        if self.pipeline_mode != "off":
            return self._steps_pipelined(state, batches, lr, guard)
        if self.sentinel is None:

            def body(state, batch):
                return self._step_impl(state, batch, lr)

            return jax.lax.scan(body, state, batches)
        from deeprec_tpu.guard.sentinel import guard_init

        def body(carry, batch):
            st, g = carry
            st, mets = self._step_impl(st, batch, lr, g)
            return (st, {"ema": mets["guard_ema"]}), mets

        (state, _), mets = jax.lax.scan(
            body, (state, guard if guard is not None else guard_init()),
            batches,
        )
        return state, mets

    # ------------------------------------------------- pipelined K-step scan

    def _pipe_prologue(self, state: TrainState, batch0,
                       guard=None) -> PipelineCarry:
        """Fill the pipeline: full split-phase lookup of the window's
        first batch (identical program to the sequential lookup)."""
        tables = dict(state.tables)
        routes = self._route_all(batch0, True)
        tables, pending = self._resolve_all(tables, routes, state.step, True)
        views, res = self._finish_all(tables, pending, batch0, True)
        return PipelineCarry(
            inner=TrainState(step=state.step, tables=tables,
                             dense=state.dense, opt_state=state.opt_state),
            batch=batch0, views=views, bundle_res=res, guard=guard,
        )

    def _pipe_step(self, carry: PipelineCarry, batch_next, lr):
        """One pipelined train step: dense fwd/bwd + sparse apply + dense
        update for the CARRIED batch t, interleaved with the lookahead for
        batch t+1 —

          1. route+resolve(t+1) issued BEFORE the dense compute (no data
             dependency on it: route reads only ids, resolve reads
             keys/meta which the diet apply never writes) so XLA's async
             collectives hide the id exchange + probe behind the matmuls;
          2. dense fwd/bwd on the carried (finished) lookup of batch t;
          3. sparse apply of batch t;
          4. finish(t+1) — value gather + embedding exchange — AFTER the
             apply, so batch t+1 sees post-apply tables: exact, no
             staleness.

        `batch_next=None` is the window epilogue (nothing to prefetch);
        the returned carry's lookahead fields are then stale garbage and
        only `.inner` is meaningful."""
        state = carry.inner
        step = state.step
        tables = dict(state.tables)
        if batch_next is not None:
            with jax.named_scope("phase_route_next"):
                routes = self._route_all(batch_next, True)
                tables, pending = self._resolve_all(
                    tables, routes, step + 1, True
                )
        views = carry.views
        prev_batch = carry.batch
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}

        def loss_fn(dense, embs):
            inputs = self._build_inputs(embs, views, prev_batch)
            apply = (
                jax.checkpoint(self.model.apply, static_argnums=(2,))
                if self.remat
                else self.model.apply
            )
            out = apply(dense, inputs, True)
            loss, out = self._loss_from_logits(out, prev_batch)
            return loss, out

        with jax.named_scope("phase_dense_fwd_bwd"):
            (loss, out), (g_dense, g_embs) = jax.value_and_grad(
                loss_fn, argnums=(0, 1), has_aux=True
            )(state.dense, embs)
        with jax.named_scope("phase_sparse_apply"):
            tables = self._apply_all(tables, carry.bundle_res, g_embs, step, lr)
        mets = {"loss": loss}
        if not isinstance(out, dict):
            probs = jax.nn.sigmoid(out)
            mets["accuracy"] = M.accuracy(probs, prev_batch["label"])
        else:
            mets["accuracy"] = jnp.zeros(())
        guard = carry.guard
        if self.sentinel is not None:
            # Sentinel over batch t: the apply above wrote batch t's rows,
            # so the row pass reads them BEFORE finish(t+1)'s gather.
            with jax.named_scope("phase_sentinel"):
                tables, mets["_sentinel"] = self._sentinel_observe(
                    tables, carry.bundle_res, loss, g_dense, g_embs, step
                )
            mets, guard = self._sentinel_fold(mets, guard)
        if batch_next is not None:
            with jax.named_scope("phase_finish_exchange"):
                views_n, res_n = self._finish_all(
                    tables, pending, batch_next, True
                )
        else:
            batch_next, views_n, res_n = prev_batch, views, carry.bundle_res
        updates, opt_state = self.dense_opt.update(
            g_dense, state.opt_state, state.dense
        )
        dense = optax.apply_updates(state.dense, updates)
        new_state = TrainState(
            step=step + 1, tables=tables, dense=dense, opt_state=opt_state
        )
        return PipelineCarry(
            inner=new_state, batch=batch_next, views=views_n,
            bundle_res=res_n, guard=guard,
        ), mets

    def _steps_pipelined(self, state: TrainState, batches, lr, guard=None):
        """K-step device loop with the one-batch lookahead rotated through
        the scan carry (pipeline_mode != "off"): prologue looks up batch
        0, each scan iteration consumes the carried lookup and prefetches
        the next batch's, the peeled epilogue consumes the last. Bit-
        identical to the sequential scan — tests/test_pipeline_overlap.py
        pins exactness on table ints, values and losses."""
        if self.sentinel is not None and guard is None:
            from deeprec_tpu.guard.sentinel import guard_init

            guard = guard_init()
        batch0 = jax.tree.map(lambda x: x[0], batches)
        rest = jax.tree.map(lambda x: x[1:], batches)
        carry = self._pipe_prologue(state, batch0, guard)

        def body(carry, batch_next):
            return self._pipe_step(carry, batch_next, lr)

        carry, mets = jax.lax.scan(body, carry, rest)
        carry, tail = self._pipe_step(carry, None, lr)
        mets = jax.tree.map(
            lambda a, b: jnp.concatenate([a, b[None]]), mets, tail
        )
        return carry.inner, mets

    def forward_views(self, state: TrainState, batch):
        """Readonly lookup pass (no inserts/counters): per-feature views
        plus per-bundle results. Shared by eval and the serving predictor."""
        tables = dict(state.tables)
        _, views, bundle_res = self._lookup_all(
            tables, batch, state.step, False
        )
        return views, bundle_res

    def probs_from_views(self, state: TrainState, views, batch):
        """Label-free forward: views -> sigmoid probabilities (dict per
        task for multi-task models). Returns (logits, probs)."""
        embs = {n: v[0].astype(jnp.float32) for n, v in views.items()}
        inputs = self._build_inputs(embs, views, batch)
        out = self.model.apply(state.dense, inputs, train=False)
        if isinstance(out, dict):
            probs = {k: jax.nn.sigmoid(v) for k, v in out.items()}
        else:
            probs = jax.nn.sigmoid(out)
        return out, probs

    def _eval_impl(self, state: TrainState, batch):
        views, _ = self.forward_views(state, batch)
        out, probs = self.probs_from_views(state, views, batch)
        loss, _ = self._loss_from_logits(out, batch)
        return loss, probs

    # ----------------------------------------------------------- auto-stage

    def input_keys(self) -> frozenset:
        """Batch keys the jitted step consumes — the model's input
        signature (sparse + dense feature names; labels ride by the
        'label*' convention, see _loss_from_logits). This is the
        SmartStage boundary derivation
        (/root/reference/tensorflow/core/graph/smart_stage_pass.cc:30)
        reduced to its JAX form: the reference walks the graph to find
        the IO-side cut; here the cut IS the batch dict, so the analysis
        collapses to 'which keys does the step read'."""
        return frozenset(f.name for f in self.sparse_specs) | frozenset(
            f.name for f in self.dense_specs
        )

    def stage_batch(self, batch):
        """Trim a host batch to the input signature and start its async
        device transfer (device_put returns immediately). Idempotent —
        re-staging a staged batch is a cheap no-op."""
        keep = self.input_keys()
        return self._stage_put({
            k: v for k, v in batch.items()
            if k in keep or k.startswith("label")
        })

    def _stage_put(self, batch):
        # ShardedTrainer overrides with mesh placement.
        return jax.device_put(batch)

    def stage(self, source, depth: int = 2, on_consume=None):
        """Auto-staged input pipeline: wrap any host batch iterator so IO,
        the host->device transfer, and the train step overlap — zero
        manual `staged()` calls, boundary derived from the model (the
        SmartStage user contract). Returns `source` unchanged when the
        trainer was built with stage="off".

        `on_consume`: called once per batch DELIVERED to the train loop
        (not per batch produced) — stream-position carriers
        (CriteoStats.mark_consumed) checkpoint the consumed index through
        this so a restore never skips the ring's in-flight batches.
        When omitted and `source` itself carries the contract
        (mark_consumed/attach_consumer — CriteoStats, the
        ParallelInputPipeline), it is wired automatically: forgetting the
        hookup silently broke exactly-once resume, the worst kind of
        correct-looking bug."""
        if self.stage_mode != "auto":
            return source
        from deeprec_tpu.data.prefetch import Prefetcher

        if on_consume is None:
            mark = getattr(source, "mark_consumed", None)
            if callable(mark):
                attach = getattr(source, "attach_consumer", None)
                if callable(attach):
                    attach()
                on_consume = mark
        pager = getattr(self, "_tier_pager", None)
        return Prefetcher(iter(source), depth=depth,
                          transform=self.stage_batch,
                          on_consume=on_consume,
                          peek=pager.observe if pager is not None else None)

    # --------------------------------------------------------------- public

    def _guard_or_init(self, guard):
        from deeprec_tpu.guard.sentinel import guard_init

        return guard if guard is not None else guard_init()

    def train_step(self, state: TrainState, batch, lr: Optional[float] = None,
                   guard=None):
        # lr always rides as a traced scalar so schedules never recompile.
        # `guard` is the sentinel carry from the PREVIOUS dispatch's mets
        # (guard/sentinel.guard_carry) — a device reference, never read
        # host-side here; omitted entirely when no sentinel is configured
        # so sentinel-less trainers trace the exact legacy signature.
        lr = jnp.asarray(self.sparse_opt.lr if lr is None else lr, jnp.float32)
        if self.sentinel is None:
            return self._train_step(state, batch, lr)
        return self._train_step(state, batch, lr, self._guard_or_init(guard))

    def train_steps(self, state: TrainState, batches,
                    lr: Optional[float] = None, guard=None):
        """Run K train steps in ONE device dispatch (`lax.scan`).

        `batches` is either a list/tuple of K same-shape batch dicts
        (stacked on the spot via `stack_batches`) or an already-stacked
        pytree with a leading [K, ...] axis — pre-stack and `device_put`
        it when the transfer should overlap compute. Returns
        (final_state, metrics) with metric leaves stacked [K] (per-step
        loss/accuracy, so streamed metric accumulation sees every step,
        same as K `train_step` calls). The input state is donated.

        Semantics are exactly K sequential `train_step` calls — table
        insertion/admission/eviction counters and the global step advance
        per inner step. Run checkpoint/eval/maintain() at K-step
        boundaries (they are host-side and see only the returned state).
        Compiles once per K; see docs/perf.md for the K-curve."""
        if isinstance(batches, (list, tuple)):
            batches = stack_batches(batches)
        lr = jnp.asarray(self.sparse_opt.lr if lr is None else lr, jnp.float32)
        if self.sentinel is None:
            return self._train_steps(state, batches, lr)
        return self._train_steps(state, batches, lr,
                                 self._guard_or_init(guard))

    def train_step_accum(self, state: TrainState, batch, accum_steps: int,
                         lr: Optional[float] = None, guard=None):
        """Micro-batched step: batch leaves [A*B, ...] are split into A
        micro-batches; sparse tables update per micro-batch, dense params
        once — DeepRec's micro_batch_num semantics with scan instead of graph
        duplication. Cuts activation memory A× for large effective batches."""
        def split(x):
            return x.reshape(accum_steps, x.shape[0] // accum_steps,
                             *x.shape[1:])

        lr = jnp.asarray(self.sparse_opt.lr if lr is None else lr, jnp.float32)
        if self.sentinel is None:
            return self._train_step_accum(state, jax.tree.map(split, batch),
                                          lr)
        return self._train_step_accum(state, jax.tree.map(split, batch), lr,
                                      self._guard_or_init(guard))

    def eval_step(self, state: TrainState, batch):
        return self._eval_step(state, batch)

    def evict_tables(self, state: TrainState, step=None) -> TrainState:
        """Apply each table's eviction policies (TTL / L2) and rebuild —
        run at checkpoint cadence like the reference
        (docs/docs_en/Feature-Eviction.md). No-op for tables without
        eviction options."""
        step = jnp.asarray(int(state.step) if step is None else step, jnp.int32)
        tables = dict(state.tables)
        for bname, b in self.bundles.items():
            ev = b.table.cfg.ev
            if ev.global_step_evict is None and ev.l2_weight_evict is None:
                continue
            tables[bname] = self._evict_bundle(b, tables[bname], step)
        return TrainState(step=state.step, tables=tables, dense=state.dense,
                          opt_state=state.opt_state)

    def _slot_fills(self, b: Bundle):
        """Optimizer slot init values, so evicted rows are reborn correctly."""
        return tuple(
            (name, init)
            for name, (_, init) in self.sparse_opt.slot_specs(b.table.cfg.dim).items()
        )

    def _evict_bundle(self, b: Bundle, ts, step):
        fills = self._slot_fills(b)
        fn = lambda s: b.table.evict(s, step, slot_fills=fills)
        if b.stacked:
            return jax.vmap(fn)(ts)
        return fn(ts)

    # --------------------------------------------- capacity management

    def _bundle_lead_dims(self, b: Bundle) -> Tuple[int, ...]:
        """Leading axes of this bundle's state arrays before [C, ...]:
        (T,) for stacked groups, () for single tables. ShardedTrainer adds
        the shard axis."""
        return (len(b.features),) if b.stacked else ()

    def _multi_tier_for(self, b: Bundle, idx: Tuple[int, ...]):
        """Lazily build one MultiTierTable per (bundle, member/shard) —
        each holds its own host KV store."""
        from deeprec_tpu.embedding.multi_tier import MultiTierTable

        if not hasattr(self, "_tiers"):
            self._tiers = {}
        key = (b.name, idx)
        if key not in self._tiers:
            # Per-member store paths: every grouped table / shard owns its
            # own disk log — a shared path would interleave members' rows
            # in one log and let each member's index save clobber the rest.
            base = b.table.cfg.ev.storage.storage_path
            member_path = (
                base + "_m" + "_".join(map(str, idx)) if base and idx
                else base
            )
            self._tiers[key] = MultiTierTable(
                b.table, slot_fills=self._slot_fills(b),
                storage_path=member_path,
            )
        return self._tiers[key]

    @staticmethod
    def _state_bytes(ts) -> int:
        return sum(
            a.size * a.dtype.itemsize for a in jax.tree.leaves(ts)
        )

    # ------------------------------------------- unique-budget telemetry

    def _bundle_dedup_counters(self, ts):
        """Host-read (unique, ids, overflow) totals of one bundle's state,
        summed over every leading axis (grouped tables × shards)."""
        import numpy as np

        return (
            int(np.sum(np.asarray(jax.device_get(ts.dedup_unique)))),
            int(np.sum(np.asarray(jax.device_get(ts.dedup_ids)))),
            int(np.sum(np.asarray(jax.device_get(ts.dedup_overflow)))),
        )

    def _per_shard_stats(self, b: Bundle, member_ts):
        """Per-mesh-position owner-load breakdown of one member table, or
        None when there is no shard axis (the base trainer). ShardedTrainer
        overrides — the counters themselves accumulate in
        ShardedTable.resolve."""
        return None

    def dedup_stats(self, state: TrainState) -> Dict[str, Dict[str, float]]:
        """Per-TABLE dedup telemetry since the last counter reset:
        `unique_fraction` (budgeted uniques + overflow over id positions —
        the quantity the auto budget tracks) and `dedup_overflow`. Stacked
        bundles report each member table under its own feature name.

        Sharded trainers additionally report `per_shard` per table — the
        owner-unique/arrival counts and modeled exchange bytes of every
        mesh position plus their max/mean imbalance (ops/traffic.py) — so
        exchange skew is observable from a live TrainState without
        running a bench."""
        import numpy as np

        out: Dict[str, Dict[str, float]] = {}
        for bname, b in self.bundles.items():
            ts = state.tables[bname]
            for k, f in enumerate(b.features):
                member = (
                    jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
                )
                uniq, ids, ovf = self._bundle_dedup_counters(member)
                out[fcol.resolve_table_name(f)] = {
                    "unique_fraction": (
                        round((uniq + ovf) / ids, 4) if ids else None
                    ),
                    "dedup_overflow": ovf,
                }
                per_shard = self._per_shard_stats(b, member)
                if per_shard is not None:
                    out[fcol.resolve_table_name(f)]["per_shard"] = per_shard
                if not b.stacked:
                    break  # shared-table bundles hold one merged counter
        self._publish_dedup_obs(out)
        return out

    @staticmethod
    def _publish_dedup_obs(stats: Dict[str, Dict]) -> None:
        """Mirror the dedup/per-shard telemetry into the obs plane:
        per-table unique-fraction + overflow gauges, and — for sharded
        trainers — the per-shard exchange-bytes series plus the max/mean
        imbalance gauge whose windowed SLOPE is the drift signal
        Placement v2's replan cadence keys off. Values are the host ints
        this method already paid the device_get for; labels (table name,
        shard index) are bounded sets."""
        from deeprec_tpu.obs import metrics as obs_metrics

        if not obs_metrics.metrics_enabled():
            return
        reg = obs_metrics.default_registry()
        for tname, rec in stats.items():
            lab = {"table": tname}
            if rec.get("unique_fraction") is not None:
                reg.gauge("deeprec_dedup_unique_fraction",
                          "budgeted uniques + overflow over id positions",
                          lab).set(rec["unique_fraction"])
            reg.gauge("deeprec_dedup_overflow",
                      "ids past the unique budget since last reset",
                      lab).set(rec.get("dedup_overflow") or 0)
            ps = rec.get("per_shard")
            if not ps:
                continue
            reg.gauge("deeprec_shard_imbalance",
                      "max/mean per-shard exchange-bytes imbalance",
                      lab).set(ps["imbalance"])
            for i, xb in enumerate(ps.get("exchange_bytes", ())):
                reg.gauge("deeprec_shard_exchange_bytes",
                          "modeled exchange bytes per mesh position",
                          {"table": tname, "shard": str(i)}).set(xb)

    def update_budgets(
        self, state: TrainState, *, slack: float = 1.5, ema: float = 0.5
    ) -> Tuple[TrainState, Dict[str, Dict[str, float]]]:
        """Fold the per-table dedup counters into the auto-budget EMA,
        derive each "auto" bundle's budget fraction (slack x EMA, rounded
        UP onto a 1/16 grid so drift inside a bucket never recompiles),
        and reset the counters. Host-side, call at maintain/log cadence —
        a changed bucket rebuilds the jitted steps (budgets resolve at
        trace time; a cached executable would silently keep its old unique
        sizes) so the next dispatch recompiles once. Returns (new_state,
        report) with per-bundle unique_fraction / dedup_overflow /
        unique_budget_fraction."""
        from deeprec_tpu.ops import dedup

        tables = dict(state.tables)
        report: Dict[str, Dict[str, float]] = {}
        changed = False
        for bname, b in self.bundles.items():
            ts = tables[bname]
            uniq, ids, ovf = self._bundle_dedup_counters(ts)
            rep: Dict[str, float] = {"dedup_overflow": ovf}
            if ids > 0:
                # Overflowed ids are uniques the budget refused — count
                # them so a too-tight budget widens instead of latching.
                frac = min(1.0, (uniq + ovf) / ids)
                rep["unique_fraction"] = round(frac, 4)
                old = self._unique_ema.get(bname)
                self._unique_ema[bname] = (
                    frac if old is None else (1.0 - ema) * old + ema * frac
                )
                if self._budget_modes.get(bname) == "auto":
                    new_frac = dedup.auto_budget_fraction(
                        self._unique_ema[bname], slack=slack
                    )
                    changed |= self._auto_frac.get(bname) != new_frac
                    self._auto_frac[bname] = new_frac
            if bname in self._auto_frac:
                rep["unique_budget_fraction"] = self._auto_frac[bname]
            # Reset via *0 so sharded leaves keep their placement. The
            # owner-load telemetry shares the window semantics: stats read
            # since-last-reset, bench windows bracket with update_budgets.
            tables[bname] = ts.replace(
                dedup_unique=ts.dedup_unique * 0,
                dedup_ids=ts.dedup_ids * 0,
                dedup_overflow=ts.dedup_overflow * 0,
                owner_arrivals=ts.owner_arrivals * 0,
                owner_unique=ts.owner_unique * 0,
            )
            report[bname] = rep
        if changed:
            self._make_jits()
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    def update_placement(
        self, state: TrainState, **kw
    ) -> Tuple[TrainState, Dict[str, Dict[str, float]]]:
        """Recompute the skew-aware shard placement from live counters and
        re-shard tables whose plan changed (parallel/placement.py). The
        base trainer has no shard axis — placement is meaningless, so this
        is a no-op; ShardedTrainer implements it and maintain() runs it
        (through the maybe_replan drift gate) next to update_budgets when
        the trainer was built with placement="plan"."""
        return state, {}

    def maybe_replan(
        self, state: TrainState
    ) -> Tuple[TrainState, Dict[str, Dict[str, float]]]:
        """Drift-driven replan gate: run the placer only when the live
        per-shard imbalance telemetry says the key distribution moved AND
        the modeled gain amortizes the migration. No shard axis on the
        base trainer — no-op; ShardedTrainer implements."""
        return state, {}

    def maintain(
        self,
        state: TrainState,
        *,
        grow_threshold: float = 0.85,
        max_capacity: Optional[int] = None,
        hbm_budget_bytes: Optional[int] = None,
        step: Optional[int] = None,
        tier_async: bool = False,
    ) -> Tuple[TrainState, Dict[str, Dict[str, float]]]:
        """Close the capacity loop DeepRec's tables close implicitly
        (embedding_var.h:142 LookupOrCreateKey never refuses a key): consume
        each table's insert_fails / occupancy signals and act — demote cold
        rows to the host tier (storage_type=HBM_DRAM), else grow the table.
        Host-side; call at log/checkpoint cadence, NOT per step. Growth
        recompiles downstream jits once per new capacity.

        Returns (new_state, report) where report[bundle] carries occupancy,
        insert_fails, and what action was taken. max_capacity is the cap PER
        TABLE as this trainer shards it (for ShardedTrainer: the global cap;
        it is divided by the shard count internally); non-power-of-two caps
        round down.

        hbm_budget_bytes bounds the TOTAL device bytes of all table state:
        when a needed growth would exceed it, the bundle is auto-tiered —
        cold rows demote to the host store instead of the table growing.
        This is the automated device-placement decision (the reference
        places oversized EVs on CPU by hand; DeepRec multi_tier_storage.h).

        tier_async=True overlaps each member tier's HostKV/DiskKV IO with
        the next dispatches (MultiTierTable.sync_async): maintain() pays
        only the device-side extraction, promotions found in the
        background land at the NEXT maintain() boundary. Capacity-
        pressure syncs (hbm_budget_bytes force path) stay synchronous.
        """
        import numpy as np

        step = int(state.step) if step is None else int(step)
        # Placement BEFORE update_budgets: the replanner wants the
        # window's owner-load counters, which update_budgets resets.
        # maybe_replan is the drift gate — the placer itself runs only
        # when the windowed imbalance telemetry breaches the ReplanConfig
        # trigger and the modeled gain amortizes the migration.
        placement_report = {}
        if getattr(self, "placement", "uniform") == "plan":
            state, placement_report = self.maybe_replan(state)
        # Dedup telemetry: fold counters into the auto-budget EMA,
        # reset them, and carry the per-bundle stats into the report.
        state, dedup_report = self.update_budgets(state)
        total_bytes = (
            sum(self._state_bytes(ts) for ts in state.tables.values())
            if hbm_budget_bytes
            else 0
        )
        if max_capacity:
            # largest power of two <= cap (capacities must be powers of two)
            max_capacity = 1 << (int(max_capacity).bit_length() - 1)
        tables = dict(state.tables)
        report: Dict[str, Dict[str, float]] = {}
        for bname, b in self.bundles.items():
            ts = tables[bname]
            lead = self._bundle_lead_dims(b)
            C = b.table.cfg.capacity
            # Member states: iterate every leading index (tables × shards).
            idxs = list(np.ndindex(*lead)) if lead else [()]
            members = [
                jax.tree.map(lambda a, i=i: a[i] if i else a, ts)
                for i in idxs
            ]
            # Row hygiene (guard/rows.py): rows whose norm exploded past
            # the quantile bound re-initialize HERE, before occupancy /
            # growth read the state — a hot poisoned id must not
            # contaminate the table between checkpoints, and must never
            # trigger a growth it doesn't deserve.
            rows_reinit = 0
            sen = getattr(self, "sentinel", None)
            if sen is not None and sen.row_evict_quantile is not None:
                from deeprec_tpu.guard import rows as guard_rows

                fills = self._slot_fills(b)
                for mi, m in enumerate(members):
                    members[mi], n_bad = guard_rows.anomaly_evict(
                        b.table, m, sen.row_evict_quantile,
                        sen.row_evict_factor, fills,
                    )
                    rows_reinit += n_bad
                if rows_reinit:
                    ts = self._restack(members, lead)
                    from deeprec_tpu.obs import metrics as _obs_metrics

                    if _obs_metrics.metrics_enabled():
                        _obs_metrics.default_registry().counter(
                            "deeprec_guard_rows_reinit",
                            "anomalous table rows re-initialized by "
                            "maintain() row hygiene",
                            {"table": bname},
                        ).inc(rows_reinit)
            occ = max(int(b.table.size(m)) for m in members) / C
            fails_each = [int(m.insert_fails) for m in members]
            fails = sum(fails_each)
            rep = {"occupancy": occ, "insert_fails": fails, "capacity": C}
            if rows_reinit:
                rep["rows_reinit"] = rows_reinit
            rep.update(dedup_report.get(bname, {}))
            if bname in placement_report:
                rep["placement"] = placement_report[bname]
            multi_tier = b.table.cfg.ev.storage.storage_type.value in (
                "hbm_dram", "hbm_dram_ssd"
            )
            if multi_tier:
                members, demoted, promoted = self._tier_sync(
                    b, idxs, members, step, tier_async=tier_async
                )
                rep.update(demoted=demoted, promoted=promoted)
                ts = self._restack(members, lead)
            elif fails > 0 or occ > grow_threshold:
                # Size by the WORST member (each member has its own slots);
                # summing across shards would overprovision every shard.
                worst = max(fails_each)
                new_c = C * 2
                while worst > 0 and new_c < (worst + occ * C) * 2:
                    new_c *= 2
                if max_capacity:
                    new_c = min(new_c, max_capacity)
                bundle_bytes = self._state_bytes(ts)
                growth_bytes = bundle_bytes * (new_c // C - 1)
                if (
                    hbm_budget_bytes
                    and total_bytes + growth_bytes > hbm_budget_bytes
                ):
                    # Budget exceeded: auto-place on the host tier instead
                    # of growing — demote cold rows, keep capacity fixed.
                    # force=True: pressure may come from probe clustering
                    # below the high watermark; the tier must still act
                    # (demote to the low mark, or at least rebuild to heal
                    # chains and reset insert_fails).
                    members, demoted, promoted = self._tier_sync(
                        b, idxs, members, step, force=True
                    )
                    rep.update(auto_tiered=True, demoted=demoted,
                               promoted=promoted)
                    ts = self._restack(members, lead)
                elif new_c > C:
                    fills = self._slot_fills(b)
                    members = [
                        b.table.grow(m, new_c, slot_fills=fills)
                        for m in members
                    ]
                    self._set_bundle_capacity(b, new_c)
                    rep["grew_to"] = new_c
                    total_bytes += growth_bytes
                    ts = self._restack(members, lead)
            tables[bname] = ts
            report[bname] = rep
        pager = getattr(self, "_tier_pager", None)
        if pager is not None:
            # The demotes above retired the pump's in-flight gathers and
            # may have demoted rows the staged batches are about to look
            # up — re-probe the pipeline window so the next folds still
            # land before those lookups.
            pager.requeue_recent()
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    def _tier_sync(self, b: Bundle, idxs, members, step: int,
                   force: bool = False, tier_async: bool = False):
        """Run the host-tier sync over every member state; returns
        (members, total_demoted, total_promoted). tier_async=True routes
        through MultiTierTable.sync_async — the HostKV/DiskKV IO of every
        member overlaps the next dispatches, promotions land at the next
        maintain() boundary. Capacity-pressure syncs (force=True) stay
        synchronous: the caller needs the healed table NOW."""
        demoted = promoted = 0
        members = list(members)
        for k, (i, m) in enumerate(zip(idxs, members)):
            mt = self._multi_tier_for(b, i)
            if tier_async and not force:
                m, stats = mt.sync_async(m, step)
            else:
                m, stats = mt.sync(m, step, force=force)
            members[k] = m
            demoted += stats.demoted
            promoted += stats.promoted
        return members, demoted, promoted

    def tier_stall_ms(self) -> float:
        """Accumulated caller-side multi-tier sync stall across every
        member tier (bench.py `sync_stall_ms` accounting)."""
        return sum(
            mt.sync_stall_ms for mt in getattr(self, "_tiers", {}).values()
        )

    # ------------------------------------------------ overlapped tier paging

    def enable_tier_paging(self, *, depth: int = 4, chunk: int = 256,
                           max_pending: int = 8192):
        """Turn on demand-driven tier paging: a background `TierPrefetcher`
        probes each staged batch's ids (Prefetcher `peek`, before
        `device_put`) against every multi-tier member's host/disk key
        indexes and gathers resident packed rows off the training thread;
        `fold_tier_prefetch(state)` folds them back into the device tables
        at dispatch boundaries through one fixed-chunk compiled promote
        program. Call BEFORE `stage()` — the pager taps the pipeline there.
        Returns the pager (close() it when the run ends; the thread is a
        daemon either way). docs/multi-tier-storage.md#overlapped-tier-paging.

        chunk: fold chunk size — rounded up to a power of two by
        `fold_candidates`, one compile per (table, chunk) then 0
        steady-state compiles."""
        if hasattr(self, "num_shards"):
            # Sharded multi-tier is pinned to uniform routing
            # (docs/placement.md); paging the per-shard members from the
            # base pump needs shard-aware id routing — not wired yet.
            raise NotImplementedError(
                "tier paging is wired for the base Trainer; sharded "
                "multi-tier runs keep maintain(tier_async=True)"
            )
        from deeprec_tpu.embedding.tier_prefetch import TierPrefetcher

        specs = []
        for bname, b in self.bundles.items():
            if b.table.cfg.ev.storage.storage_type.value not in (
                "hbm_dram", "hbm_dram_ssd"
            ):
                continue
            if b.stacked:
                specs.extend(
                    ((bname, (k,)), (f.name,))
                    for k, f in enumerate(b.features)
                )
            else:
                specs.append(
                    ((bname, ()), tuple(f.name for f in b.features))
                )
        if not specs:
            raise ValueError(
                "no multi-tier bundle (storage_type hbm_dram / "
                "hbm_dram_ssd) — nothing to page"
            )

        def extract(batch, specs=tuple(specs)):
            import numpy as np

            out = {}
            for key, names in specs:
                arrs = [
                    np.asarray(batch[n]).reshape(-1)
                    for n in names if n in batch
                ]
                if arrs:
                    out[key] = (
                        np.concatenate(arrs) if len(arrs) > 1 else arrs[0]
                    )
            return out

        self._tier_chunk = int(chunk)
        # resolve via _tiers.get, never _multi_tier_for: the pump must not
        # CREATE tiers (a member that never demoted has nothing resident).
        self._tier_pager = TierPrefetcher(
            resolve=lambda key: getattr(self, "_tiers", {}).get(key),
            extract=extract, depth=depth, max_pending=max_pending,
        )
        return self._tier_pager

    def warm_tier_folds(self, state: TrainState) -> None:
        """Pre-compile every multi-tier member's fixed-chunk fold program
        (an all-sentinel no-op fold per member). Call at the end of a
        warmup phase: the steady-state window then pays zero fold
        compiles even when the first demote lands inside it."""
        import numpy as np

        chunk = getattr(self, "_tier_chunk", 256)
        for bname, b in self.bundles.items():
            if b.table.cfg.ev.storage.storage_type.value not in (
                "hbm_dram", "hbm_dram_ssd"
            ):
                continue
            ts = state.tables[bname]
            lead = self._bundle_lead_dims(b)
            idxs = list(np.ndindex(*lead)) if lead else [()]
            for i in idxs:
                member = jax.tree.map(lambda a, i=i: a[i] if i else a, ts)
                self._multi_tier_for(b, i).warm_fold(member, chunk=chunk)

    def fold_tier_prefetch(self, state: TrainState):
        """Dispatch-boundary half of tier paging: fold every buffered
        candidate package into its member table (revalidated against
        current device freq — a row that trained past its tier copy is
        dropped to the retry set, never clobbered). Host-side, call where
        you'd call maintain() but at a finer cadence (every K-step
        dispatch is fine: with nothing buffered it is two dict reads).
        Returns (new_state, report) with per-bundle folded/dropped counts;
        `state` comes back unchanged when nothing folds."""
        import numpy as np

        pager = getattr(self, "_tier_pager", None)
        if pager is None:
            return state, {}
        keys = pager.pending_keys()
        if not keys:
            return state, {}
        by_bundle: Dict[str, list] = {}
        for key in keys:
            by_bundle.setdefault(key[0], []).append(key)
        tables = dict(state.tables)
        report: Dict[str, Dict[str, int]] = {}
        changed = False
        for bname, bkeys in by_bundle.items():
            b = self.bundles.get(bname)
            if b is None:
                continue
            ts = tables[bname]
            lead = self._bundle_lead_dims(b)
            idxs = list(np.ndindex(*lead)) if lead else [()]
            members = [
                jax.tree.map(lambda a, i=i: a[i] if i else a, ts)
                for i in idxs
            ]
            folded = dropped = 0
            touched = False
            for key in bkeys:
                idx = key[1]
                if idx not in idxs:
                    continue
                cand = pager.take(key)
                if cand is None:
                    continue
                mt = self._multi_tier_for(b, idx)
                k = idxs.index(idx)
                members[k], f, d = mt.fold_candidates(
                    members[k], cand,
                    chunk=getattr(self, "_tier_chunk", 256),
                )
                folded += f
                dropped += d
                touched = touched or bool(f)
            if touched:
                tables[bname] = self._restack(members, lead)
                changed = True
            if folded or dropped:
                report[bname] = {"folded": folded, "dropped": dropped}
        if not changed:
            return state, report
        return (
            TrainState(step=state.step, tables=tables, dense=state.dense,
                       opt_state=state.opt_state),
            report,
        )

    def tier_paging_stats(self) -> Dict[str, float]:
        """Pager + fold accounting for bench/eval reports: pump drop/error
        counters plus the per-tier fold totals (rows, bytes, training-
        thread stall ms — `fold_stall_ms` is the paging analog of the
        `sync_stall_ms` that `tier_stall_ms()` sums)."""
        pager = getattr(self, "_tier_pager", None)
        out: Dict[str, float] = dict(pager.stats()) if pager else {}
        tiers = getattr(self, "_tiers", {}).values()
        out["folded_rows"] = sum(mt.folded_rows for mt in tiers)
        out["fold_bytes"] = sum(mt.fold_bytes for mt in tiers)
        out["fold_stall_ms"] = sum(mt.fold_stall_ms for mt in tiers)
        return out

    def close_tier_paging(self) -> None:
        """Stop the pager pump (safe mid-gather — probes are read-only)."""
        pager = getattr(self, "_tier_pager", None)
        if pager is not None:
            pager.close()
            self._tier_pager = None

    def _restack(self, members, lead):
        """Reassemble member states into the bundle's stacked layout."""
        if not lead:
            return members[0]
        flat = [jax.tree.flatten(m)[0] for m in members]
        treedef = jax.tree.structure(members[0])
        stacked = []
        for leaf_i in range(len(flat[0])):
            arrs = jnp.stack([f[leaf_i] for f in flat])
            stacked.append(arrs.reshape(lead + arrs.shape[1:]))
        return jax.tree.unflatten(treedef, stacked)

    def _set_bundle_capacity(self, b: Bundle, new_c: int) -> None:
        """Point the bundle at the grown capacity (invalidates jit caches
        keyed on the old config — one recompile per growth event)."""
        b.table = EmbeddingTable(
            dataclasses.replace(b.table.cfg, capacity=new_c)
        )

    def evaluate(self, state: TrainState, batches) -> Dict[str, float]:
        """Streamed AUC/loss over an iterable of batches. Multi-task models
        report one AUC per task (labels under 'label_<task>')."""
        aucs: Dict[str, M.AucState] = {}
        total, n = 0.0, 0
        upd = _jit_auc_update
        for batch in batches:
            loss, probs = self.eval_step(state, batch)
            task_probs = probs if isinstance(probs, dict) else {"": probs}
            for task, p in task_probs.items():
                label = batch[f"label_{task}"] if task else batch["label"]
                aucs.setdefault(task, M.AucState.create())
                aucs[task] = upd(aucs[task], p, label)
            total += float(loss)
            n += 1
        out = {"loss": total / max(n, 1)}
        for task, st in aucs.items():
            out[f"auc_{task}" if task else "auc"] = float(M.auc_compute(st))
        return out
