from deeprec_tpu.training.trainer import (
    ModelInputs,
    PipelineCarry,
    Trainer,
    TrainState,
    stack_batches,
)
from deeprec_tpu.training.metrics import (
    AucState,
    accuracy,
    auc_compute,
    auc_update,
    bce_loss,
)
