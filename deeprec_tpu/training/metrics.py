"""Streaming metrics. The benchmark harness asserts AUC/ACC scraped from
logs (reference: modelzoo/benchmark/*/log_process.py), so AUC must be
computable online without holding all predictions: histogram-based streaming
AUC (the same approach tf.metrics.auc uses, with fixed thresholds bins)."""
from __future__ import annotations

import jax.numpy as jnp
from flax import struct

NUM_BINS = 512


@struct.dataclass
class AucState:
    pos: jnp.ndarray  # [NUM_BINS] float32 — positive-label prob histogram
    neg: jnp.ndarray  # [NUM_BINS]

    @classmethod
    def create(cls) -> "AucState":
        z = jnp.zeros((NUM_BINS,), jnp.float32)
        return cls(pos=z, neg=z)


def auc_update(state: AucState, probs: jnp.ndarray, labels: jnp.ndarray) -> AucState:
    probs = probs.reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    bins = jnp.clip((probs * NUM_BINS).astype(jnp.int32), 0, NUM_BINS - 1)
    pos = state.pos.at[bins].add(labels)
    neg = state.neg.at[bins].add(1.0 - labels)
    return AucState(pos=pos, neg=neg)


def auc_compute(state: AucState) -> jnp.ndarray:
    """Probability a random positive outranks a random negative, from the
    histograms (ties get half credit)."""
    P = jnp.sum(state.pos)
    N = jnp.sum(state.neg)
    neg_below = jnp.cumsum(state.neg) - state.neg
    wins = jnp.sum(state.pos * neg_below) + 0.5 * jnp.sum(state.pos * state.neg)
    return jnp.where((P > 0) & (N > 0), wins / (P * N), 0.5)


def accuracy(probs: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    pred = (probs.reshape(-1) >= 0.5).astype(jnp.float32)
    return jnp.mean((pred == labels.reshape(-1).astype(jnp.float32)).astype(jnp.float32))


def bce_loss(logits: jnp.ndarray, labels: jnp.ndarray) -> jnp.ndarray:
    """Numerically stable sigmoid cross-entropy."""
    logits = logits.reshape(-1)
    labels = labels.reshape(-1).astype(jnp.float32)
    return jnp.mean(
        jnp.maximum(logits, 0.0) - logits * labels + jnp.log1p(jnp.exp(-jnp.abs(logits)))
    )
