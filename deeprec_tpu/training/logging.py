"""Training metrics logging — the summaries/observability analog.

DeepRec relies on TF summaries + log scraping (SURVEY.md §5). Here: a JSONL
metrics stream any dashboard can tail, plus the WorkQueue/table gauges the
reference exposes (queue size via WorkQueue.add_summary, EV size via
EVGetSize)."""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict


class MetricsLogger:
    """Append-only JSONL metrics: one record per call, wall-clock stamped."""

    def __init__(self, path: str):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self._f = open(path, "a", buffering=1)

    def log(self, step: int, **scalars: Any) -> None:
        rec = {"step": int(step), "time": time.time()}  # noqa: DRT002 — logging surface: deliberate scalar D2H at log cadence
        for k, v in scalars.items():
            try:
                rec[k] = float(v)  # noqa: DRT002 — logging surface, same contract as above
            except (TypeError, ValueError):
                rec[k] = v
        self._f.write(json.dumps(rec) + "\n")

    def close(self) -> None:
        self._f.close()


def table_gauges(trainer, state) -> Dict[str, int]:
    """Live table sizes + insert-failure counters (EVGetSize parity)."""
    out = {}
    for name, t in trainer.tables.items():
        ts = trainer.table_state(state, name)
        # sharded states carry a leading shard dim; sum over it
        occ = t.occupied(ts) if ts.keys.ndim == 1 else None
        if occ is not None:
            out[f"table_size/{name}"] = int(t.size(ts))
        else:
            import jax
            import jax.numpy as jnp

            sizes = jax.vmap(t.size)(ts)
            out[f"table_size/{name}"] = int(jnp.sum(sizes))
    return out
