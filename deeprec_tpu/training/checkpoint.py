"""Checkpointing: full + incremental saves of sparse tables and dense params.

Parity with DeepRec's EV checkpoint machinery (SURVEY.md §3.3):
  * Full save: per table, the compacted tensors keys/values/freqs/versions
    (+ optimizer slots and filter sketch) with partition offsets — the
    "9 parts" export of SaveV2(has_ev=true)
    (docs/docs_en/Embedding-Variable.md "Checkpoint",
    embedding_var_ckpt_data.cc). Non-admitted (filtered) keys are saved with
    their frequency so admission counters survive restore
    (TF_EV_SAVE_FILTERED_FEATURES behavior).
  * Incremental save: only rows dirtied since the last save — the IncrSave /
    IndicesIncrRecorder delta path (core/kernels/incr_save_restore_ops.h:43),
    used for fast PS failover and serving delta updates.
  * Restore: latest full checkpoint, then replay deltas in order
    (Incremental-Checkpoint.md:3-7). Keys are re-inserted by probing, so a
    checkpoint restores onto ANY topology — different mesh size or grown
    capacity — which is what elastic re-scaling needs (elastic_training.proto
    semantics without the gRPC choreography).

Format: a directory per step, numpy .npz per table plus dense.npz and a JSON
manifest. Host-side; runs at checkpoint cadence, not on the hot path.
"""
from __future__ import annotations

import json
import os
import re
import shutil
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.embedding.table import EmbeddingTable, TableState, empty_key
from deeprec_tpu.training.trainer import TrainState, Trainer
from deeprec_tpu.utils import hashing


# ----------------------------------------------------------- table export


def is_per_row(name: str) -> bool:
    """Checkpoint-array routing by NAME (never by shape, which is ambiguous):
    per-row arrays are compacted/partitioned; per-table arrays (CBF sketch,
    scalar optimizer slots) are carried whole."""
    if name in ("keys", "values", "freqs", "versions"):
        return True
    return name.startswith("slot:") and not name.startswith("slot:scalar/")


def export_table_arrays(
    table: EmbeddingTable, state_np: Dict[str, np.ndarray], only_dirty: bool
) -> Dict[str, np.ndarray]:
    """Compact one LOCAL table state (host numpy arrays) to its live rows."""
    cfg = table.cfg
    keys = state_np["keys"]
    occ = keys != empty_key(cfg)
    if only_dirty:
        occ = occ & state_np["dirty"]
    if (
        not cfg.ev.ckpt.save_filtered_features
        and cfg.ev.counter_filter is not None
        and cfg.ev.counter_filter.filter_freq > 0
    ):
        # CheckpointOption / TF_EV_SAVE_FILTERED_FEATURES=False: drop
        # sub-threshold keys at save time (admission counters restart).
        # COUNTER filter only: its admission counter IS the row freq. In
        # CBF mode sub-threshold keys never occupy rows (the counter lives
        # in the sketch), so every resident row is admitted and a row-freq
        # threshold would wrongly drop just-admitted keys.
        occ = occ & (state_np["freq"] >= cfg.ev.counter_filter.filter_freq)
    idx = np.nonzero(occ)[0]
    out = {
        "keys": keys[idx],
        "values": state_np["values"][idx],
        "freqs": state_np["freq"][idx],
        "versions": state_np["version"][idx],
    }
    for sname, arr in state_np.items():
        if sname.startswith("slot:"):
            out[sname] = arr[idx] if is_per_row(sname) else arr
    if state_np.get("bloom") is not None:
        out["bloom"] = state_np["bloom"]
    return out


def _to_host(x) -> np.ndarray:
    """Materialize an array on THIS host — including multi-host global
    arrays, whose shards are assembled across processes (shared-FS
    checkpointing: every process sees the full value, process 0 writes)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _state_to_np(ts: TableState) -> Dict[str, np.ndarray]:
    d = {
        "keys": _to_host(ts.keys),
        "values": _to_host(ts.values),
        "freq": _to_host(ts.freq),
        "version": _to_host(ts.version),
        "dirty": _to_host(ts.dirty),
    }
    for sname, arr in ts.slots.items():
        d["slot:" + sname] = _to_host(arr)
    if ts.bloom is not None:
        d["bloom"] = _to_host(ts.bloom)
    return d


def import_rows(
    table: EmbeddingTable,
    state: TableState,
    rows: Dict[str, np.ndarray],
    strict: bool = True,
) -> TableState:
    """Insert checkpointed rows into a (fresh or live) local table state."""
    n = rows["keys"].shape[0]
    if n == 0:
        if "bloom" in rows and state.bloom is not None:
            state = state.replace(bloom=jnp.asarray(rows["bloom"]))
        return state
    keys = jnp.asarray(rows["keys"])
    new_keys, slot_ix, created, failed = table._probe(
        state.keys, keys, jnp.ones((n,), bool)
    )
    if strict and bool(jnp.any(failed)):
        raise RuntimeError(
            f"table {table.cfg.name}: {int(jnp.sum(failed))} keys failed to "
            f"insert on restore — grow the capacity"
        )
    ix = jnp.where(slot_ix >= 0, slot_ix, state.capacity)
    values = state.values.at[ix].set(
        jnp.asarray(rows["values"]).astype(state.values.dtype), mode="drop"
    )
    freq = state.freq.at[ix].set(jnp.asarray(rows["freqs"]), mode="drop")
    version = state.version.at[ix].set(jnp.asarray(rows["versions"]), mode="drop")
    slots = dict(state.slots)
    for sname, arr in state.slots.items():
        key = "slot:" + sname
        if key not in rows:
            continue
        r = jnp.asarray(rows[key])
        if is_per_row(key):
            slots[sname] = arr.at[ix].set(r, mode="drop")
        else:
            slots[sname] = r
    bloom = state.bloom
    if "bloom" in rows and bloom is not None:
        bloom = jnp.asarray(rows["bloom"])
    return state.replace(
        keys=new_keys, values=values, freq=freq, version=version, slots=slots,
        bloom=bloom,
    )


# -------------------------------------------------------- checkpoint manager


def _tree_to_npz_dict(tree) -> Dict[str, np.ndarray]:
    leaves, _ = jax.tree_util.tree_flatten(tree)
    return {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}


def _tree_from_npz_dict(template, data) -> object:
    leaves, treedef = jax.tree_util.tree_flatten(template)
    new_leaves = [
        jnp.asarray(data[f"leaf_{i}"]).astype(l.dtype).reshape(l.shape)
        if hasattr(l, "dtype")
        else data[f"leaf_{i}"]
        for i, l in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


class CheckpointManager:
    """Save/restore for a Trainer (single-device or sharded).

    Layout:
        <dir>/full-<step>/manifest.json, dense.npz, table_<bundle>[_tK].npz
        <dir>/incr-<step>/...            (deltas since previous save)
    """

    def __init__(self, directory: str, trainer: Trainer, keep: int = 3):
        self.dir = directory
        self.trainer = trainer
        self.keep = keep
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------- helpers

    def _bundle_states(self, state: TrainState, bname: str) -> List[Tuple[str, Dict]]:
        """Split a (possibly stacked and/or sharded) bundle state into LOCAL
        per-table host states, tagged 'tK' for stacked member K. Shard dims
        are concatenated: rows from all shards merge into one export (the
        partition_offset records the split for forensics)."""
        b = self.trainer.bundles[bname]
        ts = state.tables[bname]
        out = []
        members = range(len(b.features)) if b.stacked else [None]
        for k in members:
            sub = jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
            out.append((f"t{k}" if k is not None else "t", _state_to_np(sub)))
        return out

    def _is_sharded(self) -> bool:
        return hasattr(self.trainer, "num_shards")

    def _export_bundle(self, state, bname, only_dirty) -> Dict[str, Dict[str, np.ndarray]]:
        from deeprec_tpu.embedding.table import empty_key

        b = self.trainer.bundles[bname]
        exports = {}
        for tag, np_state in self._bundle_states(state, bname):
            if self._is_sharded():
                # leading dim = shard axis: compact each shard, concatenate,
                # remember offsets (DeepRec's -partition_offset tensor)
                parts = []
                offsets = [0]
                N = np_state["keys"].shape[0]
                for s in range(N):
                    local = {k: v[s] for k, v in np_state.items()}
                    parts.append(export_table_arrays(b.table, local, only_dirty))
                    offsets.append(offsets[-1] + parts[-1]["keys"].shape[0])
                merged = {}
                for k in parts[0]:
                    if is_per_row(k):
                        merged[k] = np.concatenate([p[k] for p in parts])
                    elif k == "bloom":
                        # keep each shard's sketch: restoring onto the SAME
                        # shard count is then exact (sub-threshold admission
                        # counts survive); re-sharding falls back to a
                        # rebuild from row freqs (see _import_local)
                        merged["bloom_parts"] = np.stack([p[k] for p in parts])
                    else:  # per-table scalar slot: identical on all shards
                        merged[k] = parts[0][k]
                merged["partition_offset"] = np.asarray(offsets, np.int64)
                exports[tag] = merged
            else:
                exports[tag] = export_table_arrays(b.table, np_state, only_dirty)
            if only_dirty:
                # Deltas carry the FULL live-key set (keys only, compact):
                # restore prunes resurrected keys that were evicted between
                # saves — dirty rows alone cannot express an eviction.
                keys = np_state["keys"]
                occ = keys != empty_key(b.table.cfg)
                exports[tag]["live_keys"] = keys[occ]
        return exports

    def _clear_dirty(self, state: TrainState) -> TrainState:
        tables = {
            bname: ts.replace(dirty=jax.tree.map(jnp.zeros_like, ts.dirty))
            if not isinstance(ts, dict)
            else ts
            for bname, ts in state.tables.items()
        }
        return TrainState(
            step=state.step, tables=tables, dense=state.dense,
            opt_state=state.opt_state,
        )

    # ---------------------------------------------------------------- save

    def _is_writer(self) -> bool:
        """Multi-host: every process assembles the global arrays (shared-FS
        layout needs the files once), process 0 writes them.

        Memory model: saves gather each table to host RAM (a full
        process_allgather per save, incremental included) and multi-host
        restore materializes it on one device per process — correct up to
        host/device memory, which covers single-slice pods. A per-process
        shard-part file format (no global gather anywhere) is the
        pod-scale follow-up; see docs/STATUS-round2.md.
        """
        if jax.process_count() > 1 and not self._is_sharded():
            raise RuntimeError(
                "multi-process checkpointing requires a ShardedTrainer "
                "(a plain Trainer under jax.distributed has no global mesh "
                "to gather from / place onto)"
            )
        return jax.process_index() == 0

    @staticmethod
    def _sync(tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)

    def save(self, state: TrainState) -> Tuple[TrainState, str]:
        """Full checkpoint. Returns (state with dirty bits cleared, path).
        Multi-host safe: all processes participate in the gather, process 0
        writes, and nobody returns before the manifest exists."""
        step = int(state.step)
        path = os.path.join(self.dir, f"full-{step}")
        write = self._is_writer()
        if write:
            os.makedirs(path, exist_ok=True)
        for bname in self.trainer.bundles:
            for tag, arrays in self._export_bundle(state, bname, False).items():
                if write:
                    np.savez(
                        os.path.join(path, f"table_{bname}_{tag}.npz"), **arrays
                    )
        if write:
            np.savez(os.path.join(path, "dense.npz"),
                     **_tree_to_npz_dict(state.dense))
            np.savez(os.path.join(path, "opt.npz"),
                     **_tree_to_npz_dict(state.opt_state))
            manifest = {
                "step": step,
                "kind": "full",
                "bundles": {
                    bn: [f.name for f in b.features]
                    for bn, b in self.trainer.bundles.items()
                },
            }
            with open(os.path.join(path, "manifest.json"), "w") as f:
                json.dump(manifest, f)
            self._gc()
        self._sync(f"ckpt-full-{step}")
        return self._clear_dirty(state), path

    def save_incremental(self, state: TrainState) -> Tuple[TrainState, str]:
        """Delta checkpoint: rows touched since the previous (full or incr)
        save. The consumer replays deltas over the latest full save."""
        step = int(state.step)
        path = os.path.join(self.dir, f"incr-{step}")
        write = self._is_writer()
        if write:
            os.makedirs(path, exist_ok=True)
        for bname in self.trainer.bundles:
            for tag, arrays in self._export_bundle(state, bname, True).items():
                if write:
                    np.savez(
                        os.path.join(path, f"table_{bname}_{tag}.npz"), **arrays
                    )
        if write:
            np.savez(os.path.join(path, "dense.npz"),
                     **_tree_to_npz_dict(state.dense))
            np.savez(os.path.join(path, "opt.npz"),
                     **_tree_to_npz_dict(state.opt_state))
            with open(os.path.join(path, "manifest.json"), "w") as f:
                json.dump({"step": step, "kind": "incr"}, f)
        self._sync(f"ckpt-incr-{step}")
        return self._clear_dirty(state), path

    # ------------------------------------------------------------- restore

    def _list(self, kind: str) -> List[int]:
        pat = re.compile(rf"^{kind}-(\d+)$")
        out = []
        for d in os.listdir(self.dir):
            m = pat.match(d)
            if m and os.path.exists(os.path.join(self.dir, d, "manifest.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_full(self) -> Optional[int]:
        fulls = self._list("full")
        return fulls[-1] if fulls else None

    def restore(self, template: Optional[TrainState] = None) -> TrainState:
        """Latest full checkpoint + all newer deltas, onto the trainer's
        CURRENT topology (mesh size / process count / capacity may all
        differ from save time — this is the elastic-rescale mechanism).
        Multi-host: every process replays the same files host-side, then
        the result is re-placed onto the global mesh."""
        full_step = self.latest_full()
        if full_step is None:
            raise FileNotFoundError(f"no full checkpoint under {self.dir}")
        state = template if template is not None else self.trainer.init(0)
        multi = jax.process_count() > 1
        if multi:
            # host-local replay: the import machinery indexes/reshapes
            # per-shard states, which global multi-host arrays cannot do
            state = jax.tree.map(lambda a: jnp.asarray(_to_host(a)), state)
        state = self._apply_ckpt(state, os.path.join(self.dir, f"full-{full_step}"),
                                 load_dense=True)
        for istep in [s for s in self._list("incr") if s > full_step]:
            state = self._apply_ckpt(
                state, os.path.join(self.dir, f"incr-{istep}"), load_dense=True
            )
            full_step = istep
        with open(os.path.join(self.dir, self._latest_dir(), "manifest.json")) as f:
            step = json.load(f)["step"]
        out = TrainState(
            step=jnp.asarray(step, jnp.int32),
            tables=state.tables,
            dense=state.dense,
            opt_state=state.opt_state,
        )
        if multi:
            out = self._place_on_mesh(out)
        return out

    def _place_on_mesh(self, state: TrainState) -> TrainState:
        """Re-place host-local restored state onto the trainer's global
        mesh (every process holds identical host values and contributes
        its addressable shards)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeprec_tpu.parallel.mesh import put_global

        if not self._is_sharded():  # unreachable: _is_writer() raises first
            raise RuntimeError("multi-process restore requires ShardedTrainer")
        mesh = self.trainer.mesh
        tables = {
            bname: jax.tree.map(
                lambda a, sh=NamedSharding(
                    mesh, self.trainer._table_spec(bname)
                ): put_global(a, sh),
                ts,
            )
            for bname, ts in state.tables.items()
        }
        repl = NamedSharding(mesh, P())
        return TrainState(
            step=put_global(state.step, repl),
            tables=tables,
            dense=jax.tree.map(lambda a: put_global(a, repl), state.dense),
            opt_state=jax.tree.map(
                lambda a: put_global(a, repl), state.opt_state
            ),
        )

    def _latest_dir(self) -> str:
        fulls = self._list("full")
        incrs = [s for s in self._list("incr") if s > fulls[-1]]
        return f"incr-{incrs[-1]}" if incrs else f"full-{fulls[-1]}"

    def _apply_ckpt(self, state: TrainState, path: str, load_dense: bool) -> TrainState:
        tables = dict(state.tables)
        for bname, b in self.trainer.bundles.items():
            ts = tables[bname]
            members = range(len(b.features)) if b.stacked else [None]
            new_members = []
            for k in members:
                tag = f"t{k}" if k is not None else "t"
                fpath = os.path.join(path, f"table_{bname}_{tag}.npz")
                sub = jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
                if os.path.exists(fpath):
                    rows = dict(np.load(fpath))
                    rows.pop("partition_offset", None)
                    live = rows.pop("live_keys", None)
                    sub = self._import_local(b.table, sub, rows)
                    if live is not None:
                        # delta semantics: anything absent from the delta's
                        # live set was evicted since the previous save
                        sub = self._prune_to_live(b, sub, live)
                new_members.append(sub)
            if b.stacked:
                ts = jax.tree.map(lambda *xs: jnp.stack(xs), *new_members)
            else:
                ts = new_members[0]
            tables[bname] = ts
        dense, opt_state = state.dense, state.opt_state
        if load_dense and os.path.exists(os.path.join(path, "dense.npz")):
            dense = _tree_from_npz_dict(state.dense, np.load(os.path.join(path, "dense.npz")))
        if load_dense and os.path.exists(os.path.join(path, "opt.npz")):
            opt_state = _tree_from_npz_dict(
                state.opt_state, np.load(os.path.join(path, "opt.npz"))
            )
        return TrainState(step=state.step, tables=tables, dense=dense,
                          opt_state=opt_state)

    def _prune_to_live(self, b, sub: TableState, live: np.ndarray) -> TableState:
        """Drop keys not in the delta's live set (evicted between saves) —
        rebuild-based, so probe chains heal and freed optimizer slot rows
        restart at the optimizer's init value."""
        fills = self.trainer._slot_fills(b)
        keys = np.asarray(sub.keys)
        if keys.ndim == 2:  # sharded: [N, C_local]
            keep = np.stack([np.isin(k, live) for k in keys])
            fn = jax.vmap(
                lambda s, kp: b.table.rebuild(s, keep=kp, slot_fills=fills)
            )
            return fn(sub, jnp.asarray(keep))
        return b.table.rebuild(
            sub, keep=jnp.asarray(np.isin(keys, live)), slot_fills=fills
        )

    def _import_local(self, table, sub: TableState, rows) -> TableState:
        """Import rows into a local (possibly shard-stacked) table state."""
        if self._is_sharded():
            N = self.trainer.num_shards
            owner = np.asarray(hashing.hash_shard(jnp.asarray(rows["keys"]), N))
            shards = []
            bloom_parts = rows.get("bloom_parts")
            same_topology = (
                bloom_parts is not None and bloom_parts.shape[0] == N
            )
            for s in range(N):
                sel = owner == s
                shard_rows = {
                    k: (v[sel] if is_per_row(k) else v)
                    for k, v in rows.items()
                    if k != "bloom_parts"
                }
                # Same shard count: each shard gets its own saved sketch back
                # (exact, sub-threshold counts included). Re-shard: rebuild
                # from owned rows' freqs — exact for admitted keys,
                # sub-threshold-only keys restart (documented semantic).
                # Never hand a summed global sketch to every shard: that
                # would inflate ~N× per save/restore cycle.
                shard_rows.pop("bloom", None)  # legacy merged-sketch files
                local = jax.tree.map(lambda a: a[s], sub)
                local = import_rows(table, local, shard_rows)
                cbf = table.cfg.ev.cbf_filter
                if cbf is not None and local.bloom is not None and same_topology:
                    local = local.replace(
                        bloom=jnp.asarray(bloom_parts[s], jnp.int32)
                    )
                elif cbf is not None and local.bloom is not None:
                    from deeprec_tpu.embedding import filters as _filters

                    bloom = jnp.zeros_like(local.bloom)
                    if shard_rows["keys"].shape[0] > 0:
                        bloom, _ = _filters.cbf_add(
                            cbf,
                            bloom,
                            jnp.asarray(shard_rows["keys"]),
                            jnp.asarray(shard_rows["freqs"], jnp.int32),
                        )
                    local = local.replace(bloom=bloom)
                shards.append(local)
            return jax.tree.map(lambda *xs: jnp.stack(xs), *shards)
        return import_rows(table, sub, rows)

    # ----------------------------------------------------------------- gc

    def _gc(self):
        fulls = self._list("full")
        for s in fulls[: -self.keep] if self.keep > 0 else []:
            shutil.rmtree(os.path.join(self.dir, f"full-{s}"), ignore_errors=True)
            for i in self._list("incr"):
                if i <= s:
                    shutil.rmtree(
                        os.path.join(self.dir, f"incr-{i}"), ignore_errors=True
                    )
