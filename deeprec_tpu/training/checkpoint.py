"""Checkpointing: full + incremental saves of sparse tables and dense params.

Parity with DeepRec's EV checkpoint machinery (SURVEY.md §3.3):
  * Full save: per table, the compacted tensors keys/values/freqs/versions
    (+ optimizer slots and filter sketch) with partition offsets — the
    "9 parts" export of SaveV2(has_ev=true)
    (docs/docs_en/Embedding-Variable.md "Checkpoint",
    embedding_var_ckpt_data.cc). Non-admitted (filtered) keys are saved with
    their frequency so admission counters survive restore
    (TF_EV_SAVE_FILTERED_FEATURES behavior).
  * Incremental save: only rows dirtied since the last save — the IncrSave /
    IndicesIncrRecorder delta path (core/kernels/incr_save_restore_ops.h:43),
    used for fast PS failover and serving delta updates.
  * Restore: latest full checkpoint, then replay deltas in order
    (Incremental-Checkpoint.md:3-7). Keys are re-inserted by probing, so a
    checkpoint restores onto ANY topology — different mesh size or grown
    capacity — which is what elastic re-scaling needs (elastic_training.proto
    semantics without the gRPC choreography).

Format: a directory per step, numpy .npz per table plus dense.npz and a JSON
manifest. Host-side; runs at checkpoint cadence, not on the hot path.

Off-the-hot-path choreography (round 9): every save is split into a STAGE
half (device work only: for incremental saves a jitted dirty-row compaction
so the device->host transfer scales with the dirty fraction, not capacity;
for full saves a donation-safe device snapshot) and a WRITE half (host
numpy materialization + npz IO + manifest-last commit). `save()` runs both
on the caller; `save_async()` / `save_incremental_async()` run the write
half on a background writer thread so the npz IO overlaps the next train
dispatches — at most one save in flight, `wait()` drains it, and a killed
writer leaves a manifest-less dir that `_list()` already ignores (the
manifest stays the completeness marker).

Checksummed chains (round 12): every npz array's digest is recorded in the
manifest at write time, delta manifests carry a `base` link to the save
they apply over, and `verify()`/`valid_chain()` replay the checks on the
read side. A corrupt or torn link is QUARANTINED (dir renamed to
`*.quarantined`) and consumers fall back to the longest valid chain
prefix; a quarantined step newer than the latest full escalates the
trainer's next save to full (`_effective_kind`), which re-anchors the
chain — the self-healing loop docs/fault-tolerance.md specifies.
"""
from __future__ import annotations

import dataclasses
import json
import logging
import os
import re
import shutil
import threading
import time
import zlib
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from deeprec_tpu.analysis.annotations import not_thread_safe
from deeprec_tpu.embedding.table import EmbeddingTable, TableState, empty_key
from deeprec_tpu.training.trainer import TrainState, Trainer
from deeprec_tpu.utils import hashing

_log = logging.getLogger(__name__)


class CheckpointCorrupt(RuntimeError):
    """A committed checkpoint dir failed integrity verification (missing
    file, torn manifest, digest mismatch). Consumers treat the dir as
    absent — quarantine + longest-valid-prefix fallback — rather than
    letting this escape into serving."""


def _array_digest(arr: np.ndarray) -> str:
    """Per-array content digest recorded in the manifest at write time and
    re-checked by `CheckpointManager.verify`. crc32 over the raw bytes plus
    dtype/shape: fast enough to run inline with the npz write (GB/s), and
    any payload bit-flip the zip layer misses still fails here."""
    a = np.ascontiguousarray(arr)
    crc = zlib.crc32(a.tobytes()) & 0xFFFFFFFF
    shape = "x".join(map(str, a.shape))
    return f"crc32:{crc:08x}:{a.dtype.str}:{shape}"


# ----------------------------------------------------------- table export


def is_per_row(name: str) -> bool:
    """Checkpoint-array routing by NAME (never by shape, which is ambiguous):
    per-row arrays are compacted/partitioned; per-table arrays (CBF sketch,
    scalar optimizer slots) are carried whole."""
    if name in ("keys", "values", "freqs", "versions"):
        return True
    return name.startswith("slot:") and not name.startswith("slot:scalar/")


def export_table_arrays(
    table: EmbeddingTable, state_np: Dict[str, np.ndarray], only_dirty: bool
) -> Dict[str, np.ndarray]:
    """Compact one LOCAL table state (host numpy arrays) to its live rows.

    The checkpoint format is LOGICAL rows — packed small-dim arrays
    (ops/packed.py) unpack via a free numpy reshape here, so checkpoints
    are portable across layout choices."""
    from deeprec_tpu.ops.packed import unpack_array

    cfg = table.cfg
    keys = state_np["keys"]
    C = keys.shape[0]
    state_np = {
        name: (
            unpack_array(arr, C)
            if name == "values"
            or (name.startswith("slot:") and is_per_row(name))
            else arr
        )
        for name, arr in state_np.items()
    }
    occ = keys != empty_key(cfg)
    if only_dirty:
        occ = occ & state_np["dirty"]
    if (
        not cfg.ev.ckpt.save_filtered_features
        and cfg.ev.counter_filter is not None
        and cfg.ev.counter_filter.filter_freq > 0
    ):
        # CheckpointOption / TF_EV_SAVE_FILTERED_FEATURES=False: drop
        # sub-threshold keys at save time (admission counters restart).
        # COUNTER filter only: its admission counter IS the row freq. In
        # CBF mode sub-threshold keys never occupy rows (the counter lives
        # in the sketch), so every resident row is admitted and a row-freq
        # threshold would wrongly drop just-admitted keys.
        occ = occ & (state_np["freq"] >= cfg.ev.counter_filter.filter_freq)
    idx = np.nonzero(occ)[0]
    out = {
        "keys": keys[idx],
        "values": state_np["values"][idx],
        "freqs": state_np["freq"][idx],
        "versions": state_np["version"][idx],
    }
    for sname, arr in state_np.items():
        if sname.startswith("slot:"):
            out[sname] = arr[idx] if is_per_row(sname) else arr
    if state_np.get("bloom") is not None:
        out["bloom"] = state_np["bloom"]
    return out


def _to_host(x) -> np.ndarray:
    """Materialize an array on THIS host — including multi-host global
    arrays, whose shards are assembled across processes (shared-FS
    checkpointing: every process sees the full value, process 0 writes)."""
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        from jax.experimental import multihost_utils

        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _meta_columns(meta_np: np.ndarray) -> Dict[str, np.ndarray]:
    """Unpack the fused [..., 3, C] metadata into the checkpoint's COLUMNAR
    freq/version/dirty arrays — the on-disk format is unchanged by the
    packed device layout, so old checkpoints restore as-is and new ones
    restore into old code."""
    from deeprec_tpu.embedding.table import META_DIRTY, META_FREQ, META_VERSION

    return {
        "freq": meta_np[..., META_FREQ, :],
        "version": meta_np[..., META_VERSION, :],
        "dirty": meta_np[..., META_DIRTY, :] != 0,
    }


def _state_to_np(ts: TableState) -> Dict[str, np.ndarray]:
    d = {
        "keys": _to_host(ts.keys),
        "values": _to_host(ts.values),
        **_meta_columns(_to_host(ts.meta)),
    }
    for sname, arr in ts.slots.items():
        d["slot:" + sname] = _to_host(arr)
    if ts.bloom is not None:
        d["bloom"] = _to_host(ts.bloom)
    return d


def import_rows(
    table: EmbeddingTable,
    state: TableState,
    rows: Dict[str, np.ndarray],
    strict: bool = True,
    bucket: bool = False,
    chunk: Optional[int] = None,
) -> TableState:
    """Insert checkpointed rows into a (fresh or live) local table state.

    bucket=True pads the row count to the next power of two before the
    probe/scatter: every distinct count is a distinct static shape, and
    delta replays at serving cadence (poll_updates) would otherwise bake
    a fresh XLA program per update. Padding keys hold the empty-key
    sentinel, which _probe treats as invalid — inert by construction.
    One-shot full restores skip it (each shape compiles once anyway, and
    padding would transiently copy the whole values array). Only PER-ROW
    arrays pad; per-table entries (scalar optimizer slots, bloom) pass
    through untouched.

    chunk=N (overrides bucket) imports in sequential fixed-size slices of
    exactly N rows (last slice padded): ONE static shape per table, ever.
    This is the zero-stall serving discipline — power-of-two bucketing
    still traces a fresh XLA program the first time each bucket size
    appears, and that trace holds the GIL for hundreds of ms while live
    requests wait. With a fixed chunk the program compiles once at
    startup/warmup and every later full reload or delta replay is pure
    cache-hit dispatch. Per-table entries (scalar slots, bloom) are
    whole-table values, identical in every slice, so re-applying them per
    slice is idempotent. Costs one full values-array copy per slice —
    pick a chunk that keeps the slice count small at your row scale.
    """
    n = rows["keys"].shape[0]
    if n == 0:
        if "bloom" in rows and state.bloom is not None:
            state = state.replace(bloom=jnp.asarray(rows["bloom"]))
        return state
    if chunk is not None and n > chunk:
        for off in range(0, n, chunk):
            sl = {
                k: (v[off:off + chunk] if is_per_row(k) else v)
                for k, v in rows.items()
            }
            state = import_rows(table, state, sl, strict=strict, chunk=chunk)
        return state
    m = chunk if chunk is not None else (
        (1 << (n - 1).bit_length()) if bucket else n
    )

    def _padded(k, a):
        per_row = k in ("keys", "values", "freqs", "versions") or (
            k.startswith("slot:") and is_per_row(k)
        )
        if m == n or not per_row:
            return a
        a = np.asarray(a)
        fill = empty_key(table.cfg) if k == "keys" else 0
        return np.concatenate(
            [a, np.full((m - n,) + a.shape[1:], fill, a.dtype)]
        )

    rows = {k: _padded(k, v) for k, v in rows.items()}
    from deeprec_tpu.embedding.table import probe_jit

    keys = jnp.asarray(rows["keys"])
    new_keys, slot_ix, created, failed = probe_jit(
        table, state.keys, keys, jnp.ones((m,), bool)
    )
    if strict and bool(jnp.any(failed)):
        raise RuntimeError(
            f"table {table.cfg.name}: {int(jnp.sum(failed))} keys failed to "
            f"insert on restore — grow the capacity"
        )
    from deeprec_tpu.ops.packed import scatter_rows_any

    ix = jnp.where(slot_ix >= 0, slot_ix, state.capacity)
    put_ix = jnp.where(slot_ix >= 0, slot_ix, -1)
    # Restored rows are LOGICAL; scatter_rows_any re-packs on the way in.
    # Exact restore for f32; bf16 values round stochastically (identity
    # for rows that came out of a bf16 table — already representable).
    # int8 serving residency quantizes ON IMPORT: checkpoints stay fp32
    # on disk, the per-row scale lands in TableState.qscale, and the
    # quantize ops run at the same fixed chunk shape as the scatter —
    # the zero-retrace delta-replay contract holds unchanged.
    val_rows = jnp.asarray(rows["values"], np.float32)
    qscale = state.qscale
    if getattr(table, "quantized", False):
        from deeprec_tpu.embedding.table import quantize_rows_int8

        val_rows, scale = quantize_rows_int8(val_rows)
        qscale = qscale.at[ix].set(scale, mode="drop")
    values = scatter_rows_any(
        state.values, put_ix, val_rows, state.capacity,
    )
    from deeprec_tpu.embedding.table import META_FREQ, META_VERSION

    meta = state.meta.at[META_FREQ, ix].set(
        jnp.asarray(rows["freqs"], jnp.int32), mode="drop"
    )
    meta = meta.at[META_VERSION, ix].set(
        jnp.asarray(rows["versions"], jnp.int32), mode="drop"
    )
    slots = dict(state.slots)
    for sname, arr in state.slots.items():
        key = "slot:" + sname
        if key not in rows:
            continue
        r = jnp.asarray(rows[key])
        if is_per_row(key):
            slots[sname] = scatter_rows_any(
                arr, put_ix, r.astype(jnp.float32), state.capacity
            )
        else:
            slots[sname] = r
    bloom = state.bloom
    if "bloom" in rows and bloom is not None:
        bloom = jnp.asarray(rows["bloom"])
    return state.replace(
        keys=new_keys, values=values, meta=meta, slots=slots, bloom=bloom,
        qscale=qscale,
    )


# ----------------------------------------- device-side dirty compaction

import functools as _ft

from deeprec_tpu.embedding.table import META_DIRTY, META_FREQ, META_VERSION


@_ft.partial(jax.jit, static_argnums=(0, 3))
def _rebuild_keep_jit(table, state: TableState, keep: jnp.ndarray,
                      slot_fills) -> TableState:
    """Jitted keep-mask rebuild for delta-replay pruning (_prune_to_live):
    compile-cached per (table, slot_fills, shapes) so serving-cadence
    replays never re-trace the probe loop."""
    return table.rebuild(state, keep=keep, slot_fills=slot_fills)


@_ft.partial(jax.jit, static_argnums=(0, 3))
def _rebuild_keep_sharded_jit(table, state: TableState, keep: jnp.ndarray,
                              slot_fills) -> TableState:
    return jax.vmap(
        lambda s, kp: table.rebuild(s, keep=kp, slot_fills=slot_fills)
    )(state, keep)


@_ft.partial(jax.jit, static_argnums=(1,))
def _dirty_count_jit(state: TableState, sentinel: int) -> jnp.ndarray:
    """Occupied-and-dirty row count of one LOCAL table state — the one
    scalar an incremental save reads from the device to size its
    compacted export."""
    occ = state.keys != jnp.asarray(sentinel, state.keys.dtype)
    return jnp.sum(occ & (state.meta[META_DIRTY] != 0)).astype(jnp.int32)


@_ft.partial(jax.jit, static_argnums=(1, 2))
def _compact_dirty_jit(
    state: TableState, sentinel: int, size: int
) -> Dict[str, jnp.ndarray]:
    """Compact one LOCAL table state's dirty rows ON DEVICE at static
    budget `size` (ops/compact.py prefix-sum compaction, ascending slot
    order — the same order the legacy host-side `np.nonzero` export
    produced, so files stay byte-identical after truncation).

    Everything returned is a FRESH buffer (jit outputs never alias
    non-donated inputs), so an async writer can materialize it while the
    training loop donates the live state through the next dispatches.
    Rows past the true dirty count are garbage the host truncates; the
    full key array rides along (`_all_keys`) for the delta's live set.
    """
    from deeprec_tpu.ops.compact import rank_compact
    from deeprec_tpu.ops.packed import gather_rows_any

    C = state.capacity
    sent = jnp.asarray(sentinel, state.keys.dtype)
    occ = state.keys != sent
    dirty = occ & (state.meta[META_DIRTY] != 0)
    idx, _, _ = rank_compact(dirty, size)
    safe = jnp.where(idx >= 0, idx, 0)
    out = {
        "keys": jnp.where(idx >= 0, state.keys[safe], sent),
        "values": gather_rows_any(state.values, safe, C),
        "freqs": state.meta[META_FREQ, safe],
        "versions": state.meta[META_VERSION, safe],
        "_all_keys": jnp.copy(state.keys),
    }
    for sname, arr in state.slots.items():
        key = "slot:" + sname
        out[key] = (
            gather_rows_any(arr, safe, C) if is_per_row(key)
            else jnp.copy(arr)
        )
    if state.bloom is not None:
        out["bloom"] = jnp.copy(state.bloom)
    return out


@jax.jit
def _copy_tree(tree):
    """Donation-safe device snapshot: fresh buffers for every leaf, so the
    async writer's host copies survive the training loop donating the
    originals (jnp.copy lowers to an XLA copy — outputs never alias)."""
    return jax.tree.map(jnp.copy, tree)


def _prefetch_host(tree) -> None:
    """Best-effort: start the device->host copies now so the writer
    thread's np.asarray calls find the bytes already on their way."""
    for leaf in jax.tree.leaves(tree):
        fn = getattr(leaf, "copy_to_host_async", None)
        if fn is not None:
            try:
                fn()
            except Exception:
                pass


def _tree_bytes(tree) -> int:
    return sum(
        leaf.size * leaf.dtype.itemsize
        for leaf in jax.tree.leaves(tree)
        if hasattr(leaf, "dtype")
    )


@dataclasses.dataclass
class _SavePlan:
    """Everything the WRITE half needs, detached from the live TrainState:
    device snapshots / compacted exports (fresh buffers), dataset positions
    snapshotted at stage time (the training loop advances readers while an
    async writer runs), and the manifest ingredients."""

    path: str
    kind: str
    step: int
    parts: bool
    write: bool
    state: Optional[TrainState]  # full saves: the (possibly snapshotted) state
    incr: Optional[Dict[str, Dict[str, list]]]  # incr: bundle->tag->[(sid, arrays, n)]
    dense: Any
    opt_state: Any
    positions: Optional[Dict[str, dict]]
    stats: Dict[str, float]
    # Per-bundle routing fingerprint at STAGE time (the async writer must
    # not read the live trainer's plans — a maintain() can adopt a new
    # plan while the write half runs). "uniform" = hash routing.
    routing: Dict[str, str] = dataclasses.field(default_factory=dict)


# -------------------------------------------------------- checkpoint manager


def _tree_to_npz_dict(tree) -> Dict[str, np.ndarray]:
    leaves, _ = jax.tree_util.tree_flatten(tree)
    return {f"leaf_{i}": np.asarray(l) for i, l in enumerate(leaves)}


def _tree_from_npz_dict(template, data) -> object:
    leaves, treedef = jax.tree_util.tree_flatten(template)
    new_leaves = [
        jnp.asarray(data[f"leaf_{i}"]).astype(l.dtype).reshape(l.shape)
        if hasattr(l, "dtype")
        else data[f"leaf_{i}"]
        for i, l in enumerate(leaves)
    ]
    return jax.tree_util.tree_unflatten(treedef, new_leaves)


class CheckpointManager:
    """Save/restore for a Trainer (single-device or sharded).

    Layout:
        <dir>/full-<step>/manifest.json, dense.npz, table_<bundle>[_tK].npz
        <dir>/incr-<step>/...            (deltas since previous save)
    """

    def __init__(self, directory: str, trainer: Trainer, keep: int = 3,
                 sharded_io: Optional[bool] = None,
                 datasets: Optional[Dict[str, object]] = None):
        """sharded_io: write per-process shard-part files instead of the
        gathered single-file format (pod-scale: no process_allgather on
        save, no host-side global materialization on restore). Default None
        = auto: parts when the trainer is sharded AND multi-process; the
        gathered format is kept for single-process runs where it is cheap
        and produces fewer files. Either format restores onto any topology;
        sharded trainers also restore either format.

        datasets: {name: reader} of input-state carriers (anything with
        ``save() -> dict`` / ``restore(dict)`` — KafkaStreamReader,
        TCPStreamReader, FileTailReader, WorkQueue). Their positions are
        written with every checkpoint and restored with the model, the
        reference's dataset-state-in-checkpoint behavior (KafkaDataset
        offsets ride TF checkpoints, kafka_dataset_op.cc SaveInternal).
        Positions are PER-PROCESS (each process checkpoints its own
        readers); after an elastic topology change a missing per-process
        file is skipped — data rebalancing across a rescale is the shared
        WorkQueue's job, not a byte-offset's."""
        self.dir = directory
        self.trainer = trainer
        self.keep = keep
        self.sharded_io = sharded_io
        self.datasets = dict(datasets or {})
        # Async-writer state: at most one save in flight; wait() drains and
        # re-raises. on_write is a test seam invoked in the writer thread
        # before any file IO (crash/overlap injection).
        self._writer: Optional[threading.Thread] = None
        self._writer_err: Optional[Tuple[BaseException, str]] = None
        self._force_full = False  # failed incr writer -> next save is full
        self.on_write = None
        # Integrity state: dirs that already passed verify() (files are
        # immutable once the manifest commits, so one pass is enough);
        # quarantine_count / last_quarantined surface through serving
        # health (Predictor.health, /healthz).
        self._verified: set = set()
        self.quarantine_count = 0
        self.last_quarantined: Optional[str] = None
        # Stall/traffic accounting (bench.py, tools/bench_ckpt.py):
        # ckpt_stall_ms accumulates CALLER-side blocking time across saves;
        # last_save records {kind, path, async, stall_ms, transfer_bytes,
        # write_ms (async, once the writer finishes)}.
        self.ckpt_stall_ms: float = 0.0
        self.last_save: Dict[str, Any] = {}
        os.makedirs(directory, exist_ok=True)

    # ------------------------------------------------------------- helpers

    def _bundle_states(self, state: TrainState, bname: str) -> List[Tuple[str, Dict]]:
        """Split a (possibly stacked and/or sharded) bundle state into LOCAL
        per-table host states, tagged 'tK' for stacked member K. Shard dims
        are concatenated: rows from all shards merge into one export (the
        partition_offset records the split for forensics)."""
        b = self.trainer.bundles[bname]
        ts = state.tables[bname]
        out = []
        members = range(len(b.features)) if b.stacked else [None]
        for k in members:
            sub = jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
            out.append((f"t{k}" if k is not None else "t", _state_to_np(sub)))
        return out

    def _is_sharded(self) -> bool:
        return hasattr(self.trainer, "num_shards")

    def _export_bundle(self, state, bname, only_dirty) -> Dict[str, Dict[str, np.ndarray]]:
        from deeprec_tpu.embedding.table import empty_key

        b = self.trainer.bundles[bname]
        exports = {}
        for tag, np_state in self._bundle_states(state, bname):
            if self._is_sharded():
                # leading dim = shard axis: compact each shard, concatenate,
                # remember offsets (DeepRec's -partition_offset tensor)
                parts = []
                offsets = [0]
                N = np_state["keys"].shape[0]
                for s in range(N):
                    local = {k: v[s] for k, v in np_state.items()}
                    parts.append(export_table_arrays(b.table, local, only_dirty))
                    offsets.append(offsets[-1] + parts[-1]["keys"].shape[0])
                merged = {}
                for k in parts[0]:
                    if is_per_row(k):
                        merged[k] = np.concatenate([p[k] for p in parts])
                    elif k == "bloom":
                        # keep each shard's sketch: restoring onto the SAME
                        # shard count is then exact (sub-threshold admission
                        # counts survive); re-sharding falls back to a
                        # rebuild from row freqs (see _import_local)
                        merged["bloom_parts"] = np.stack([p[k] for p in parts])
                    else:  # per-table scalar slot: identical on all shards
                        merged[k] = parts[0][k]
                merged["partition_offset"] = np.asarray(offsets, np.int64)
                exports[tag] = merged
            else:
                exports[tag] = export_table_arrays(b.table, np_state, only_dirty)
            if only_dirty:
                # Deltas carry the FULL live-key set (keys only, compact):
                # restore prunes resurrected keys that were evicted between
                # saves — dirty rows alone cannot express an eviction.
                keys = np_state["keys"]
                occ = keys != empty_key(b.table.cfg)
                exports[tag]["live_keys"] = keys[occ]
        return exports

    # ------------------------------------------------ pod-scale parts format
    #
    # At pod scale the gathered format above stops working: a full
    # process_allgather per save means every host materializes every table.
    # The parts format writes one file per PROCESS per table containing only
    # that process's addressable shards' compacted rows (the analog of
    # DeepRec's per-PS checkpoint partitions, Embedding-Variable.md
    # "Checkpoint" 9-part layout — except parts here follow the device mesh,
    # not a PS assignment). Restore streams every part file and re-routes
    # each key to its owner shard by hash, so a parts checkpoint restores
    # onto ANY topology (different process count, mesh size, or capacity),
    # exactly like the gathered format.

    def _use_parts(self) -> bool:
        if not self._is_sharded():
            return False
        if self.sharded_io is not None:
            return self.sharded_io
        return jax.process_count() > 1

    def _shard_axis(self, bname) -> int:
        """Position of the shard axis in this bundle's state leaves
        ([T, N, ...] stacked, [N, ...] plain)."""
        return 1 if self.trainer.bundles[bname].stacked else 0

    @staticmethod
    def _owned_ids(leaf, k) -> List[int]:
        """Shard indices addressable on this process (all of them when
        single-process)."""
        return sorted({s.index[k].start or 0 for s in leaf.addressable_shards})

    @staticmethod
    def _local_block(leaf, k, s) -> np.ndarray:
        """One owned shard's data with the shard axis dropped — reads the
        addressable shard directly, never the global value."""
        for sh in leaf.addressable_shards:
            if (sh.index[k].start or 0) == s:
                data = np.asarray(sh.data)
                assert data.shape[k] == 1, (
                    f"expected one shard index per device, got {data.shape}"
                )
                return np.squeeze(data, axis=k)
        raise KeyError(f"shard {s} is not addressable on this process")

    def _export_bundle_parts(
        self, state, bname, only_dirty
    ) -> Dict[str, Dict[str, np.ndarray]]:
        """Compact THIS process's shards of one bundle (no cross-process
        collectives). Arrays mirror _export_bundle plus routing metadata:
        shard_ids (which shards the rows came from, partition_offset-aligned)
        and num_shards (sharding at save time, for exact-sketch restore)."""
        b = self.trainer.bundles[bname]
        ts = state.tables[bname]
        k = self._shard_axis(bname)
        owned = self._owned_ids(ts.keys, k)
        members = range(len(b.features)) if b.stacked else [None]
        exports = {}
        for m in members:
            tag = f"t{m}" if m is not None else "t"

            def np_state_for(s, m=m):
                def get(leaf):
                    blk = self._local_block(leaf, k, s)
                    return blk[m] if m is not None else blk

                d = {
                    "keys": get(ts.keys),
                    "values": get(ts.values),
                    # unpack the fused metadata HOST-side (the device leaf
                    # is [3, C_local]; the file format stays columnar)
                    **_meta_columns(get(ts.meta)),
                }
                for sname, arr in ts.slots.items():
                    d["slot:" + sname] = get(arr)
                if ts.bloom is not None:
                    d["bloom"] = get(ts.bloom)
                return d

            parts, offsets, blooms, live = [], [0], [], []
            for s in owned:
                np_state = np_state_for(s)
                parts.append(export_table_arrays(b.table, np_state, only_dirty))
                offsets.append(offsets[-1] + parts[-1]["keys"].shape[0])
                if np_state.get("bloom") is not None:
                    blooms.append(np_state["bloom"])
                if only_dirty:
                    occ = np_state["keys"] != empty_key(b.table.cfg)
                    live.append(np_state["keys"][occ])
            merged = {}
            for key in parts[0]:
                if key == "bloom":
                    continue  # per-shard sketches ride bloom_parts below
                merged[key] = (
                    np.concatenate([p[key] for p in parts])
                    if is_per_row(key)
                    else parts[0][key]
                )
            if blooms:
                merged["bloom_parts"] = np.stack(blooms)
            merged["partition_offset"] = np.asarray(offsets, np.int64)
            merged["shard_ids"] = np.asarray(owned, np.int64)
            merged["num_shards"] = np.asarray(self.trainer.num_shards, np.int64)
            if only_dirty:
                merged["live_keys"] = (
                    np.concatenate(live)
                    if live
                    else np.empty((0,), parts[0]["keys"].dtype)
                )
            exports[tag] = merged
        return exports

    # ------------------------------------- incremental staging (device half)

    @staticmethod
    def _local_device_block(leaf, k: int, s: int):
        """One owned shard's block with the shard axis dropped, as a DEVICE
        array (the np-returning `_local_block` is the full-transfer legacy
        read; the compacted exporter must not pull [C_local, D] leaves to
        the host just to pick a few dirty rows out of them)."""
        for sh in leaf.addressable_shards:
            if (sh.index[k].start or 0) == s:
                return jnp.squeeze(sh.data, axis=k)
        raise KeyError(f"shard {s} is not addressable on this process")

    def _member_local_state(self, ts: TableState, m: Optional[int],
                            s: Optional[int], k: int) -> TableState:
        """LOCAL TableState view (device leaves) for member `m` of shard
        `s` (None = unstacked / unsharded)."""
        def get(leaf):
            x = self._local_device_block(leaf, k, s) if s is not None else leaf
            return x[m] if m is not None else x

        return jax.tree.map(get, ts)

    def _stage_incr(self, state: TrainState):
        """Device half of an incremental save: per (bundle, member, shard),
        read ONE dirty-count scalar, quantize it to a power-of-two budget
        (ops/compact.quantize_rows — drift re-traces at most log2(C) times
        per table) and run the jitted compaction. Returns
        ({bundle: {tag: [(shard_id, device_arrays, n)]}}, transfer_bytes)
        where transfer_bytes is what actually crosses device->host: the
        padded compacted rows + the [C] key array per shard — dirty-
        fraction-scaled, not capacity-scaled."""
        from deeprec_tpu.ops.compact import quantize_rows

        out: Dict[str, Dict[str, list]] = {}
        jobs = []  # (pkgs-list, shard_id, sentinel, sub_state, count_device)
        for bname, b in self.trainer.bundles.items():
            ts = state.tables[bname]
            sent = empty_key(b.table.cfg)
            k = self._shard_axis(bname) if self._is_sharded() else 0
            if not self._is_sharded():
                sids: List[Optional[int]] = [None]
            elif self._use_parts():
                sids = list(self._owned_ids(ts.keys, k))
            else:
                sids = list(range(self.trainer.num_shards))
            members = range(len(b.features)) if b.stacked else [None]
            out[bname] = {}
            for m in members:
                tag = f"t{m}" if m is not None else "t"
                pkgs: list = []
                out[bname][tag] = pkgs
                for s in sids:
                    # Pass 1: dispatch every count (async) — the first
                    # int() below drains the dispatch queue ONCE for all
                    # of them instead of one flush per (bundle, member,
                    # shard).
                    sub = self._member_local_state(ts, m, s, k)
                    jobs.append((pkgs, s, sent, sub,
                                 _dirty_count_jit(sub, sent)))
        total = 0
        for pkgs, s, sent, sub, cnt in jobs:
            n = int(cnt)
            size = quantize_rows(n, sub.capacity)
            arrays = _compact_dirty_jit(sub, sent, size)
            total += _tree_bytes(arrays)
            pkgs.append((s, arrays, n))
        return out, total

    # -------------------------------------- incremental assembly (IO half)

    def _materialize_pkg(self, b, arrays: Dict[str, jnp.ndarray], n: int):
        """One shard's staged compaction -> (row dict truncated to the true
        dirty count, live keys, bloom, per-table scalar entries). Applies
        the same save-time counter-filter drop as `export_table_arrays`, on
        the already-small compacted arrays."""
        cfg = b.table.cfg
        np_arrays = {key: np.asarray(v) for key, v in arrays.items()}
        all_keys = np_arrays.pop("_all_keys")
        bloom = np_arrays.pop("bloom", None)
        per_table = {
            key: v for key, v in np_arrays.items()
            if key.startswith("slot:") and not is_per_row(key)
        }
        rows = {
            key: v[:n] for key, v in np_arrays.items() if key not in per_table
        }
        if (
            not cfg.ev.ckpt.save_filtered_features
            and cfg.ev.counter_filter is not None
            and cfg.ev.counter_filter.filter_freq > 0
        ):
            keep = rows["freqs"] >= cfg.ev.counter_filter.filter_freq
            rows = {key: v[keep] for key, v in rows.items()}
        live = all_keys[all_keys != empty_key(cfg)]
        return rows, live, bloom, per_table

    def _assemble_incr(self, plan: _SavePlan, bname: str,
                       parts: bool) -> Dict[str, Dict[str, np.ndarray]]:
        """Merge a bundle's staged per-shard compactions into the exact
        file layout the legacy host-side incremental export produced
        (gathered single / gathered sharded / parts) — restore code is
        untouched."""
        b = self.trainer.bundles[bname]
        exports = {}
        for tag, pkgs in plan.incr[bname].items():
            rows_list, live_list, blooms, offsets = [], [], [], [0]
            per_table: Dict[str, np.ndarray] = {}
            shard_ids = []
            for sid, arrays, n in pkgs:
                rows, live, bloom, scal = self._materialize_pkg(b, arrays, n)
                rows_list.append(rows)
                live_list.append(live)
                if bloom is not None:
                    blooms.append(bloom)
                per_table.update(scal)
                offsets.append(offsets[-1] + rows["keys"].shape[0])
                shard_ids.append(sid)
            if len(pkgs) == 1 and pkgs[0][0] is None:
                # plain Trainer: single gathered file, no partition metadata
                merged = {**rows_list[0], **per_table}
                if blooms:
                    merged["bloom"] = blooms[0]
            else:
                merged = {
                    key: np.concatenate([r[key] for r in rows_list])
                    for key in rows_list[0]
                }
                merged.update(per_table)
                if blooms:
                    merged["bloom_parts"] = np.stack(blooms)
                merged["partition_offset"] = np.asarray(offsets, np.int64)
                if parts:
                    merged["shard_ids"] = np.asarray(shard_ids, np.int64)
                    merged["num_shards"] = np.asarray(
                        self.trainer.num_shards, np.int64
                    )
            merged["live_keys"] = (
                np.concatenate(live_list)
                if live_list
                else np.empty((0,), rows_list[0]["keys"].dtype)
            )
            exports[tag] = merged
        return exports

    def _clear_dirty(self, state: TrainState) -> TrainState:
        # Zero the META_DIRTY row of the fused metadata leaf; the columnar
        # multiply broadcasts over any leading (group/shard) axes and keeps
        # the arrays' device placement.
        _keep = jnp.asarray([1, 1, 0], jnp.int32)[:, None]
        tables = {
            bname: ts.replace(meta=ts.meta * _keep)
            if not isinstance(ts, dict)
            else ts
            for bname, ts in state.tables.items()
        }
        return TrainState(
            step=state.step, tables=tables, dense=state.dense,
            opt_state=state.opt_state,
        )

    # ---------------------------------------------------------------- save

    def _is_writer(self) -> bool:
        """Multi-host: every process assembles the global arrays (shared-FS
        layout needs the files once), process 0 writes them.

        Memory model: saves gather each table to host RAM (a full
        process_allgather per save, incremental included) and multi-host
        restore materializes it on one device per process — correct up to
        host/device memory, which covers single-slice pods. A per-process
        shard-part file format (no global gather anywhere) is the
        pod-scale follow-up; see docs/STATUS-round2.md.
        """
        if jax.process_count() > 1 and not self._is_sharded():
            raise RuntimeError(
                "multi-process checkpointing requires a ShardedTrainer "
                "(a plain Trainer under jax.distributed has no global mesh "
                "to gather from / place onto)"
            )
        return jax.process_index() == 0

    @staticmethod
    def _sync(tag: str) -> None:
        if jax.process_count() > 1:
            from jax.experimental import multihost_utils

            multihost_utils.sync_global_devices(tag)

    def save(self, state: TrainState) -> Tuple[TrainState, str]:
        """Full checkpoint. Returns (state with dirty bits cleared, path).
        Multi-host safe: all processes participate in the gather, process 0
        writes, and nobody returns before the manifest exists."""
        return self._save(state, "full")

    def save_incremental(self, state: TrainState) -> Tuple[TrainState, str]:
        """Delta checkpoint: rows touched since the previous (full or incr)
        save, compacted ON DEVICE so the device->host transfer scales with
        the dirty fraction. The consumer replays deltas over the latest
        full save."""
        return self._save(state, "incr")

    # ------------------------------------------------------- async saves

    def save_async(self, state: TrainState) -> Tuple[TrainState, str]:
        """Full checkpoint with the write half on a background thread.

        The caller-side cost is the device snapshot dispatch (fresh
        buffers, so later donation of the live state cannot touch them)
        plus starting the host copies; np.savez + manifest run on the
        writer while the next dispatches train. Returns immediately with
        (dirty-cleared state, path); the checkpoint is durable only once
        `wait()` returns — a crash mid-write leaves a manifest-less dir
        that restore ignores (the existing crash contract). At most one
        save is in flight: a second save_*_async first drains the first.
        Transiently holds one extra device-side copy of the tables;
        multi-process runs fall back to the synchronous path (the barrier
        choreography must run on the dispatch thread)."""
        return self._save_async(state, "full")

    def save_incremental_async(self, state: TrainState) -> Tuple[TrainState, str]:
        """Delta checkpoint off the training thread: the device-compacted
        dirty rows (small, dirty-fraction-sized buffers) are staged on the
        caller, the npz write happens on the writer thread."""
        return self._save_async(state, "incr")

    def _save_async(self, state: TrainState, kind: str) -> Tuple[TrainState, str]:
        if jax.process_count() > 1:
            # sync_global_devices from a writer thread would interleave
            # with the training thread's collectives — degrade to the
            # synchronous multi-host path, which is already correct.
            return self._save(state, kind)
        self.wait()  # at most one save in flight
        kind = self._effective_kind(kind)
        t0 = time.perf_counter()
        plan = self._stage(state, kind, snapshot=True)
        # Account (and rebind last_save) BEFORE the writer starts: a fast
        # writer could otherwise finish and stamp write_ms into the
        # PREVIOUS save's record right as this one replaces it.
        record = self._account(plan, t0, background=True)
        self._writer = threading.Thread(
            target=self._writer_main, args=(plan, record), daemon=True,
            name=f"ckpt-writer-{kind}-{plan.step}",
        )
        self._writer.start()
        return self._clear_dirty(state), plan.path

    def _writer_main(self, plan: _SavePlan, record: Dict[str, Any]) -> None:
        try:
            if self.on_write is not None:
                self.on_write(plan.path)  # test seam (crash/overlap tests)
            t0 = time.perf_counter()
            t0w = time.time()
            self._write_plan(plan)  # noqa: DRT004 — single-writer invariant: _save_async drains the previous writer, readers wait() first
            record["write_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
            # obs timeline span: the background npz+manifest write — the
            # "checkpoint writer" track of the train→delta→serve trace
            # (no-op unless DEEPREC_TRACE is configured)
            from deeprec_tpu.obs import trace as obs_trace

            obs_trace.phase_span(f"ckpt_write_{plan.kind}", t0w,
                                 time.time(), cat="train")
            if plan.kind == "full":
                self._force_full = False  # chain re-anchored durably
        except BaseException as e:  # surfaced by wait()/next save/restore
            self._writer_err = (e, plan.kind)

    def wait(self) -> None:
        """Drain the in-flight async save, if any. Until this returns the
        checkpoint is not durable (no manifest). Re-raises a writer
        failure — after which the half-written dir has no manifest and is
        invisible to restore, exactly like a crash. A failed INCREMENTAL
        writer additionally escalates the next save to FULL: that delta's
        dirty bits were already cleared on the training thread, so only a
        full re-anchor can put its rows back in the chain."""
        t = getattr(self, "_writer", None)
        if t is not None:
            t.join()
            self._writer = None
        err = getattr(self, "_writer_err", None)
        self._writer_err = None
        if err is not None:
            e, kind = err
            if kind == "incr":
                self._force_full = True
            raise RuntimeError(f"async checkpoint writer failed: {e}") from e

    def close(self) -> None:
        self.wait()

    def _effective_kind(self, kind: str) -> str:
        if kind != "incr":
            return kind
        if getattr(self, "_force_full", False):
            return "full"  # see wait(): a lost delta voids the incr chain
        if self._chain_has_gap():
            # A consumer quarantined a corrupt/torn link newer than the
            # latest full: deltas past the gap can never replay, so the
            # next save must re-anchor the chain (self-healing contract,
            # same semantics as the failed-incr-writer escalation).
            return "full"
        return kind

    # ------------------------------------------------------- save halves

    def _save(self, state: TrainState, kind: str) -> Tuple[TrainState, str]:
        self.wait()  # serialize behind any in-flight async save
        kind = self._effective_kind(kind)
        t0 = time.perf_counter()
        plan = self._stage(state, kind, snapshot=False)
        self._write_plan(plan)
        if kind == "full":
            self._force_full = False
        self._account(plan, t0, background=False)
        return self._clear_dirty(state), plan.path

    def _account(self, plan: _SavePlan, t0: float,
                 background: bool) -> Dict[str, Any]:
        stall = (time.perf_counter() - t0) * 1e3
        self.ckpt_stall_ms = getattr(self, "ckpt_stall_ms", 0.0) + stall
        self.last_save = {
            "kind": plan.kind, "path": plan.path, "async": background,
            "stall_ms": round(stall, 3), **plan.stats,
        }
        return self.last_save

    def _stage(self, state: TrainState, kind: str, snapshot: bool) -> _SavePlan:
        """Device half of a save: everything that must read the live state.
        With snapshot=True every carried array is a FRESH buffer (device
        copies / jit outputs), so the plan stays valid while the training
        loop donates the live state through subsequent dispatches."""
        step = int(state.step)
        path = os.path.join(self.dir, f"{kind}-{step}")
        # The manifest at this path is about to change (clear + rewrite);
        # drop any cached copy so a later restore() on this manager
        # validates against the new one.
        getattr(self, "_manifest_cache", {}).pop(path, None)
        self._verified.discard(path)
        write = self._is_writer()
        parts = self._use_parts()
        positions = (
            {name: r.save() for name, r in self.datasets.items()}
            if self.datasets else None
        )
        incr = None
        snap_state = state
        if kind == "incr" and jax.process_count() > 1 and not parts:
            # Explicit sharded_io=False on a multi-process run: shards this
            # process cannot address have no device-local block to compact.
            # Keep the legacy gathered export (process_allgather + host
            # dirty mask) — correctness over the transfer diet here.
            transfer = _tree_bytes(state.tables)
        elif kind == "incr":
            incr, transfer = self._stage_incr(state)
            snap_state = None
        elif snapshot:
            snap_state = TrainState(
                step=state.step, tables=_copy_tree(state.tables),
                dense=state.dense, opt_state=state.opt_state,
            )
            transfer = _tree_bytes(snap_state.tables)
        else:
            transfer = _tree_bytes(state.tables)
        dense = _copy_tree(state.dense) if snapshot else state.dense
        opt = _copy_tree(state.opt_state) if snapshot else state.opt_state
        transfer += _tree_bytes(dense) + _tree_bytes(opt)
        if snapshot:
            _prefetch_host(snap_state.tables if snap_state is not None else incr)
            _prefetch_host((dense, opt))
        return _SavePlan(
            path=path, kind=kind, step=step, parts=parts, write=write,
            state=snap_state, incr=incr, dense=dense, opt_state=opt,
            positions=positions, stats={"transfer_bytes": int(transfer)},
            routing={
                bname: self._routing_fp(bname)
                for bname in self.trainer.bundles
            },
        )

    def _routing_fp(self, bname: str) -> str:
        """The trainer's active routing fingerprint for one bundle —
        "uniform" for plan-less trainers (and every pre-placement
        checkpoint, whose manifest has no routing record at all)."""
        fn = getattr(self.trainer, "routing_fingerprint", None)
        return fn(bname) if fn is not None else "uniform"

    @staticmethod
    def _savez(digests: Dict[str, Dict[str, str]], path: str, fname: str,
               arrays: Dict[str, np.ndarray]) -> None:
        """np.savez + per-array digest recording: the digests land in the
        manifest (written LAST), so any committed checkpoint carries the
        checksums `verify()` replays. Digests are computed from the exact
        arrays handed to np.savez — what's on disk must hash to this."""
        arrays = {k: np.asarray(v) for k, v in arrays.items()}
        np.savez(os.path.join(path, fname), **arrays)
        digests[fname] = {k: _array_digest(v) for k, v in arrays.items()}

    @not_thread_safe
    def _write_plan(self, plan: _SavePlan) -> None:
        """Host half of a save: materialize, write npz files, commit the
        manifest LAST (completeness marker), GC. Runs on the caller (sync)
        or the writer thread (async — single-process only, so every
        `_sync` below is a no-op there). @not_thread_safe: it mutates the
        manager's bookkeeping (digest memo, GC state, the checkpoint dir
        itself) with no lock — the single-writer invariant (at most one
        writer thread in flight, `_save_async` drains the previous one and
        every read path calls `wait()` first) is the serialization."""
        path, kind, step = plan.path, plan.kind, plan.step
        write, parts = plan.write, plan.parts
        digests: Dict[str, Dict[str, str]] = {}
        try:
            if write or parts or self.datasets:
                os.makedirs(path, exist_ok=True)
            if parts:
                # Pod-scale path: every process writes ONLY its addressable
                # shards' rows — no process_allgather, no host ever holds a
                # table it doesn't own a shard of.
                #
                # A crashed earlier attempt at this step (no manifest written)
                # can leave part files behind — including pids beyond this
                # run's process_count after an elastic downscale, or gathered
                # single files from a pre-rescale save that would shadow the
                # fresh parts on restore. Restore globs part*.npz, so stale
                # files would be silently merged: the writer clears the
                # manifest FIRST (so a crash mid-clear/mid-write leaves an
                # incomplete dir that _list() ignores, not a dir that
                # restores empty), then every table file, behind a barrier,
                # before anyone writes.
                pid = jax.process_index()
                if write:
                    import glob as _glob
                    mf = os.path.join(path, "manifest.json")
                    if os.path.exists(mf):
                        os.remove(mf)
                    # table_*.npz matches gathered AND .partNNNNN.npz
                    # files; stale dataset positions (e.g. pids beyond a
                    # downscaled topology) must go too, or a later wider
                    # restore rewinds readers to a dead run's offsets
                    for stale in _glob.glob(
                        os.path.join(path, "table_*.npz")
                    ) + _glob.glob(
                        os.path.join(path, "datasets.part*.json")
                    ):
                        os.remove(stale)
                self._sync(f"ckpt-{kind}-{step}-clear")
                for bname in self.trainer.bundles:
                    exported = (
                        self._assemble_incr(plan, bname, parts=True)
                        if kind == "incr"
                        else self._export_bundle_parts(plan.state, bname, False)
                    )
                    for tag, arrays in exported.items():
                        # Digest the writer process's OWN part files; other
                        # processes' parts are covered by the part-count
                        # check in _iter_part_rows, not by checksums.
                        self._savez(
                            digests, path,
                            f"table_{bname}_{tag}.part{pid:05d}.npz", arrays,
                        )
                self._write_positions(path, plan.positions)
                # The manifest is the completeness marker (_list() ignores
                # dirs without one): it must not exist until every process
                # has finished writing its part files AND dataset positions.
                self._sync(f"ckpt-{kind}-{step}-parts")
            else:
                for bname in self.trainer.bundles:
                    exported = (
                        self._assemble_incr(plan, bname, parts=False)
                        if plan.incr is not None
                        # plan.incr None + kind incr = the multi-process
                        # gathered fallback: legacy host-side dirty mask
                        else self._export_bundle(
                            plan.state, bname, kind == "incr"
                        )
                    )
                    for tag, arrays in exported.items():
                        if write:
                            self._savez(
                                digests, path, f"table_{bname}_{tag}.npz",
                                arrays,
                            )
            if not parts:
                # parts mode wrote positions before its pre-manifest
                # barrier above; the gathered path writes them here.
                self._write_positions(path, plan.positions)
                self._sync(f"ckpt-{kind}-{step}-datasets")
            if write:
                self._savez(digests, path, "dense.npz",
                            _tree_to_npz_dict(plan.dense))
                self._savez(digests, path, "opt.npz",
                            _tree_to_npz_dict(plan.opt_state))
                manifest = {"step": step, "kind": kind, "digests": digests,
                            "routing": plan.routing}
                if parts:
                    manifest["format"] = "parts"
                    manifest["parts"] = jax.process_count()
                    manifest["num_shards"] = self.trainer.num_shards
                if kind == "incr":
                    # Chain linkage: the step of the save this delta applies
                    # over. Restore walks base-links from the full anchor —
                    # a delta whose base is missing (quarantined or deleted
                    # middle link) sits beyond a gap and must not replay.
                    manifest["base"] = self._chain_tip(before=step)
                if kind == "full":
                    manifest["bundles"] = {
                        bn: [f.name for f in b.features]
                        for bn, b in self.trainer.bundles.items()
                    }
                # Atomic manifest commit: a crash mid-write must leave NO
                # manifest (dir invisible), never a torn one.
                mtmp = os.path.join(path, ".manifest.json.tmp")
                with open(mtmp, "w") as f:
                    json.dump(manifest, f)
                os.replace(mtmp, os.path.join(path, "manifest.json"))
                # GC after BOTH kinds: full saves age out old fulls, and
                # either kind sweeps incr dirs orphaned by an aged-out base.
                self._gc()
        finally:
            # The barrier must be reached even if the writer's I/O raises:
            # without it every other process blocks in sync_global_devices
            # forever. (A writer error mid-export still mismatches the
            # remaining gathers — that fails loudly at the runtime level,
            # which beats a silent deadlock.)
            self._sync(f"ckpt-{kind}-{step}")

    def _write_positions(self, path: str,
                         positions: Optional[Dict[str, dict]]) -> None:
        """Every process writes its OWN readers' positions
        (dataset-state-in-checkpoint, KafkaDataset parity). The positions
        were snapshotted at STAGE time — an async writer must record where
        the readers were when the checkpointed state was captured, not
        wherever the still-running training loop has advanced them to."""
        if not positions:
            return
        dpath = os.path.join(
            path, f"datasets.part{jax.process_index():05d}.json"
        )
        with open(dpath, "w") as f:
            json.dump(positions, f)

    # ------------------------------------------------------------- restore

    def _list(self, kind: str) -> List[int]:
        pat = re.compile(rf"^{kind}-(\d+)$")
        out = []
        for d in os.listdir(self.dir):
            m = pat.match(d)
            if m and os.path.exists(os.path.join(self.dir, d, "manifest.json")):
                out.append(int(m.group(1)))
        return sorted(out)

    def latest_full(self) -> Optional[int]:
        fulls = self._list("full")
        return fulls[-1] if fulls else None

    # ------------------------------------------- chain integrity (verify)

    def _chain_tip(self, before: Optional[int] = None) -> int:
        """Step of the newest committed link the next delta applies over:
        the latest full plus any newer deltas (-1 when the dir is empty).
        `before` bounds the scan to steps < before (the save being written
        must not see itself)."""
        steps = self._list("full") + self._list("incr")
        if before is not None:
            steps = [s for s in steps if s < before]
        return max(steps, default=-1)

    def _verify_quiet(self, path: str) -> Optional[str]:
        """Integrity-check one committed checkpoint dir against its
        manifest digests. Returns None when intact, else a reason string.
        Covers: torn/unparseable manifest, missing files, npz that fail to
        read (truncation tears the zip), and per-array digest mismatches
        (payload bit-flips). Dirs without digests (pre-checksum saves)
        verify their files are at least readable. Results are memoized —
        committed files are immutable, so each dir pays the read once."""
        if path in self._verified:
            return None
        try:
            with open(os.path.join(path, "manifest.json")) as f:
                manifest = json.load(f)
        except OSError as e:
            return f"manifest unreadable: {e}"
        except ValueError as e:
            return f"manifest torn: {e}"
        digests = manifest.get("digests")
        if digests:
            for fname, arrays in digests.items():
                fpath = os.path.join(path, fname)
                if not os.path.exists(fpath):
                    return f"{fname}: missing from committed checkpoint"
                try:
                    with np.load(fpath) as z:
                        names = set(z.files)
                        for aname, want in arrays.items():
                            if aname not in names:
                                return f"{fname}:{aname}: array absent"
                            got = _array_digest(z[aname])
                            if got != want:
                                return (f"{fname}:{aname}: digest mismatch "
                                        f"({got} != recorded {want})")
                except Exception as e:  # zip CRC / truncation / bad header
                    return f"{fname}: unreadable ({type(e).__name__}: {e})"
        self._verified.add(path)
        return None

    def verify(self, path: str) -> None:
        """Raise CheckpointCorrupt if `path` fails integrity checks."""
        err = self._verify_quiet(path)
        if err is not None:
            raise CheckpointCorrupt(f"checkpoint {path}: {err}")

    def quarantine(self, path: str, reason: str) -> Optional[str]:
        """Move a corrupt/torn dir out of the chain namespace (rename to
        `*.quarantined[.N]`) so every consumer — this process and any
        other sharing the FS — stops seeing it as a chain link. Returns
        the new path, or None if a racing consumer quarantined it first.
        The rename is the signal the TRAINER self-heals from: a
        quarantined step newer than the latest full means the delta chain
        has a gap, and `_effective_kind` escalates the next save to full."""
        dst = path + ".quarantined"
        i = 1
        while os.path.exists(dst):
            dst = f"{path}.quarantined.{i}"
            i += 1
        try:
            os.rename(path, dst)
        except OSError:
            return None  # another consumer won the rename race
        self.quarantine_count += 1
        self.last_quarantined = dst
        getattr(self, "_manifest_cache", {}).pop(path, None)
        self._verified.discard(path)
        _log.warning("checkpoint quarantined: %s -> %s (%s)",
                     path, dst, reason)
        return dst

    def valid_chain(self) -> Tuple[List[str], int]:
        """The longest verified full+delta chain, quarantining any corrupt
        link it finds. Returns (dir paths in replay order, tip step).

        Walk: newest intact full, then deltas in step order while (a) each
        verifies and (b) its manifest `base` links to the previous step —
        a corrupt delta is quarantined and truncates the chain there; a
        base mismatch (missing middle link) truncates WITHOUT quarantining
        the later, intact-but-unusable deltas. A corrupt full falls back
        to the next-older full. Raises FileNotFoundError when no intact
        full exists."""
        excluded: set = set()
        while True:
            fulls = [s for s in self._list("full") if s not in excluded]
            if not fulls:
                raise FileNotFoundError(
                    f"no intact full checkpoint under {self.dir}"
                )
            fs = fulls[-1]
            fpath = os.path.join(self.dir, f"full-{fs}")
            err = self._verify_quiet(fpath)
            if err is not None:
                self.quarantine(fpath, err)
                excluded.add(fs)
                continue
            chain, prev = [fpath], fs
            for s in self._list("incr"):
                if s <= fs:
                    continue
                p = os.path.join(self.dir, f"incr-{s}")
                err = self._verify_quiet(p)
                if err is not None:
                    self.quarantine(p, err)
                    break  # later deltas sit beyond the gap
                base = self._manifest(p).get("base")
                if base is not None and base != prev:
                    break  # missing middle link: stop, keep later dirs
                chain.append(p)
                prev = s
            return chain, prev

    def chain_dirs(self) -> List[str]:
        """Basenames of the current valid chain (serving poll contract:
        corrupt links are quarantined as a side effect, never returned).
        Empty when no intact full exists yet."""
        try:
            chain, _ = self.valid_chain()
        except FileNotFoundError:
            return []
        return [os.path.basename(p) for p in chain]

    def _chain_has_gap(self) -> bool:
        """True when a quarantined dir's step is newer than the latest
        intact full — the delta chain is missing a link only a full
        re-anchor can repair. Checked by `_effective_kind` on every save,
        so a quarantine by ANY consumer of the shared FS (e.g. the serving
        process) escalates this trainer's next save to full."""
        fulls = self._list("full")
        latest = fulls[-1] if fulls else -1
        pat = re.compile(r"^(?:full|incr)-(\d+)\.quarantined")
        try:
            names = os.listdir(self.dir)
        except OSError:
            return False
        return any(
            (m := pat.match(d)) is not None and int(m.group(1)) > latest
            for d in names
        )

    def restore(self, template: Optional[TrainState] = None,
                chunk: Optional[int] = None) -> TrainState:
        """Latest full checkpoint + all newer deltas, onto the trainer's
        CURRENT topology (mesh size / process count / capacity may all
        differ from save time — this is the elastic-rescale mechanism).
        Sharded multi-process trainers stream per-shard: each process reads
        the row files and keeps only keys its shards own — no global
        gather, no host-side global materialization.

        `chunk` (serving restores) imports rows in fixed-size slices so
        the import program has ONE static shape across every reload —
        ignored on the sharded streaming path, which already imports
        file-sized chunks and runs off the serving hot path."""
        self.wait()  # an in-flight async save must land (or fail) first
        if not self._list("full"):
            raise FileNotFoundError(f"no full checkpoint under {self.dir}")
        # Verified chain: corrupt or torn links are quarantined and the
        # restore falls back to the longest valid prefix — a bad delta
        # (or even a bad full) degrades to an older consistent state, it
        # never raises into the caller as a parse/shape error.
        chain, step = self.valid_chain()
        self._restore_datasets(chain)
        if self._is_sharded() and (
            jax.process_count() > 1 or self._use_parts()
        ):
            return self._restore_streaming(template, chain, step)
        state = template if template is not None else self.trainer.init(0)
        for path in chain:
            state = self._apply_ckpt(state, path, load_dense=True,
                                     chunk=chunk)
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            tables=state.tables,
            dense=state.dense,
            opt_state=state.opt_state,
        )

    def warm_replay(self, state: TrainState, chunk: int) -> None:
        """Compile the delta-replay programs — the chunked row import and
        the keep-mask prune rebuild — against `state`'s table shapes, so
        the FIRST live replay (poll_updates under traffic) is pure
        cache-hit dispatch instead of a GIL-held trace. The dummy import
        uses empty-key sentinel rows, inert by construction; all outputs
        are discarded. Single-host layouts only (sharded streaming
        restores run off the serving path)."""
        from deeprec_tpu.embedding.table import empty_key

        for bname, b in self.trainer.bundles.items():
            ts = state.tables[bname]
            sub = jax.tree.map(lambda a: a[0], ts) if b.stacked else ts
            keys_np = np.asarray(sub.keys)
            if keys_np.ndim != 1:
                continue
            cfg = b.table.cfg
            rows = {
                "keys": np.full((chunk,), empty_key(cfg), keys_np.dtype),
                "values": np.zeros((chunk, cfg.dim), np.float32),
                "freqs": np.zeros((chunk,), np.int32),
                "versions": np.zeros((chunk,), np.int32),
            }
            for sname, arr in sub.slots.items():
                if is_per_row("slot:" + sname):
                    a = np.asarray(arr)
                    rows["slot:" + sname] = np.zeros(
                        (chunk,) + a.shape[1:], np.float32
                    )
            out = import_rows(b.table, sub, rows, strict=False, chunk=chunk)
            fills = self.trainer._slot_fills(b)
            jax.block_until_ready(_rebuild_keep_jit(
                b.table, sub, jnp.ones(keys_np.shape, bool), fills
            ))
            jax.block_until_ready(out)

    def restore_into(self, state: TrainState, path: str,
                     chunk: Optional[int] = None,
                     load_dense: bool = True) -> TrainState:
        """Replay ONE checkpoint dir (full or incr) onto `state` and
        return the resulting TrainState — the shadow-copy building block
        of zero-stall serving updates (Predictor.poll_updates).

        Contract: the input `state` is NEVER mutated — all updates are
        functional (fresh arrays), so a reader holding the old reference
        keeps serving a complete, consistent model while the caller
        assembles the next one; the caller publishes the returned state
        with one atomic reference swap. The replayed result is
        bit-identical on table contents to applying the same dir in
        place (pinned by tests/test_serving_update.py). The returned
        step advances to the dir's manifest step (never backwards)."""
        out = self._apply_ckpt(state, path, load_dense=load_dense,
                               chunk=chunk)
        step = int(state.step)
        mf = os.path.join(path, "manifest.json")
        if os.path.exists(mf):
            with open(mf) as f:
                step = max(step, json.load(f)["step"])
        return TrainState(
            step=jnp.asarray(step, jnp.int32),
            tables=out.tables,
            dense=out.dense,
            opt_state=out.opt_state,
        )

    def _restore_datasets(self, chain: List[str]) -> None:
        """Rewind registered input readers to the NEWEST chain dir that
        carries this process's dataset positions. Missing files (pre-
        datasets checkpoints, or a rescaled topology) are skipped — the
        model state still restores; data rebalancing across topologies is
        the WorkQueue's job."""
        if not self.datasets:
            return
        fname = f"datasets.part{jax.process_index():05d}.json"
        for path in reversed(chain):
            p = os.path.join(path, fname)
            if not os.path.exists(p):
                continue
            with open(p) as f:
                saved = json.load(f)
            for name, reader in self.datasets.items():
                if name in saved:
                    reader.restore(saved[name])
            return

    @staticmethod
    def _get_member(sub, m):
        """Member m's view of a (possibly stacked) local table state."""
        return jax.tree.map(lambda a: a[m], sub) if m is not None else sub

    @staticmethod
    def _set_member(sub, new, m):
        """Write member m's updated state back into the stacked local state."""
        if m is None:
            return new
        return jax.tree.map(lambda a, u: a.at[m].set(u), sub, new)

    def _restore_streaming(
        self, template: Optional[TrainState], chain: List[str], step: int
    ) -> TrainState:
        """Pod-scale restore for sharded trainers: per checkpoint dir, each
        process streams row files one at a time, routes keys by hash to the
        shards it owns, and imports into host-local per-shard states built
        from its addressable template shards. Reads either format (parts or
        legacy gathered files) and any save topology; the result is
        assembled directly into global arrays, shard by shard."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        from deeprec_tpu.embedding import filters as _filters
        from deeprec_tpu.parallel.mesh import put_global

        tr = self.trainer
        N = tr.num_shards
        state = template if template is not None else tr.init(0)
        mesh = tr.mesh
        out_tables = {}
        for bname, b in tr.bundles.items():
            ts = state.tables[bname]
            k = self._shard_axis(bname)
            owned = self._owned_ids(ts.keys, k)
            members = list(range(len(b.features))) if b.stacked else [None]
            # Host-local owned-shard states (leaves keep the member axis for
            # stacked bundles, shard axis dropped).
            local = {
                s: jax.tree.map(
                    lambda leaf, s=s: jnp.asarray(self._local_block(leaf, k, s)),
                    ts,
                )
                for s in owned
            }
            cbf = b.table.cfg.ev.cbf_filter
            for path in chain:
                # Exact per-shard sketch reuse needs save-time ROUTING to
                # match, not just the shard count (see _import_local) —
                # manifests without a routing record predate plans and
                # routed uniformly.
                sketch_exact_ok = (
                    self._manifest(path).get("routing", {})
                    .get(bname, "uniform") == self._routing_fp(bname)
                )
                for m in members:
                    tag = f"t{m}" if m is not None else "t"
                    live_chunks: List[np.ndarray] = []
                    exact_sketch: Dict[int, np.ndarray] = {}
                    # CBF re-shard fallback: rows imported this dir, per shard
                    resharded_rows: Dict[int, List[Tuple[np.ndarray, np.ndarray]]] = {}
                    seen_any = False
                    is_incr = os.path.basename(path).startswith("incr-")
                    for rows in self._iter_part_rows(path, bname, tag):
                        seen_any = True
                        rows.pop("partition_offset", None)
                        sids = rows.pop("shard_ids", None)
                        save_n = int(np.asarray(rows.pop("num_shards", -1)))
                        lv = rows.pop("live_keys", None)
                        if lv is not None:
                            live_chunks.append(np.asarray(lv))
                        bp = rows.pop("bloom_parts", None)
                        rows.pop("bloom", None)  # legacy merged sketch
                        if bp is not None:
                            if sids is None:  # legacy gathered file
                                sids = np.arange(bp.shape[0])
                                save_n = bp.shape[0]
                            if save_n == N and sketch_exact_ok:
                                for i, sid in enumerate(np.asarray(sids)):
                                    if int(sid) in local:
                                        exact_sketch[int(sid)] = bp[i]
                        keys = rows["keys"]
                        if keys.shape[0] == 0:
                            continue
                        owner = self._restore_owner(bname, m, keys, N)
                        for s in owned:
                            sel = owner == s
                            if not sel.any():
                                continue
                            shard_rows = {
                                kk: (vv[sel] if is_per_row(kk) else vv)
                                for kk, vv in rows.items()
                            }
                            sub = local[s]
                            subm = self._get_member(sub, m)
                            subm = import_rows(b.table, subm, shard_rows)
                            if cbf is not None and subm.bloom is not None:
                                resharded_rows.setdefault(s, []).append(
                                    (shard_rows["keys"], shard_rows["freqs"])
                                )
                            local[s] = self._set_member(sub, subm, m)
                    if not seen_any:
                        continue
                    # Sketch restore: exact per-shard parts when the save
                    # topology matches; otherwise rebuild from the rows each
                    # shard imported this dir (same fallback semantics as
                    # _import_local — sub-threshold-only keys restart).
                    if cbf is not None:
                        for s in owned:
                            sub = local[s]
                            subm = self._get_member(sub, m)
                            if subm.bloom is None:
                                continue
                            if s in exact_sketch:
                                subm = subm.replace(
                                    bloom=jnp.asarray(
                                        exact_sketch[s], jnp.int32
                                    )
                                )
                            elif s in resharded_rows:
                                bloom = jnp.zeros_like(subm.bloom)
                                ks = np.concatenate(
                                    [p[0] for p in resharded_rows[s]]
                                )
                                fs = np.concatenate(
                                    [p[1] for p in resharded_rows[s]]
                                )
                                bloom, _ = _filters.cbf_add(
                                    cbf, bloom, jnp.asarray(ks),
                                    jnp.asarray(fs, jnp.int32),
                                )
                                subm = subm.replace(bloom=bloom)
                            local[s] = self._set_member(sub, subm, m)
                    if is_incr and live_chunks:
                        live = np.concatenate(live_chunks)
                        fills = tr._slot_fills(b)
                        for s in owned:
                            sub = local[s]
                            subm = self._get_member(sub, m)
                            keep = jnp.asarray(
                                np.isin(np.asarray(subm.keys), live)
                            )
                            subm = b.table.rebuild(
                                subm, keep=keep, slot_fills=fills
                            )
                            local[s] = self._set_member(sub, subm, m)
            # Assemble global arrays: each process contributes exactly its
            # owned shards via the callback (only addressable indices are
            # ever requested).
            sh = NamedSharding(mesh, tr._table_spec(bname))
            leaves_t, treedef = jax.tree_util.tree_flatten(ts)
            local_leaves = {
                s: jax.tree_util.tree_flatten(local[s])[0] for s in owned
            }

            def mk(i, gl):
                def cb(idx):
                    s = idx[k].start or 0
                    return np.expand_dims(
                        np.asarray(local_leaves[s][i]), axis=k
                    )

                return jax.make_array_from_callback(gl.shape, sh, cb)

            out_tables[bname] = jax.tree_util.tree_unflatten(
                treedef, [mk(i, gl) for i, gl in enumerate(leaves_t)]
            )
        # Dense/opt/step are replicated; the writer's npz is read by every
        # process off the shared FS (tiny next to the tables).
        dense, opt_state = state.dense, state.opt_state
        for path in chain:
            dpath = os.path.join(path, "dense.npz")
            if os.path.exists(dpath):
                dense = _tree_from_npz_dict(state.dense, np.load(dpath))
            opath = os.path.join(path, "opt.npz")
            if os.path.exists(opath):
                opt_state = _tree_from_npz_dict(
                    state.opt_state, np.load(opath)
                )
        repl = NamedSharding(mesh, P())
        return TrainState(
            step=put_global(jnp.asarray(step, jnp.int32), repl),
            tables=out_tables,
            dense=jax.tree.map(
                lambda t, a: put_global(np.asarray(a), repl), state.dense, dense
            ),
            opt_state=jax.tree.map(
                lambda t, a: put_global(np.asarray(a), repl),
                state.opt_state, opt_state,
            ),
        )

    @staticmethod
    def _part_files(path: str, bname: str, tag: str) -> List[str]:
        import glob as _glob

        return sorted(
            _glob.glob(os.path.join(path, f"table_{bname}_{tag}.part*.npz"))
        )

    def _manifest(self, path: str) -> dict:
        """The dir's manifest, cached per path (restore re-enters per
        bundle × member × chain dir; don't re-parse each time)."""
        cache = getattr(self, "_manifest_cache", None)
        if cache is None:
            cache = self._manifest_cache = {}
        if path not in cache:
            try:
                with open(os.path.join(path, "manifest.json")) as f:
                    cache[path] = json.load(f)
            except OSError:
                cache[path] = {}  # pre-manifest legacy dir
            except ValueError as e:
                # A manifest that EXISTS but doesn't parse is a torn write;
                # degrading to {} would disable exactly the stale/partial
                # validation this dir needs. Fail the dir instead.
                raise ValueError(
                    f"checkpoint {path}: manifest.json exists but is "
                    f"unparseable ({e}) — torn save; refusing to restore"
                )
        return cache[path]

    def _iter_part_rows(self, path: str, bname: str, tag: str):
        """Yield row dicts for one table from a checkpoint dir, one file at
        a time (bounded memory) — a single gathered file or N part files.
        Validates the part-file count against the manifest so a stale or
        partial save fails loudly instead of merging duplicate rows. Zero
        files is tolerated only for bundles the manifest doesn't declare
        (restoring a checkpoint that predates a newly added table)."""
        mf = self._manifest(path)
        single = os.path.join(path, f"table_{bname}_{tag}.npz")
        # In a parts-format dir a gathered file can only be stale residue
        # (pre-rescale save at the same step) — never prefer it.
        if mf.get("format") != "parts" and os.path.exists(single):
            yield dict(np.load(single))
            return
        files = self._part_files(path, bname, tag)
        expected = mf.get("parts")
        declared = bname in mf.get("bundles", {})
        if expected is not None and len(files) != expected and (
            files or declared
        ):
            raise ValueError(
                f"checkpoint {path}: {len(files)} part files for table "
                f"{bname}/{tag} but manifest records {expected} — stale or "
                f"partial save; refusing to merge"
            )
        for pf in files:
            yield dict(np.load(pf))

    def _load_rows(self, path: str, bname: str, tag: str):
        """All row sources for one table merged into a single dict — the
        small-scale restore path (plain Trainer / single-process sharded),
        where holding one table's live rows on the host is fine."""
        chunks = list(self._iter_part_rows(path, bname, tag))
        if not chunks:
            return None
        if len(chunks) == 1:
            chunks[0].pop("shard_ids", None)
            chunks[0].pop("num_shards", None)
            return chunks[0]
        merged = {}
        for key in chunks[0]:
            if key in ("partition_offset", "shard_ids", "num_shards",
                       "bloom_parts"):
                continue
            merged[key] = (
                np.concatenate([c[key] for c in chunks])
                if is_per_row(key) or key == "live_keys"
                else chunks[0][key]
            )
        if "bloom_parts" in chunks[0]:
            # reassemble per-shard sketches in shard order so same-topology
            # restores stay exact regardless of which process wrote which part
            pairs = []
            for c in chunks:
                pairs.extend(zip(np.asarray(c["shard_ids"]).tolist(),
                                 c["bloom_parts"]))
            pairs.sort(key=lambda p: p[0])
            merged["bloom_parts"] = np.stack([b for _, b in pairs])
        return merged

    def _apply_ckpt(self, state: TrainState, path: str, load_dense: bool,
                    chunk: Optional[int] = None) -> TrainState:
        # Delta replays recur at serving cadence with a different row
        # count each time — bucket those to stabilize compiled shapes;
        # one-shot full restores import exact-size. A serving caller
        # passes `chunk` instead: ONE static import shape for full and
        # delta alike (see import_rows), so no replay ever traces a new
        # XLA program while requests are in flight.
        bucket = os.path.basename(path).startswith("incr-")
        mf_routing = self._manifest(path).get("routing", {})
        tables = dict(state.tables)
        for bname, b in self.trainer.bundles.items():
            ts = tables[bname]
            members = range(len(b.features)) if b.stacked else [None]
            new_members = []
            for k in members:
                tag = f"t{k}" if k is not None else "t"
                sub = jax.tree.map(lambda a: a[k], ts) if b.stacked else ts
                rows = self._load_rows(path, bname, tag)
                if rows is not None:
                    rows.pop("partition_offset", None)
                    live = rows.pop("live_keys", None)
                    sub = self._import_local(
                        b.table, sub, rows, bucket=bucket, chunk=chunk,
                        bname=bname, member=k,
                        sketch_exact_ok=(
                            mf_routing.get(bname, "uniform")
                            == self._routing_fp(bname)
                        ),
                    )
                    if live is not None:
                        # delta semantics: anything absent from the delta's
                        # live set was evicted since the previous save
                        sub = self._prune_to_live(b, sub, live)
                new_members.append(sub)
            if b.stacked:
                ts = jax.tree.map(lambda *xs: jnp.stack(xs), *new_members)
            else:
                ts = new_members[0]
            tables[bname] = ts
        dense, opt_state = state.dense, state.opt_state
        if load_dense and os.path.exists(os.path.join(path, "dense.npz")):
            dense = _tree_from_npz_dict(state.dense, np.load(os.path.join(path, "dense.npz")))
        if load_dense and os.path.exists(os.path.join(path, "opt.npz")):
            opt_state = _tree_from_npz_dict(
                state.opt_state, np.load(os.path.join(path, "opt.npz"))
            )
        return TrainState(step=state.step, tables=tables, dense=dense,
                          opt_state=opt_state)

    def _prune_to_live(self, b, sub: TableState, live: np.ndarray) -> TableState:
        """Drop keys not in the delta's live set (evicted between saves) —
        rebuild-based, so probe chains heal and freed optimizer slot rows
        restart at the optimizer's init value. Jit-wrapped with a stable
        cache key (table, fills): the old eager closure re-traced the
        rebuild probe loop on EVERY delta replay, a GIL-held stall at
        serving cadence (poll_updates) — now it compiles once per table
        shape and every later replay is cache-hit dispatch."""
        from deeprec_tpu.embedding.table import empty_key

        fills = self.trainer._slot_fills(b)
        keys = np.asarray(sub.keys)
        # Nothing evicted since the previous save (every occupied key is in
        # the live set) -> the rebuild is an identity: skip it. Deltas at
        # serving cadence with stable key sets pay zero rebuild work.
        occupied_live = np.isin(keys, live) | (keys == empty_key(b.table.cfg))
        if occupied_live.all():
            return sub
        if keys.ndim == 2:  # sharded: [N, C_local]
            keep = np.stack([np.isin(k, live) for k in keys])
            return _rebuild_keep_sharded_jit(
                b.table, sub, jnp.asarray(keep), fills
            )
        return _rebuild_keep_jit(
            b.table, sub, jnp.asarray(np.isin(keys, live)), fills
        )

    def _restore_owner(self, bname, member, keys, N) -> np.ndarray:
        """Owner shard of restored keys: the trainer's ACTIVE placement
        plan when it carries one (ShardedTrainer.restore_owner), else the
        uniform hash. Routing by the live plan — not the hash, not the plan
        at save time — is what makes a checkpoint saved under plan A
        restore correctly into a trainer running plan B: each row lands on
        the shard where plan B's route will look it up."""
        fn = getattr(self.trainer, "restore_owner", None)
        if fn is not None and bname is not None:
            return np.asarray(fn(bname, member, keys), np.int32)
        return np.asarray(hashing.hash_shard(jnp.asarray(keys), N))

    def _import_local(self, table, sub: TableState, rows,
                      bucket: bool = False,
                      chunk: Optional[int] = None,
                      bname=None, member=None,
                      sketch_exact_ok: bool = True) -> TableState:
        """Import rows into a local (possibly shard-stacked) table state.

        `sketch_exact_ok` gates the per-shard exact CBF-sketch reuse: a
        saved sketch describes the rows save-time ROUTING put on that
        shard, so matching shard count alone is no longer enough — the
        caller compares the manifest's routing fingerprint against the
        restoring trainer's (a plan change falls back to rebuilding the
        sketches from the rows each shard actually imports)."""
        if self._is_sharded():
            N = self.trainer.num_shards
            owner = self._restore_owner(bname, member, rows["keys"], N)
            shards = []
            bloom_parts = rows.get("bloom_parts")
            same_topology = (
                bloom_parts is not None and bloom_parts.shape[0] == N
                and sketch_exact_ok
            )
            for s in range(N):
                sel = owner == s
                shard_rows = {
                    k: (v[sel] if is_per_row(k) else v)
                    for k, v in rows.items()
                    if k != "bloom_parts"
                }
                # Same shard count: each shard gets its own saved sketch back
                # (exact, sub-threshold counts included). Re-shard: rebuild
                # from owned rows' freqs — exact for admitted keys,
                # sub-threshold-only keys restart (documented semantic).
                # Never hand a summed global sketch to every shard: that
                # would inflate ~N× per save/restore cycle.
                shard_rows.pop("bloom", None)  # legacy merged-sketch files
                local = jax.tree.map(lambda a: a[s], sub)
                local = import_rows(table, local, shard_rows,
                                    bucket=bucket, chunk=chunk)
                cbf = table.cfg.ev.cbf_filter
                if cbf is not None and local.bloom is not None and same_topology:
                    local = local.replace(
                        bloom=jnp.asarray(bloom_parts[s], jnp.int32)
                    )
                elif cbf is not None and local.bloom is not None:
                    from deeprec_tpu.embedding import filters as _filters

                    bloom = jnp.zeros_like(local.bloom)
                    if shard_rows["keys"].shape[0] > 0:
                        bloom, _ = _filters.cbf_add(
                            cbf,
                            bloom,
                            jnp.asarray(shard_rows["keys"]),
                            jnp.asarray(shard_rows["freqs"], jnp.int32),
                        )
                    local = local.replace(bloom=bloom)
                shards.append(local)
            return jax.tree.map(lambda *xs: jnp.stack(xs), *shards)
        return import_rows(table, sub, rows, bucket=bucket, chunk=chunk)

    # ----------------------------------------------------------------- gc

    def _gc(self):
        if self.keep <= 0:
            return  # keep everything (legacy contract)
        fulls = self._list("full")
        for s in fulls[: -self.keep]:
            shutil.rmtree(os.path.join(self.dir, f"full-{s}"), ignore_errors=True)
        fulls = fulls[-self.keep:]
        if not fulls:
            return
        # Incr dirs whose base full aged out of `keep` are orphaned: a
        # delta at step s only ever replays over a full with step < s, and
        # the oldest such full left is fulls[0] — without this sweep a
        # long run accumulates unbounded incr directories between every
        # pair of long-dead fulls (deltas newer than a KEPT full stay:
        # they are that full's replay chain).
        for i in self._list("incr"):
            if i <= fulls[0]:
                shutil.rmtree(
                    os.path.join(self.dir, f"incr-{i}"), ignore_errors=True
                )
        # Quarantined dirs are kept for forensics while relevant, but age
        # out with the chain they broke (same bound as orphaned incrs).
        pat = re.compile(r"^(?:full|incr)-(\d+)\.quarantined")
        try:
            names = os.listdir(self.dir)
        except OSError:
            return
        for d in names:
            m = pat.match(d)
            if m and int(m.group(1)) <= fulls[0]:
                shutil.rmtree(os.path.join(self.dir, d), ignore_errors=True)
