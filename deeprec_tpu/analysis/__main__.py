"""CLI: ``python -m deeprec_tpu.analysis [--check | --fix-baseline]``.

Exit codes: 0 = clean (every finding suppressed or baselined), 1 = new
findings or stale baseline entries, 2 = usage error.
"""
from __future__ import annotations

import argparse
import sys

from deeprec_tpu.analysis import lint


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m deeprec_tpu.analysis",
        description="JAX-aware static analysis for deeprec_tpu "
                    "(rule catalog: docs/analysis.md)",
    )
    p.add_argument("targets", nargs="*", default=None,
                   help="files/dirs relative to the repo root "
                        f"(default: {', '.join(lint.DEFAULT_TARGETS)})")
    p.add_argument("--check", action="store_true",
                   help="lint and compare against the baseline (CI gate; "
                        "the default action)")
    p.add_argument("--fix-baseline", action="store_true",
                   help="rewrite the baseline to accept every current "
                        "unsuppressed finding")
    p.add_argument("--baseline", default=None,
                   help="baseline file (default: "
                        "deeprec_tpu/analysis/baseline.txt)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected)")
    p.add_argument("--rules", default=None,
                   help="comma list of rule codes to run (default: all)")
    p.add_argument("--list", dest="list_all", action="store_true",
                   help="print every finding (incl. suppressed/baselined) "
                        "and exit 0")
    p.add_argument("--list-rules", action="store_true",
                   help="print the rule catalog and exit")
    args = p.parse_args(argv)

    if args.list_rules:
        for code, doc in sorted(lint.RULES.items()):
            print(f"{code}  {doc}")
        return 0
    rules = (
        [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        if args.rules else None
    )
    if rules:
        unknown = sorted(set(rules) - set(lint.RULES))
        if unknown:
            print(f"unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    root = args.root or lint.repo_root()
    targets = tuple(args.targets) if args.targets else lint.DEFAULT_TARGETS

    if args.list_all:
        mods = lint.collect_modules(root, targets)
        findings = lint.run_rules(mods, rules)
        active, suppressed = lint.split_suppressed(mods, findings)
        for f in findings:
            tag = " (noqa)" if f in suppressed else ""
            print(f.render() + tag)
        print(f"{len(findings)} finding(s), {len(suppressed)} suppressed")
        return 0

    return lint.check(
        root=root, targets=targets, baseline_path=args.baseline,
        rules=rules, fix_baseline=args.fix_baseline,
    )


if __name__ == "__main__":
    sys.exit(main())
