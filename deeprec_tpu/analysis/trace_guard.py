"""Runtime trace-guard: assert a compile budget over a code region.

Every serving/perf incident of the retrace class — PR 5's
``_prune_to_live`` eager closure re-tracing the rebuild probe loop on
every delta (45–115 ms stalls next to live traffic), PR 2's stale jit
executables after ``update_budgets`` — was ultimately "XLA compiled
when we believed it could not". This module makes that belief
executable:

    from deeprec_tpu.analysis import trace_guard

    with trace_guard(max_compiles=0):
        predictor.poll_updates()          # replay must be cache-hit only

    with trace_guard(max_compiles=0) as g:
        state, mets = trainer.train_steps(state, stacked)
    print(g.compiles)                     # 0 after warmup, by contract

Counting rides jax.monitoring: one process-global listener (installed
lazily on first use, never removed) increments counters on the
``/jax/core/compile/backend_compile_duration`` event — fired exactly
once per real XLA compilation, never on an executable-cache hit — and on
``/jax/core/compile/jaxpr_trace_duration`` (tracing; informational,
retraces that hit the persistent compilation cache still cost a trace).
Counters are process-wide: a guard around region R sees compiles from
ANY thread that lands inside R's window. That is the desired semantics
for the serving tests (a background poller compiling next to traffic is
exactly the bug), but it means guards should not wrap regions where
unrelated threads legitimately warm code.

Used as a hard gate in tests/test_serving_update.py (delta replay),
tests/test_dedup.py (update_budgets rebuild), tests/test_analysis.py
(steady-state K-step training) and bench.py --smoke (steady-state
windows record their compile count into the bench JSON;
``tools/roofline.py --assert-compiles`` fails CI when it drifts above
zero).
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Optional

_COMPILE_EVENT = "/jax/core/compile/backend_compile_duration"
_TRACE_EVENT = "/jax/core/compile/jaxpr_trace_duration"

_lock = threading.Lock()
_counts = {"compiles": 0, "traces": 0}
_installed = False


class TraceGuardViolation(AssertionError):
    """A guarded region compiled more XLA programs than its budget."""

    def __init__(self, message: str, compiles: int, max_compiles: int):
        super().__init__(message)
        self.compiles = compiles
        self.max_compiles = max_compiles


def _install() -> None:
    """Register the process-global monitoring listener (idempotent).
    jax.monitoring has no unregister API in 0.4.x, so the listener is
    installed once and counts forever; guards diff the counter."""
    global _installed
    if _installed:
        return
    with _lock:
        if _installed:
            return
        import jax

        def _on_duration(event, duration, **kwargs):
            # compiles can land from any thread (background pollers,
            # writer warm passes); the lock keeps the counters exact
            # and costs nothing next to an XLA compile
            if event == _COMPILE_EVENT:
                with _lock:
                    _counts["compiles"] += 1
            elif event == _TRACE_EVENT:
                with _lock:
                    _counts["traces"] += 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _installed = True


def compile_count() -> int:
    """Process-lifetime count of real XLA compilations observed so far
    (only since the first trace_guard/compile_count use — the listener
    installs lazily)."""
    _install()
    return _counts["compiles"]


def trace_count() -> int:
    """Process-lifetime count of jaxpr traces observed so far."""
    _install()
    return _counts["traces"]


class _Guard:
    """Live view of a guarded region's counters."""

    def __init__(self, c0: int, t0: int):
        self._c0 = c0
        self._t0 = t0

    @property
    def compiles(self) -> int:
        return _counts["compiles"] - self._c0

    @property
    def traces(self) -> int:
        return _counts["traces"] - self._t0


@contextmanager
def trace_guard(max_compiles: Optional[int] = 0, note: str = ""):
    """Context manager asserting the region compiles at most
    ``max_compiles`` XLA programs (``None`` = measure only, never
    raise). Yields a guard whose ``.compiles``/``.traces`` read live and
    remain valid after exit. Exceptions from the body propagate
    unchanged (the budget is not checked on an already-failing region).
    """
    _install()
    g = _Guard(_counts["compiles"], _counts["traces"])
    # A body exception propagates from the yield on its own and skips the
    # budget check — a failing region is never double-reported.
    yield g
    if max_compiles is not None and g.compiles > max_compiles:
        where = f" [{note}]" if note else ""
        raise TraceGuardViolation(
            f"trace_guard{where}: region compiled {g.compiles} XLA "
            f"program(s), budget {max_compiles} — something inside is "
            "re-tracing (per-call jit(lambda)/closure, a stale "
            "executable rebuild, or an unwarmed shape); see "
            "docs/analysis.md",
            g.compiles, max_compiles,
        )
