"""deeprec_tpu.analysis — static lints + runtime trace-guard.

Two halves, one goal: the bug classes this repo's PRs kept rediscovering
by hand-review (per-call jit retraces, host syncs on the step, lane-
hostile layouts, unguarded cross-thread access) become executable gates.

  * ``python -m deeprec_tpu.analysis --check``  — AST lint suite
    (DRT001–DRT006, see lint.py / docs/analysis.md), wired into
    cibuild/run_tests.sh before pytest.
  * ``trace_guard(max_compiles=N)``             — runtime compile-budget
    context manager over jax.monitoring counters.
  * ``annotations``                             — @not_thread_safe /
    @guarded_by vocabulary the DRT004 lint reads.

The lint half is pure-AST: it never imports (or executes) the code it
analyzes, so a syntax-valid tree lints even when its dependencies are
broken. Note the CLI itself still pays the parent package's jax import
(``python -m deeprec_tpu.analysis`` executes ``deeprec_tpu/__init__``
first) — jax must be installed to run it, and the gate costs a jax
import plus well under a second of actual linting.
"""
from deeprec_tpu.analysis.annotations import guarded_by, not_thread_safe
from deeprec_tpu.analysis.trace_guard import (
    TraceGuardViolation,
    compile_count,
    trace_count,
    trace_guard,
)

__all__ = [
    "guarded_by",
    "not_thread_safe",
    "trace_guard",
    "TraceGuardViolation",
    "compile_count",
    "trace_count",
]
