"""JAX-aware static lints for the deeprec_tpu hot paths.

Every rule here mechanizes a bug class this repo previously caught by
hand-review (docs/analysis.md has the incident list):

  DRT001 retrace-hazard        jax.jit applied per-call to a lambda /
                               nested closure / bound method — a fresh
                               callable per call means a fresh jit cache
                               and a full XLA retrace every time (the
                               PR 5 `_prune_to_live` eager-closure class:
                               45–115 ms serving stalls per delta).
  DRT002 host-sync-in-hot-path .item() / np.asarray / float() / int() /
                               device_get / block_until_ready inside
                               functions reachable from the train-step /
                               predict roots (call-graph walk) — each is
                               a device round-trip next to the step.
  DRT003 tpu-layout            jnp array literals in ops// embedding/
                               with a small trailing dim ([C, k], k<=8 —
                               TPU lane padding inflates these up to
                               128/k x; the PR 3 `[C,3]` meta leaf would
                               have been 42x) or non-pow2 static 1-D
                               buffer sizes (bucket-ladder misses).
  DRT004 thread-safety         member access on @not_thread_safe objects
                               (HostKV/DiskKV, checkpoint write half) or
                               field writes on @guarded_by objects from
                               functions launched via threading.Thread /
                               executor submit, outside a `with <lock>:`
                               block (the PR 4 background-round HostKV
                               class).
  DRT005 unused-import         mechanical hygiene the visitor reports
                               for free.
  DRT006 shadowed-name         parameters shadowing builtins or module
                               imports.
  DRT007 metric-label-cardinality
                               obs-plane metric constructors
                               (counter/gauge/histogram/
                               register_callback/.labels) whose label
                               VALUE interpolates per-request data — a
                               user id, raw key, request payload — so
                               the series set grows without bound and
                               the registry becomes a memory leak with a
                               /metrics body to match. Label values must
                               come from bounded sets (stage names,
                               table names, member addresses).

Suppression: a trailing ``# noqa: DRT004`` (comma-list allowed) on the
flagged line, ideally with a one-line justification after it. Repo-wide
pre-existing DRT002 noise lives in the checked-in baseline
(analysis/baseline.txt): `--check` fails only on NEW findings — and on
STALE baseline entries, so the baseline can never rot silently;
`--fix-baseline` regenerates it in one command.

The analyzer is pure-AST — it never imports or executes the code under
analysis, so broken dependencies in a module can't break linting it, and
the lint pass itself costs well under a second (the `python -m` CLI
additionally pays the parent package's jax import on startup).
"""
from __future__ import annotations

import ast
import dataclasses
import os
import re
from collections import deque
from typing import Dict, List, Optional, Sequence, Set, Tuple

RULES = {
    "DRT001": "retrace-hazard: per-call jax.jit of a lambda/closure/bound "
              "method",
    "DRT002": "host-sync-in-hot-path: device round-trip reachable from a "
              "train/predict root",
    "DRT003": "tpu-layout: small trailing dim or non-pow2 static buffer in "
              "ops//embedding/",
    "DRT004": "thread-safety: unguarded access to an annotated object from "
              "thread-launched code",
    "DRT005": "unused-import",
    "DRT006": "shadowed-name: parameter shadows a builtin or module import",
    "DRT007": "metric-label-cardinality: metric label value derived from "
              "per-request data",
}

# DRT002 call-graph roots: any function/method with one of these names.
ROOT_NAMES = frozenset({
    "train_step", "train_steps", "train_step_accum", "train_steps_async",
    "predict", "predict_versioned",
})

# DRT002 sync patterns: attribute-call names that force a host sync.
_SYNC_ATTRS = frozenset({"item", "block_until_ready"})
_NP_SYNC_FNS = frozenset({"asarray", "array"})
_JAX_SYNC_FNS = frozenset({"device_get", "block_until_ready"})

# DRT006 builtin shadow set (curated: names that are both plausible
# identifiers and load-bearing builtins).
_SHADOW_BUILTINS = frozenset({
    "id", "type", "input", "vars", "hash", "bytes", "object", "dir",
    "next", "sum", "min", "max", "map", "filter", "list", "dict", "set",
    "str", "int", "float", "bool", "len", "iter", "all", "any", "open",
    "range", "zip", "sorted", "round", "format", "compile", "eval",
})

_NOQA_RE = re.compile(r"#\s*noqa:\s*((?:DRT\d+\s*,?\s*)+)", re.IGNORECASE)


# --------------------------------------------------------------------- model


@dataclasses.dataclass
class Finding:
    rule: str
    path: str          # repo-relative
    line: int
    col: int
    scope: str         # enclosing function qualname ("<module>" otherwise)
    message: str
    snippet: str       # normalized source line (fingerprint component)

    def fingerprint(self) -> str:
        return f"{self.rule}|{self.path}|{self.scope}|{self.snippet}"

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: {self.rule} "
                f"[{self.scope}] {self.message}")


@dataclasses.dataclass
class FuncInfo:
    qual: str                      # "relpath::Class.method"
    name: str                      # simple name
    cls: Optional[str]
    node: ast.AST                  # FunctionDef / AsyncFunctionDef
    module: "Module"
    thread_entry: bool = False


class Module:
    """One parsed source file plus everything the rules need from it."""

    def __init__(self, path: str, relpath: str, source: str):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.noqa: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _NOQA_RE.search(text)
            if m:
                codes = {c.strip().upper()
                         for c in m.group(1).split(",") if c.strip()}
                self.noqa[i] = codes
        # import maps
        self.imports: Dict[str, str] = {}       # local name -> module path
        self.import_nodes: List[Tuple[ast.AST, str]] = []  # (node, name)
        self.np_aliases: Set[str] = set()
        self.jnp_aliases: Set[str] = set()
        self.jax_aliases: Set[str] = set()
        self.jit_names: Set[str] = set()        # bare names bound to jax.jit
        self.partial_names: Set[str] = set()    # functools.partial aliases
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    local = a.asname or a.name.split(".")[0]
                    self.imports[local] = a.name
                    self.import_nodes.append((node, local))
                    if a.name == "numpy":
                        self.np_aliases.add(local)
                    elif a.name == "jax.numpy":
                        self.jnp_aliases.add(local)
                    elif a.name == "jax":
                        self.jax_aliases.add(local)
                    elif a.name == "functools":
                        self.partial_names.add(local + ".partial")
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for a in node.names:
                    if a.name == "*":
                        continue
                    local = a.asname or a.name
                    self.imports[local] = f"{node.module}.{a.name}" \
                        if node.module else a.name
                    self.import_nodes.append((node, local))
                    if node.module == "jax" and a.name == "numpy":
                        self.jnp_aliases.add(local)
                    if node.module == "jax" and a.name == "jit":
                        self.jit_names.add(local)
                    if node.module == "functools" and a.name == "partial":
                        self.partial_names.add(local)
        # function table (methods + module functions; nested defs belong
        # to their enclosing function's body, not the table)
        self.functions: List[FuncInfo] = []
        self._collect_functions(self.tree, cls=None)

    def _collect_functions(self, node, cls: Optional[str]):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                q = f"{self.relpath}::" + (
                    f"{cls}.{child.name}" if cls else child.name
                )
                self.functions.append(FuncInfo(q, child.name, cls, child, self))
            elif isinstance(child, ast.ClassDef):
                self._collect_functions(child, cls=child.name)
            elif isinstance(child, (ast.If, ast.Try)):
                self._collect_functions(child, cls=cls)

    def is_suppressed(self, rule: str, line: int) -> bool:
        return rule in self.noqa.get(line, ())

    def snippet_at(self, line: int) -> str:
        text = self.lines[line - 1] if 0 < line <= len(self.lines) else ""
        text = _NOQA_RE.sub("", text)
        return re.sub(r"\s+", " ", text).strip().replace("|", "¦")[:120]


# ------------------------------------------------------------------- helpers


def _dotted(node) -> str:
    """Best-effort dotted-name text of an expression ('' if not a name)."""
    if isinstance(node, ast.Name):
        return node.id
    if isinstance(node, ast.Attribute):
        base = _dotted(node.value)
        return f"{base}.{node.attr}" if base else node.attr
    return ""


def _is_jit_ref(node, mod: Module) -> bool:
    """Does this expression denote jax.jit (or an alias)?"""
    d = _dotted(node)
    if not d:
        return False
    if d in mod.jit_names:
        return True
    parts = d.split(".")
    return len(parts) == 2 and parts[0] in mod.jax_aliases \
        and parts[1] == "jit"


def _jit_target(call: ast.Call, mod: Module):
    """For a call that produces/applies a jit, the wrapped callable node
    (None when the call is jax.jit(...) used with only kwargs, e.g. as a
    decorator factory)."""
    if _is_jit_ref(call.func, mod):
        return call.args[0] if call.args else None
    # partial(jax.jit, ...) / functools.partial(jax.jit, ...)
    d = _dotted(call.func)
    if d in mod.partial_names and call.args \
            and _is_jit_ref(call.args[0], mod):
        return call.args[1] if len(call.args) > 1 else None
    return None


def _enclosing_functions(tree) -> Dict[ast.AST, ast.AST]:
    """Map every node to its nearest enclosing FunctionDef (or None)."""
    out: Dict[ast.AST, ast.AST] = {}

    def walk(node, fn):
        for child in ast.iter_child_nodes(node):
            out[child] = fn
            walk(child, child if isinstance(
                child, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ) else fn)

    walk(tree, None)
    return out


def _pow2(n: int) -> bool:
    return n > 0 and (n & (n - 1)) == 0


# ----------------------------------------------------------- DRT001 retrace


def _rule_retrace(mod: Module, findings: List[Finding]) -> None:
    encl = _enclosing_functions(mod.tree)
    for fi in mod.functions:
        fn = fi.node
        if fi.name == "__init__":
            # Per-instance jit of bound methods in a constructor is the
            # idiomatic "compile once per object" pattern — callers hold
            # one instance across many calls, so there is no per-call
            # retrace. _make_jits-style rebuilders do NOT get this pass:
            # they are called on budget/plan changes and must justify
            # themselves with a noqa naming the rebuild contract.
            continue
        local_defs = {
            n.name for n in ast.walk(fn)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            and n is not fn
        }
        module_fns = {f.name for f in mod.functions}
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                target = _jit_target(node, mod)
                if target is None:
                    continue
                kind = None
                if isinstance(target, ast.Lambda):
                    kind = "a lambda"
                elif isinstance(target, ast.Attribute):
                    kind = f"bound method .{target.attr}"
                elif isinstance(target, ast.Name) \
                        and target.id in local_defs:
                    kind = f"nested function {target.id}()"
                elif isinstance(target, ast.Name) and (
                    target.id in module_fns or target.id in mod.imports
                ):
                    # jit-ing a module-level / imported function per call
                    # is the same hazard: each jax.jit() call returns a
                    # NEW wrapper with its own empty cache, even for the
                    # identical stable callable.
                    kind = f"function {target.id}() (fresh wrapper per call)"
                if kind:
                    findings.append(Finding(
                        "DRT001", mod.relpath, node.lineno, node.col_offset,
                        fi.qual.split("::")[1],
                        f"jax.jit applied per-call to {kind}: a fresh "
                        "callable per invocation defeats the jit cache and "
                        "retraces every time (PR 5 _prune_to_live class) — "
                        "hoist the wrapper to module/instance scope or "
                        "justify with a noqa",
                        mod.snippet_at(node.lineno),
                    ))
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                    and node is not fn and encl.get(node) is not None:
                for dec in node.decorator_list:
                    c = dec if isinstance(dec, ast.Call) else None
                    if _is_jit_ref(dec, mod) or (
                        c is not None and (
                            _is_jit_ref(c.func, mod)
                            or (_dotted(c.func) in mod.partial_names
                                and c.args and _is_jit_ref(c.args[0], mod))
                        )
                    ):
                        findings.append(Finding(
                            "DRT001", mod.relpath, node.lineno,
                            node.col_offset, fi.qual.split("::")[1],
                            f"@jit on nested function {node.name}() — "
                            "re-decorated (and retraced) on every call of "
                            "the enclosing function",
                            mod.snippet_at(node.lineno),
                        ))


# ------------------------------------------------- DRT002 host-sync hot path


def _build_call_graph(mods: List[Module]):
    """(by_name, edges, alias_map): best-effort package call graph.

    Deliberately an over-approximation — attribute calls resolve to every
    package function of that name, and bare references to package
    functions count as edges (that is what makes lax.scan bodies and
    jit-wrapped impls reachable). False reachability costs a baseline
    entry; a missed edge costs a silent hot-path sync, so the bias is
    chosen."""
    by_name: Dict[str, List[FuncInfo]] = {}
    by_qual: Dict[str, FuncInfo] = {}
    for m in mods:
        for fi in m.functions:
            by_name.setdefault(fi.name, []).append(fi)
            by_qual[fi.qual] = fi
    fn_names = set(by_name)
    # alias map: self.NAME = <expr referencing package function F>
    alias: Dict[str, Set[str]] = {}
    for m in mods:
        for fi in m.functions:
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Assign):
                    continue
                for t in node.targets:
                    if isinstance(t, ast.Attribute) \
                            and isinstance(t.value, ast.Name) \
                            and t.value.id == "self":
                        refs = {
                            n.attr for n in ast.walk(node.value)
                            if isinstance(n, ast.Attribute)
                            and n.attr in fn_names
                        } | {
                            n.id for n in ast.walk(node.value)
                            if isinstance(n, ast.Name) and n.id in fn_names
                        }
                        if refs:
                            alias.setdefault(t.attr, set()).update(refs)

    edges: Dict[str, Set[str]] = {q: set() for q in by_qual}
    for m in mods:
        for fi in m.functions:
            out = edges[fi.qual]
            for node in ast.walk(fi.node):
                names: Set[str] = set()
                if isinstance(node, ast.Call):
                    if isinstance(node.func, ast.Name):
                        n = node.func.id
                        if n in m.imports:
                            leaf = m.imports[n].rsplit(".", 1)[-1]
                            names.add(leaf)
                        names.add(n)
                    elif isinstance(node.func, ast.Attribute):
                        names.add(node.func.attr)
                elif isinstance(node, ast.Attribute):
                    names.add(node.attr)
                elif isinstance(node, ast.Name):
                    names.add(node.id)
                for n in names:
                    for target in alias.get(n, ()):
                        for t in by_name.get(target, ()):
                            out.add(t.qual)
                    for t in by_name.get(n, ()):
                        out.add(t.qual)
    return by_qual, edges


def _reachable(by_qual, edges) -> Dict[str, List[str]]:
    """qual -> chain of simple names from its root (BFS shortest)."""
    chains: Dict[str, List[str]] = {}
    dq = deque()
    for q, fi in by_qual.items():
        if fi.name in ROOT_NAMES:
            chains[q] = [fi.name]
            dq.append(q)
    while dq:
        q = dq.popleft()
        for nxt in edges.get(q, ()):
            if nxt not in chains:
                chains[nxt] = chains[q] + [by_qual[nxt].name]
                dq.append(nxt)
    return chains


def _rule_host_sync(mods: List[Module], findings: List[Finding]) -> None:
    by_qual, edges = _build_call_graph(mods)
    chains = _reachable(by_qual, edges)
    for q, chain in chains.items():
        fi = by_qual[q]
        m = fi.module
        via = " -> ".join(chain[:5]) + (" -> ..." if len(chain) > 5 else "")
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            what = None
            f = node.func
            if isinstance(f, ast.Attribute):
                if f.attr in _SYNC_ATTRS and not node.args:
                    what = f".{f.attr}()"
                elif isinstance(f.value, ast.Name):
                    if f.value.id in m.np_aliases \
                            and f.attr in _NP_SYNC_FNS:
                        what = f"np.{f.attr}()"
                    elif f.value.id in m.jax_aliases \
                            and f.attr in _JAX_SYNC_FNS:
                        what = f"jax.{f.attr}()"
            elif isinstance(f, ast.Name) and f.id in ("float", "int") \
                    and len(node.args) == 1 \
                    and not isinstance(node.args[0], ast.Constant):
                what = f"{f.id}()"
            if what:
                findings.append(Finding(
                    "DRT002", m.relpath, node.lineno, node.col_offset,
                    q.split("::")[1],
                    f"{what} forces a host sync inside a function reachable "
                    f"from a hot-path root ({via}) — move it off the step "
                    "or justify with a noqa",
                    m.snippet_at(node.lineno),
                ))


# ------------------------------------------------------- DRT003 tpu layout


def _rule_layout(mod: Module, findings: List[Finding]) -> None:
    if not ("/ops/" in "/" + mod.relpath or "/embedding/" in "/" + mod.relpath):
        return
    encl = _enclosing_functions(mod.tree)

    def scope_of(node):
        fn = encl.get(node)
        while fn is not None and isinstance(fn, ast.Lambda):
            fn = encl.get(fn)
        return fn.name if fn is not None else "<module>"

    creators = {"zeros", "ones", "full", "empty", "broadcast_to"}
    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute)
                and isinstance(f.value, ast.Name)
                and f.value.id in mod.jnp_aliases
                and f.attr in creators and node.args):
            continue
        shape = node.args[0]
        if not isinstance(shape, ast.Tuple) or not shape.elts:
            continue
        elts = shape.elts
        last = elts[-1]
        if len(elts) >= 2 and isinstance(last, ast.Constant) \
                and isinstance(last.value, int) and 1 <= last.value <= 8:
            lead_big = any(
                not isinstance(e, ast.Constant)
                or (isinstance(e.value, int) and e.value >= 64)
                for e in elts[:-1]
            )
            if lead_big:
                k = last.value
                findings.append(Finding(
                    "DRT003", mod.relpath, node.lineno, node.col_offset,
                    scope_of(node),
                    f"device array with trailing dim {k}: TPU lane padding "
                    f"rounds the minor dim to 128, inflating this buffer "
                    f"~{128 // max(k, 1)}x (the PR 3 [C,3]-vs-[3,C] class) "
                    "— transpose the layout or justify with a noqa",
                    mod.snippet_at(node.lineno),
                ))
        elif len(elts) == 1 and isinstance(last, ast.Constant) \
                and isinstance(last.value, int) and last.value >= 16 \
                and not _pow2(last.value):
            findings.append(Finding(
                "DRT003", mod.relpath, node.lineno, node.col_offset,
                scope_of(node),
                f"static 1-D buffer of non-pow2 size {last.value}: off the "
                "pow2 bucket ladder, every distinct size is its own XLA "
                "shape — quantize the size or justify with a noqa",
                mod.snippet_at(node.lineno),
            ))


# ----------------------------------------------------- DRT004 thread safety


_ANNOT_DECORATORS = {"not_thread_safe", "guarded_by"}


def _annotation_registry(mods: List[Module]):
    """(classes, methods): classes maps name -> (kind, lock); methods is
    the set of simple names of @not_thread_safe functions."""
    classes: Dict[str, Tuple[str, Optional[str]]] = {}
    methods: Set[str] = set()
    for m in mods:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.ClassDef):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    name = _dotted(d).rsplit(".", 1)[-1]
                    if name == "not_thread_safe":
                        classes[node.name] = ("nts", None)
                    elif name == "guarded_by" and isinstance(dec, ast.Call) \
                            and dec.args \
                            and isinstance(dec.args[0], ast.Constant):
                        classes[node.name] = ("guarded", dec.args[0].value)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    d = dec.func if isinstance(dec, ast.Call) else dec
                    if _dotted(d).rsplit(".", 1)[-1] == "not_thread_safe":
                        methods.add(node.name)
    return classes, methods


def _bound_attrs(mods: List[Module], classes) -> Dict[str, str]:
    """Attribute names known to hold instances of annotated classes
    (`self.host = HostKV(...)`, `self.host: Optional[HostKV]`)."""
    bound: Dict[str, str] = {}
    for m in mods:
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Call):
                cname = _dotted(node.value.func).rsplit(".", 1)[-1]
                if cname in classes:
                    for t in node.targets:
                        if isinstance(t, ast.Attribute):
                            bound[t.attr] = cname
            elif isinstance(node, ast.AnnAssign) \
                    and isinstance(node.target, ast.Attribute):
                ann = ast.dump(node.annotation)
                for cname in classes:
                    if f"'{cname}'" in ann:
                        bound[node.target.attr] = cname
    return bound


def _thread_entries(mods: List[Module]) -> Set[str]:
    """Quals of functions launched on threads/executors, closed over
    same-module bare calls and same-class self-method calls."""
    by_qual: Dict[str, FuncInfo] = {}
    for m in mods:
        for fi in m.functions:
            by_qual[fi.qual] = fi

    def resolve(m: Module, cls: Optional[str], name: str) -> List[str]:
        hits = [
            fi.qual for fi in m.functions
            if fi.name == name and (fi.cls == cls or fi.cls is None or
                                    cls is None)
        ]
        if hits:
            return hits
        # cross-module: resolve through this module's imports only
        if name in m.imports:
            leaf = m.imports[name].rsplit(".", 1)[-1]
            return [q for q, fi in by_qual.items() if fi.name == leaf]
        return []

    entries: Set[str] = set()
    for m in mods:
        for fi in m.functions:
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                target = None
                if _dotted(node.func).endswith("Thread"):
                    for kw in node.keywords:
                        if kw.arg == "target":
                            target = kw.value
                elif isinstance(node.func, ast.Attribute) \
                        and node.func.attr == "submit" and node.args:
                    target = node.args[0]
                if target is None:
                    continue
                if isinstance(target, ast.Attribute) and isinstance(
                        target.value, ast.Name) and target.value.id == "self":
                    entries.update(resolve(m, fi.cls, target.attr))
                elif isinstance(target, ast.Name):
                    entries.update(resolve(m, fi.cls, target.id))
    # fixpoint: propagate through self-method and same-module bare calls
    changed = True
    while changed:
        changed = False
        for q in list(entries):
            fi = by_qual.get(q)
            if fi is None:
                continue
            for node in ast.walk(fi.node):
                if not isinstance(node, ast.Call):
                    continue
                tq: List[str] = []
                if isinstance(node.func, ast.Attribute) and isinstance(
                        node.func.value, ast.Name) \
                        and node.func.value.id == "self":
                    tq = [
                        g.qual for g in fi.module.functions
                        if g.name == node.func.attr and g.cls == fi.cls
                    ]
                elif isinstance(node.func, ast.Name):
                    tq = [
                        g.qual for g in fi.module.functions
                        if g.name == node.func.id and g.cls is None
                    ]
                for t in tq:
                    if t not in entries:
                        entries.add(t)
                        changed = True
    return entries


def _with_lock_lines(fn, lock_attrs: Set[str]) -> Set[int]:
    """Line numbers lexically inside a `with <...>.<lockattr>:` block."""
    lines: Set[int] = set()
    for node in ast.walk(fn):
        if not isinstance(node, ast.With):
            continue
        held = any(
            _dotted(item.context_expr).rsplit(".", 1)[-1] in lock_attrs
            for item in node.items
        )
        if held:
            end = getattr(node, "end_lineno", node.lineno)
            lines.update(range(node.lineno, end + 1))
    return lines


def _rule_thread_safety(mods: List[Module], findings: List[Finding]) -> None:
    classes, nts_methods = _annotation_registry(mods)
    if not classes and not nts_methods:
        return
    bound = _bound_attrs(mods, classes)
    entries = _thread_entries(mods)
    lock_attrs = {lock for kind, lock in classes.values() if lock}
    for m in mods:
        for fi in m.functions:
            if fi.qual not in entries:
                continue
            if fi.name in nts_methods:
                continue  # the annotated function itself
            locked = _with_lock_lines(fi.node, lock_attrs)
            for node in ast.walk(fi.node):
                # call of an annotated method: self._write_plan(...)
                if isinstance(node, ast.Call) and isinstance(
                        node.func, ast.Attribute) \
                        and node.func.attr in nts_methods:
                    findings.append(Finding(
                        "DRT004", m.relpath, node.lineno, node.col_offset,
                        fi.qual.split("::")[1],
                        f".{node.func.attr}() is @not_thread_safe and this "
                        "function runs on a spawned thread — serialize "
                        "externally and justify with a noqa naming the "
                        "protocol",
                        m.snippet_at(node.lineno),
                    ))
                    continue
                # member access on a bound annotated instance: *.host.put
                if isinstance(node, ast.Attribute) and isinstance(
                        node.value, ast.Attribute) \
                        and node.value.attr in bound:
                    cname = bound[node.value.attr]
                    kind, lock = classes[cname]
                    if kind == "guarded":
                        is_store = isinstance(
                            node.ctx, (ast.Store, ast.Del)
                        )
                        if not is_store or node.lineno in locked:
                            continue
                        findings.append(Finding(
                            "DRT004", m.relpath, node.lineno,
                            node.col_offset, fi.qual.split("::")[1],
                            f"field write .{node.value.attr}.{node.attr} on "
                            f"@guarded_by('{lock}') {cname} from a spawned "
                            f"thread outside `with {lock}:`",
                            m.snippet_at(node.lineno),
                        ))
                    else:
                        # No lock exemption for NTS: a `with <lock>:`
                        # block proves nothing about WHO ELSE touches the
                        # object (the lock may belong to an unrelated
                        # guarded class) — the contract is an explicit
                        # noqa naming the serialization protocol.
                        findings.append(Finding(
                            "DRT004", m.relpath, node.lineno,
                            node.col_offset, fi.qual.split("::")[1],
                            f".{node.value.attr}.{node.attr} touches "
                            f"@not_thread_safe {cname} from a spawned "
                            "thread — serialize externally and justify "
                            "with a noqa naming the protocol",
                            m.snippet_at(node.lineno),
                        ))


# -------------------------------------------- DRT007 metric label cardinality

# Metric-constructing calls whose label values the rule inspects.
_METRIC_FACTORIES = frozenset({
    "counter", "gauge", "histogram", "register_callback",
})

# Identifier shapes that smell like per-request data. Deliberately
# name-based (this is a static rule): `user_id`, `uid`, `raw_key`,
# `request`, `req`, `query`, `session_id`, `item_id`, `example` —
# underscore-delimited so `table`/`stage`/`shard` never match.
_REQ_NAME_RE = re.compile(
    r"(?:^|_)(user|uid|key|request|req|query|session|item|example|row|id)"
    r"s?(?:_|$)",
    re.IGNORECASE,
)


def _per_request_refs(expr: ast.AST) -> List[str]:
    """Names inside `expr` (including through f-strings, str() calls,
    attributes, subscripts) that match the per-request pattern."""
    hits = []
    for node in ast.walk(expr):
        if isinstance(node, ast.Name) and _REQ_NAME_RE.search(node.id):
            hits.append(node.id)
        elif isinstance(node, ast.Attribute) and \
                _REQ_NAME_RE.search(node.attr):
            hits.append(node.attr)
    return hits


def _label_dict_of(call: ast.Call) -> Optional[ast.Dict]:
    """The labels dict literal of a metric-factory call, if visible:
    `labels={...}` kwarg, or ANY positional dict literal — the factories
    take labels at different positions (counter/gauge/histogram: (name,
    help, labels); register_callback: (name, fn, help, labels)), and a
    dict literal in a metric-factory call is a labels dict in every
    idiom this rule covers."""
    for kw in call.keywords:
        if kw.arg == "labels" and isinstance(kw.value, ast.Dict):
            return kw.value
    for a in call.args:
        if isinstance(a, ast.Dict):
            return a
    return None


def _rule_label_cardinality(mod: Module, findings: List[Finding]) -> None:
    encl = _enclosing_functions(mod.tree)

    def scope_of(node):
        fn = encl.get(node)
        while fn is not None and isinstance(fn, ast.Lambda):
            fn = encl.get(fn)
        return fn.name if fn is not None else "<module>"

    def flag(node, label, refs):
        findings.append(Finding(
            "DRT007", mod.relpath, node.lineno, node.col_offset,
            scope_of(node),
            f"metric label {label} takes a value derived from per-request "
            f"data ({', '.join(sorted(set(refs)))}): unbounded series "
            "cardinality — label from a bounded set instead, or justify "
            "with a noqa",
            mod.snippet_at(node.lineno),
        ))

    for node in ast.walk(mod.tree):
        if not isinstance(node, ast.Call) or \
                not isinstance(node.func, ast.Attribute):
            continue
        attr = node.func.attr
        if attr in _METRIC_FACTORIES:
            d = _label_dict_of(node)
            if d is None:
                continue
            for k, v in zip(d.keys, d.values):
                refs = _per_request_refs(v)
                if refs:
                    key = (repr(k.value) if isinstance(k, ast.Constant)
                           else "<dynamic>")
                    flag(node, key, refs)
        elif attr == "labels":
            # prometheus-client idiom: metric.labels(user=uid, ...)
            for kw in node.keywords:
                if kw.arg is None:
                    continue
                refs = _per_request_refs(kw.value)
                if refs:
                    flag(node, repr(kw.arg), refs)
            for a in node.args:
                refs = _per_request_refs(a)
                if refs:
                    flag(node, "<positional>", refs)


# --------------------------------------------------- DRT005 / DRT006 hygiene


def _rule_unused_imports(mod: Module, findings: List[Finding]) -> None:
    if os.path.basename(mod.relpath) == "__init__.py":
        return  # re-export surface
    used: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Name):
            used.add(node.id)
        elif isinstance(node, ast.Attribute):
            d = _dotted(node)
            if d:
                used.add(d.split(".")[0])
    # string-typed annotations / __all__ entries
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str) \
                and node.value.isidentifier():
            used.add(node.value)
    for node, local in mod.import_nodes:
        if local not in used:
            findings.append(Finding(
                "DRT005", mod.relpath, node.lineno, node.col_offset,
                "<module>",
                f"import {local!r} is unused",
                mod.snippet_at(node.lineno),
            ))


def _rule_shadowed_names(mod: Module, findings: List[Finding]) -> None:
    module_imports = set(mod.imports)
    for fi in mod.functions:
        args = fi.node.args
        params = (
            list(args.posonlyargs) + list(args.args)
            + list(args.kwonlyargs)
            + ([args.vararg] if args.vararg else [])
            + ([args.kwarg] if args.kwarg else [])
        )
        for a in params:
            shadowed = None
            if a.arg in _SHADOW_BUILTINS:
                shadowed = "builtin"
            elif a.arg in module_imports:
                shadowed = "module import"
            if shadowed:
                findings.append(Finding(
                    "DRT006", mod.relpath, a.lineno, a.col_offset,
                    fi.qual.split("::")[1],
                    f"parameter {a.arg!r} shadows a {shadowed}",
                    mod.snippet_at(a.lineno),
                ))


# --------------------------------------------------------------- the engine


DEFAULT_TARGETS = ("deeprec_tpu", "tools", "bench.py")


def repo_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def default_baseline_path() -> str:
    return os.path.join(repo_root(), "deeprec_tpu", "analysis",
                        "baseline.txt")


def collect_modules(root: str, targets: Sequence[str] = DEFAULT_TARGETS,
                    source_overrides: Optional[Dict[str, str]] = None
                    ) -> List[Module]:
    overrides = {
        os.path.abspath(k): v for k, v in (source_overrides or {}).items()
    }
    paths: List[str] = []
    for t in targets:
        p = os.path.join(root, t)
        if os.path.isfile(p) and p.endswith(".py"):
            paths.append(p)
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [
                    d for d in dirnames
                    if d != "__pycache__" and not d.startswith(".")
                ]
                for f in sorted(filenames):
                    if f.endswith(".py"):
                        paths.append(os.path.join(dirpath, f))
    mods = []
    for p in sorted(set(paths)):
        ap = os.path.abspath(p)
        if ap in overrides:
            src = overrides[ap]
        else:
            with open(p, encoding="utf-8") as f:
                src = f.read()
        rel = os.path.relpath(p, root)
        try:
            mods.append(Module(p, rel, src))
        except SyntaxError as e:
            raise SyntaxError(f"{rel}: {e}") from e
    return mods


def run_rules(mods: List[Module],
              rules: Optional[Sequence[str]] = None) -> List[Finding]:
    want = set(rules or RULES)
    findings: List[Finding] = []
    for m in mods:
        if "DRT001" in want:
            _rule_retrace(m, findings)
        if "DRT003" in want:
            _rule_layout(m, findings)
        if "DRT005" in want:
            _rule_unused_imports(m, findings)
        if "DRT006" in want:
            _rule_shadowed_names(m, findings)
        if "DRT007" in want:
            _rule_label_cardinality(m, findings)
    if "DRT002" in want:
        _rule_host_sync(mods, findings)
    if "DRT004" in want:
        _rule_thread_safety(mods, findings)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings


def split_suppressed(mods: List[Module], findings: List[Finding]):
    by_rel = {m.relpath: m for m in mods}
    active, suppressed = [], []
    for f in findings:
        m = by_rel.get(f.path)
        if m is not None and m.is_suppressed(f.rule, f.line):
            suppressed.append(f)
        else:
            active.append(f)
    return active, suppressed


def fingerprints(findings: List[Finding]) -> List[str]:
    """Stable, line-number-free identities; duplicates within the same
    (rule, file, scope, snippet) get an ordinal suffix."""
    seen: Dict[str, int] = {}
    out = []
    for f in findings:
        base = f.fingerprint()
        n = seen.get(base, 0) + 1
        seen[base] = n
        out.append(base if n == 1 else f"{base}|#{n}")
    return out


BASELINE_HEADER = """\
# deeprec_tpu.analysis baseline — pre-existing findings `--check` ignores.
# One line per accepted finding: RULE|path|scope|normalized-snippet[|#n].
# Entries are line-number-free so ordinary edits don't churn them; an
# entry whose finding no longer exists is STALE and fails the check.
# Regenerate intentionally with: python -m deeprec_tpu.analysis --fix-baseline
"""


def load_baseline(path: str) -> List[str]:
    if not os.path.exists(path):
        return []
    with open(path, encoding="utf-8") as f:
        return [
            ln.rstrip("\n") for ln in f
            if ln.strip() and not ln.startswith("#")
        ]


def write_baseline(path: str, findings: List[Finding]) -> None:
    with open(path, "w", encoding="utf-8") as f:
        f.write(BASELINE_HEADER)
        for fp in sorted(fingerprints(findings)):
            f.write(fp + "\n")


def check(root: Optional[str] = None,
          targets: Sequence[str] = DEFAULT_TARGETS,
          baseline_path: Optional[str] = None,
          rules: Optional[Sequence[str]] = None,
          fix_baseline: bool = False,
          source_overrides: Optional[Dict[str, str]] = None,
          out=None) -> int:
    """The CLI core. Returns the process exit code."""
    import sys

    out = out or sys.stdout
    root = root or repo_root()
    baseline_path = baseline_path or default_baseline_path()
    mods = collect_modules(root, targets, source_overrides)
    findings = run_rules(mods, rules)
    active, suppressed = split_suppressed(mods, findings)
    if fix_baseline:
        write_baseline(baseline_path, active)
        print(
            f"analysis: baseline rewritten with {len(active)} finding(s) "
            f"({len(suppressed)} noqa-suppressed) -> {baseline_path}",
            file=out,
        )
        return 0
    base = load_baseline(baseline_path)
    fps = fingerprints(active)
    by_fp = dict(zip(fps, active))
    base_set = set(base)
    new = [fp for fp in fps if fp not in base_set]
    # Staleness only against entries this run COULD have produced: a
    # --rules invocation must not report other rules' entries as fixed,
    # and a path-restricted scan skips staleness entirely — DRT002
    # reachability depends on the whole package, so a partial scan
    # produces a subset of findings for reasons that are not fixes.
    # (New-finding detection above still works for focused runs.)
    if tuple(targets) == tuple(DEFAULT_TARGETS):
        want_rules = set(rules or RULES)
        relevant = {
            e for e in base_set if e.split("|", 2)[0] in want_rules
        }
        stale = sorted(relevant - set(fps))
    else:
        stale = []
    rc = 0
    if new:
        rc = 1
        print(f"analysis: {len(new)} NEW finding(s):", file=out)
        for fp in new:
            print("  " + by_fp[fp].render(), file=out)
    if stale:
        rc = 1
        print(
            f"analysis: {len(stale)} STALE baseline entr(y/ies) — the "
            "finding was fixed (good!) but the baseline still lists it; "
            "run --fix-baseline:", file=out,
        )
        for fp in stale:
            print("  " + fp, file=out)
    if rc == 0:
        counts: Dict[str, int] = {}
        for f in findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        summary = ", ".join(f"{r}:{n}" for r, n in sorted(counts.items()))
        print(
            f"analysis: ok — {len(findings)} finding(s) all accounted for "
            f"({len(suppressed)} noqa, {len(base)} baselined; {summary})",
            file=out,
        )
    return rc
