"""Thread-safety annotation vocabulary for the static analyzer.

The runtime half is deliberately boring: decorators that stamp metadata
attributes and return the object unchanged (zero import weight, zero
call overhead). The value lives in `deeprec_tpu.analysis.lint`, which
reads the decorators SYNTACTICALLY — annotate a class or method here and
rule DRT004 starts flagging accesses to it from code launched via
`threading.Thread` / executor `submit`: @not_thread_safe accesses always
(only an explicit noqa naming the protocol clears them — a ``with``
block proves nothing about who else touches the object), @guarded_by
field writes unless inside ``with <lock>:`` (see docs/analysis.md).

Vocabulary:

``@not_thread_safe``
    The object has no internal synchronization at all. Touching it from
    a background thread is only correct under some EXTERNAL serialization
    protocol (a drain barrier, a single-writer invariant); every such
    access must carry a ``# noqa: DRT004`` naming that protocol. The
    canonical instances are ``HostKV``/``DiskKV`` (the tier-IO worker
    owns them between ``sync_async()`` and ``_settle()`` — the PR 4
    review class) and ``CheckpointManager``'s write half (at most one
    writer thread in flight, drained by ``wait()``).

``@guarded_by("lockattr")``
    The object's FIELDS are protected by ``self.<lockattr>``; its methods
    take the lock internally and form the thread-safe API. The lint flags
    direct field writes on instances from thread-launched code outside a
    ``with <lockattr>:`` block — calling methods is always fine.
    ``ServingStats`` is the canonical instance.
"""
from __future__ import annotations

NOT_THREAD_SAFE_ATTR = "__deeprec_not_thread_safe__"
GUARDED_BY_ATTR = "__deeprec_guarded_by__"


def not_thread_safe(obj):
    """Mark a class or function as having no internal synchronization."""
    setattr(obj, NOT_THREAD_SAFE_ATTR, True)
    return obj


def guarded_by(lock_attr: str):
    """Mark a class whose fields are guarded by ``self.<lock_attr>``."""

    def mark(obj):
        setattr(obj, GUARDED_BY_ATTR, lock_attr)
        return obj

    return mark


def is_not_thread_safe(obj) -> bool:
    return bool(getattr(obj, NOT_THREAD_SAFE_ATTR, False))


def guard_lock_of(obj):
    """The guarding lock attribute name, or None."""
    return getattr(obj, GUARDED_BY_ATTR, None)
