"""Applying sparse gradients to a table — the KvResourceSparseApply* executor.

Pipeline (mirrors DeepRec's backward path, SURVEY.md §3.1): autodiff produces
gradients w.r.t. the *unique* gathered embeddings; this module gathers the
matching value/slot rows, runs the optimizer row-function, masks out invalid /
filter-blocked keys, and scatters everything back. One fused pass over [U, D].

U is whatever the dedup produced: the full flattened batch on the legacy
path, or the static unique BUDGET under the hash dedup engine
(ops/dedup.py) — the whole gather->update->scatter pass shrinks with it.
Budget-overflowed ids never reach here as rows: their positions point at
the reserved sentinel entry (uids[0], valid=False), which the `ok` mask
below drops exactly like a filter-blocked key.
"""
from __future__ import annotations

from typing import Dict, Optional

import jax.numpy as jnp

from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup
from deeprec_tpu.optim.sparse import SCALAR_PREFIX, SparseOptimizer


def ensure_slots(
    table: EmbeddingTable, state: TableState, opt: SparseOptimizer
) -> TableState:
    """Create the optimizer's slot arrays for this table (idempotent).

    The analog of slot-variable creation in DeepRec's optimizers
    (python/training/adam_async.py etc.), with slots packed next to values.
    """
    C, D = state.capacity, state.dim
    slots = dict(state.slots)
    for name, (shape, init) in opt.slot_specs(D).items():
        if name in slots:
            continue
        if name.startswith(SCALAR_PREFIX):
            slots[name] = jnp.full((1, 1), init, jnp.float32)
        else:
            # Per-row slots share the packed small-dim layout policy of the
            # values array (ops/packed.py, gated by cfg.packed): a [C, 1]
            # accumulator padded to 128 lanes would waste 128x HBM on TPU.
            (w,) = tuple(shape)
            P = table.pack_width(w, C)
            slots[name] = jnp.full((C // P, P * w), init, jnp.float32)
    return state.replace(slots=slots)


def apply_gradients(
    table: EmbeddingTable,
    state: TableState,
    opt: SparseOptimizer,
    res: UniqueLookup,
    grad_u: jnp.ndarray,  # [U, D] grads w.r.t. res.embeddings
    *,
    step: jnp.ndarray | int = 0,
    lr: Optional[jnp.ndarray | float] = None,
    grad_averaging: bool = False,
    reuse_rows: bool = False,
    stamp_meta: bool = True,
) -> TableState:
    """Update the touched rows of `state` in one compute→scatter pass.

    Traffic diet (docs/perf.md "traffic diet"), opted into by the trainer
    hot paths via `reuse_rows=True, stamp_meta=False`: the value rows this
    apply needs were already gathered by the same-step train lookup and
    ride in `res.rows` — reusing them deletes a whole [U, D] gather, and
    the lookup's fused metadata scatter already stamped version/dirty for
    every touched row, so the apply-side pair is redundant too.

    The diet is only valid when nothing wrote the touched value rows
    between the lookup that produced `res` and this apply, and when a
    same-step TRAIN lookup stamped the rows' metadata. The trainers
    enforce that precondition (and the shared-table / async paths where it
    fails keep these safe defaults — see Trainer._bundle_reuse_rows and
    AsyncShardedTrainer._apply_one); standalone callers get the legacy
    re-gather + re-stamp behavior, correct for every call pattern
    (repeated applies of one `res`, interleaved scatter_update, ...).
    """
    step = jnp.asarray(step, jnp.int32)
    lr = jnp.asarray(opt.lr if lr is None else lr, jnp.float32)

    ok = (res.slot_ix >= 0) & res.valid & res.admitted  # [U]
    safe_ix = jnp.where(ok, res.slot_ix, 0)
    drop_ix = jnp.where(ok, res.slot_ix, state.capacity)

    grad = grad_u.astype(jnp.float32)
    if grad_averaging:
        grad = grad / jnp.maximum(res.counts.astype(jnp.float32), 1.0)[:, None]

    if reuse_rows and res.rows.size:
        value = res.rows.astype(jnp.float32)
    else:
        value = table._gather(state.values, safe_ix, state.capacity).astype(
            jnp.float32
        )
    from deeprec_tpu.ops.packed import gather_rows_any, scatter_rows_any

    row_slots: Dict[str, jnp.ndarray] = {}
    for name, arr in state.slots.items():
        if name.startswith(SCALAR_PREFIX):
            row_slots[name] = arr  # [1, 1] per-table scalar, passed through
        else:
            row_slots[name] = gather_rows_any(
                arr, safe_ix, state.capacity,
                use_pallas=table.use_pallas,
                pair_kernels=table.pair_kernels,
            )

    new_value, new_slots = opt.update(value, row_slots, grad, res.counts, step, lr)

    # The values write-back goes through apply_rows_sr (packed-layout
    # aware): bf16 tables get stochastic rounding (plain round-to-nearest
    # silently drops updates smaller than ulp/2), f32 tables an exact
    # masked scatter; the Pallas DMA kernel serves tables opted into it.
    values = table._scatter(
        state.values, jnp.where(ok, res.slot_ix, -1), new_value,
        state.capacity, seed=step,
    )
    slots = dict(state.slots)
    for name, rows in new_slots.items():
        if name.startswith(SCALAR_PREFIX):
            slots[name] = rows
        else:
            slots[name] = scatter_rows_any(
                state.slots[name], jnp.where(ok, res.slot_ix, -1), rows,
                state.capacity, seed=step,
                use_pallas=table.use_pallas,
                pair_kernels=table.pair_kernels,
            )
    if stamp_meta:
        from deeprec_tpu.embedding.table import META_DIRTY, META_VERSION

        meta = state.meta.at[META_VERSION, drop_ix].set(step, mode="drop")
        meta = meta.at[META_DIRTY, drop_ix].set(1, mode="drop")
        return state.replace(values=values, slots=slots, meta=meta)
    return state.replace(values=values, slots=slots)


def apply_bag_gradients(
    table: EmbeddingTable,
    state: TableState,
    opt: SparseOptimizer,
    res,  # ops.fused_lookup.FusedBags from a matching bag_forward
    grad_out: jnp.ndarray,  # [B, D] grads w.r.t. res.out
    row_ix: jnp.ndarray,  # [B, L] resolved slot indices fed to bag_forward
    *,
    combiner: str = "mean",
    step: jnp.ndarray | int = 0,
    lr: Optional[jnp.ndarray | float] = None,
    grad_averaging: bool = False,
    interpret: bool = False,
    stamp_meta: bool = True,
) -> TableState:
    """The fused-step analog of apply_gradients: one pass segment-sums the
    per-bag grads [B, D] into unique-row space and applies the optimizer
    update fused into the scatter (ops/fused_lookup.fused_sparse_backward),
    so per-row grads never materialize outside the kernel.

    `res` must come from `table.bag_forward(state, row_ix, ...)` with the
    SAME combiner; `row_ix` is the [B, L] resolved slot indices (< 0 = pad)
    that produced it. Requires a fusable optimizer (no scalar slots, all
    slots [dim]-shaped — fused_lookup.fusable_optimizer) and the unpacked
    row layout; callers outside that envelope use apply_gradients.
    """
    from deeprec_tpu.ops import fused_lookup as fl
    from deeprec_tpu.ops.packed import is_unpacked

    if not fl.fusable_optimizer(opt, state.dim):
        raise NotImplementedError(
            f"apply_bag_gradients: optimizer {type(opt).__name__} has "
            "scalar or non-[dim] slots; use apply_gradients"
        )
    if not is_unpacked(state.values, state.capacity):
        raise NotImplementedError(
            "apply_bag_gradients: packed small-dim layouts keep the "
            "split-phase apply_gradients path"
        )
    values, slots = fl.fused_sparse_backward(
        state.values, dict(state.slots), grad_out, row_ix, res, opt,
        combiner=combiner, step=step, lr=lr, seed=step,
        grad_averaging=grad_averaging, interpret=interpret,
        use_pallas=table.fused_step,
    )
    if stamp_meta:
        from deeprec_tpu.embedding.table import META_DIRTY, META_VERSION

        # uids[0] is the reserved sentinel (-1) and overflow rows stay
        # negative — route both to the dropped C lane.
        drop_ix = jnp.where(res.uids >= 0, res.uids, state.capacity)
        meta = state.meta.at[META_VERSION, drop_ix].set(step, mode="drop")
        meta = meta.at[META_DIRTY, drop_ix].set(1, mode="drop")
        return state.replace(values=values, slots=slots, meta=meta)
    return state.replace(values=values, slots=slots)
