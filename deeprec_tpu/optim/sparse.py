"""Sparse optimizers for hash-embedding tables.

DeepRec registers 88 KvResourceSparseApply* ops (/root/reference/tensorflow/
core/ops/training_ali_ops.cc; kernels core/kernels/training_ali_ops.cc) —
per-key slot updates executed inside the PS. Here each optimizer is a pure
row-function: it receives the gathered value/slot rows for the unique touched
keys ([U, D]) plus per-key batch counts, and returns updated rows which the
table scatters back. XLA fuses the whole thing into one pass over [U, D],
where U is the dedup width — the unique BUDGET when the hash dedup engine
(ops/dedup.py) is engaged, so the optimizer pass shrinks with it too.

`*WithCounts` semantics: DeepRec's WithCounts variants thread the per-key
occurrence count through the apply so frequency is recorded and (for some
optimizers) the gradient is de-duplicated. Our tables update `freq` at lookup
time; here `counts` optionally averages the summed duplicate gradients
(`grad_averaging=True`).

Slot layout: slots live in TableState.slots as [C, D] (or [C, 1]) arrays next
to the values — the TPU translation of DeepRec storing slot EVs alongside the
primary EV. Per-table scalar state (AdamAsync beta powers) is kept as [1, 1]
arrays, exempt from rebuild row-moves.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

Array = jnp.ndarray
Slots = Dict[str, Array]

# Slot names with this prefix are per-table scalars, not per-key rows.
SCALAR_PREFIX = "scalar/"


@dataclasses.dataclass(frozen=True)
class SparseOptimizer:
    """Base: hyperparameters are static floats; `lr` may be overridden per
    apply-call with a traced scalar (for schedules without recompiles)."""

    lr: float = 0.01

    def slot_specs(self, dim: int) -> Dict[str, Tuple[Tuple[int, ...], float]]:
        """name -> (row_shape, init_value). Row shape (dim,) or (1,)."""
        return {}

    def update(
        self,
        value: Array,  # [U, D]
        slots: Slots,  # each [U, D]/[U, 1] (scalars delivered as [1, 1])
        grad: Array,  # [U, D] summed over duplicates
        counts: Array,  # [U] int32
        step: Array,  # [] int32 global step
        lr: Array,  # [] learning rate
    ) -> Tuple[Array, Slots]:
        raise NotImplementedError


@dataclasses.dataclass(frozen=True)
class GradientDescent(SparseOptimizer):
    """KvResourceSparseApplyGradientDescent."""

    def update(self, value, slots, grad, counts, step, lr):
        return value - lr * grad, {}


@dataclasses.dataclass(frozen=True)
class Adagrad(SparseOptimizer):
    """KvResourceSparseApplyAdagrad (training_ali_ops.cc)."""

    initial_accumulator_value: float = 0.1

    def slot_specs(self, dim):
        return {"accum": ((dim,), self.initial_accumulator_value)}

    def update(self, value, slots, grad, counts, step, lr):
        acc = slots["accum"] + grad * grad
        # guard acc==0 (possible after external slot resets + zero grad):
        # rsqrt(0) would turn a zero update into NaN
        new_value = value - lr * grad * jax.lax.rsqrt(jnp.maximum(acc, 1e-30))
        return new_value, {"accum": acc}


@dataclasses.dataclass(frozen=True)
class AdagradDecay(SparseOptimizer):
    """KvResourceSparseApplyAdagradDecay — Adagrad whose accumulator is
    periodically discounted so ancient history fades (semantics:
    docs/docs_en/AdagradDecay-Optimizer.md: every `accumulator_decay_step`
    global steps the accumulator is scaled by `accumulator_decay_rate` with a
    floor of `accumulator_baseline`). Sparse keys apply the decay lazily: the
    number of elapsed decay periods since the key's last update is derived
    from a per-key period slot."""

    initial_accumulator_value: float = 0.1
    accumulator_decay_step: int = 100000
    accumulator_decay_rate: float = 0.9
    accumulator_baseline: float = 0.0

    def slot_specs(self, dim):
        return {
            "accum": ((dim,), self.initial_accumulator_value),
            "decay_period": ((1,), 0.0),
        }

    def update(self, value, slots, grad, counts, step, lr):
        period = (step // jnp.int32(self.accumulator_decay_step)).astype(jnp.float32)
        # decay_period stores (last applied period + 1); 0 marks a
        # never-updated key, whose fresh accumulator must NOT be decayed
        # retroactively by the current global period.
        stored = slots["decay_period"][:, 0]
        elapsed = jnp.where(stored > 0.0, jnp.maximum(period - (stored - 1.0), 0.0), 0.0)
        scale = jnp.power(self.accumulator_decay_rate, elapsed)[:, None]
        acc = jnp.maximum(slots["accum"] * scale, self.accumulator_baseline)
        acc = acc + grad * grad
        new_value = value - lr * grad * jax.lax.rsqrt(jnp.maximum(acc, 1e-30))
        new_period = jnp.full_like(slots["decay_period"], 0.0) + period + 1.0
        return new_value, {"accum": acc, "decay_period": new_period}


@dataclasses.dataclass(frozen=True)
class Adam(SparseOptimizer):
    """KvResourceSparseApplyAdam — bias correction from the global step."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8

    def slot_specs(self, dim):
        return {"m": ((dim,), 0.0), "v": ((dim,), 0.0)}

    def update(self, value, slots, grad, counts, step, lr):
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
        v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
        # bias-corrected step size: lr * sqrt(1 - b2^t) / (1 - b1^t)
        alpha = lr * jnp.sqrt(1.0 - jnp.power(self.beta2, t)) / (
            1.0 - jnp.power(self.beta1, t)
        )
        new_value = value - alpha * m / (jnp.sqrt(v) + self.epsilon)
        return new_value, {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class AdamAsync(SparseOptimizer):
    """KvResourceSparseApplyAdamAsync (docs/docs_en/AdamAsync-Optimizer.md):
    designed for async-PS training — beta powers live as *per-variable slots*
    advanced on every apply instead of reading the global step, so stale/
    lock-free updates stay well-scaled. With `apply_sparse_rmsprop` the update
    skips momentum bias correction and uses an RMSProp-style step (the doc's
    sparse variant).

    In a synchronous SPMD world the convergence-relevant part is the
    per-variable power schedule, which is reproduced exactly; equivalence with
    the async execution model is at the AUC level (SURVEY.md §7 hard parts e).
    """

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    apply_sparse_rmsprop: bool = False

    def slot_specs(self, dim):
        return {
            "m": ((dim,), 0.0),
            "v": ((dim,), 0.0),
            SCALAR_PREFIX + "beta1_power": ((1,), self.beta1),
            SCALAR_PREFIX + "beta2_power": ((1,), self.beta2),
        }

    def update(self, value, slots, grad, counts, step, lr):
        b1p = slots[SCALAR_PREFIX + "beta1_power"][0, 0]
        b2p = slots[SCALAR_PREFIX + "beta2_power"][0, 0]
        if self.apply_sparse_rmsprop:
            v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
            m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
            new_value = value - lr * m * jax.lax.rsqrt(v + self.epsilon)
        else:
            m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
            v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
            alpha = lr * jnp.sqrt(1.0 - b2p) / (1.0 - b1p)
            new_value = value - alpha * m / (jnp.sqrt(v) + self.epsilon)
        return new_value, {
            "m": m,
            "v": v,
            SCALAR_PREFIX + "beta1_power": slots[SCALAR_PREFIX + "beta1_power"]
            * self.beta1,
            SCALAR_PREFIX + "beta2_power": slots[SCALAR_PREFIX + "beta2_power"]
            * self.beta2,
        }


@dataclasses.dataclass(frozen=True)
class AdamW(SparseOptimizer):
    """KvResourceSparseApplyAdamW — Adam with decoupled weight decay."""

    beta1: float = 0.9
    beta2: float = 0.999
    epsilon: float = 1e-8
    weight_decay: float = 0.01

    def slot_specs(self, dim):
        return {"m": ((dim,), 0.0), "v": ((dim,), 0.0)}

    def update(self, value, slots, grad, counts, step, lr):
        t = (step + 1).astype(jnp.float32)
        m = self.beta1 * slots["m"] + (1.0 - self.beta1) * grad
        v = self.beta2 * slots["v"] + (1.0 - self.beta2) * grad * grad
        alpha = lr * jnp.sqrt(1.0 - jnp.power(self.beta2, t)) / (
            1.0 - jnp.power(self.beta1, t)
        )
        new_value = value - alpha * (
            m / (jnp.sqrt(v) + self.epsilon)
        ) - lr * self.weight_decay * value
        return new_value, {"m": m, "v": v}


@dataclasses.dataclass(frozen=True)
class Ftrl(SparseOptimizer):
    """KvResourceSparseApplyFtrl — FTRL-proximal, the classic CTR optimizer."""

    learning_rate_power: float = -0.5
    initial_accumulator_value: float = 0.1
    l1: float = 0.0
    l2: float = 0.0

    def slot_specs(self, dim):
        return {
            "accum": ((dim,), self.initial_accumulator_value),
            "linear": ((dim,), 0.0),
        }

    def update(self, value, slots, grad, counts, step, lr):
        accum, linear = slots["accum"], slots["linear"]
        new_accum = accum + grad * grad
        p = -self.learning_rate_power
        sigma = (jnp.power(new_accum, p) - jnp.power(accum, p)) / lr
        linear = linear + grad - sigma * value
        quad = jnp.power(new_accum, p) / lr + 2.0 * self.l2
        l1_reg = self.l1 * jnp.sign(linear)
        new_value = jnp.where(
            jnp.abs(linear) > self.l1, (l1_reg - linear) / quad, 0.0
        )
        return new_value, {"accum": new_accum, "linear": linear}


REGISTRY = {
    "sgd": GradientDescent,
    "adagrad": Adagrad,
    "adagrad_decay": AdagradDecay,
    "adam": Adam,
    "adam_async": AdamAsync,
    "adamw": AdamW,
    "ftrl": Ftrl,
}


def make(name: str, **kw) -> SparseOptimizer:
    return REGISTRY[name](**kw)
