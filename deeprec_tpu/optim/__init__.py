from deeprec_tpu.optim.sparse import (
    REGISTRY,
    Adagrad,
    AdagradDecay,
    Adam,
    AdamAsync,
    AdamW,
    Ftrl,
    GradientDescent,
    SparseOptimizer,
    make,
)
from deeprec_tpu.optim.apply import apply_gradients, ensure_slots
