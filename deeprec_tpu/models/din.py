"""DIN — Deep Interest Network (reference modelzoo/din/train.py): local
activation unit attends over the user's behavior sequence conditioned on the
target item; attention-pooled history + target + user feed an MLP head."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.models.taobao import behavior_features


@dataclasses.dataclass
class DIN:
    emb_dim: int = 16
    capacity: int = 1 << 16
    att_hidden: Sequence[int] = (36,)
    hidden: Sequence[int] = (200, 80)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        self.features = behavior_features(self.emb_dim, self.capacity, self.ev)

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        D = 2 * self.emb_dim  # item ++ cat
        in_dim = self.emb_dim + 2 * D  # user + target + attended-history
        return {
            "att": nn.din_attention_init(k1, D, self.att_hidden),
            "mlp": nn.mlp_init(k2, in_dim, list(self.hidden) + [1]),
        }

    def _sequences(self, inputs):
        hist_i, mask = inputs.seq["hist_items"]
        hist_c, _ = inputs.seq["hist_cats"]
        hist = jnp.concatenate([hist_i, hist_c], axis=-1)  # [B, L, 2d]
        target = jnp.concatenate(
            [inputs.pooled["target_item"], inputs.pooled["target_cat"]], axis=-1
        )  # [B, 2d]
        return hist, mask, target

    def apply(self, params, inputs, train: bool):
        hist, mask, target = self._sequences(inputs)
        attended = nn.din_attention_apply(params["att"], target, hist, mask)
        x = jnp.concatenate([inputs.pooled["user"], target, attended], axis=-1)
        return nn.mlp_apply(params["mlp"], x, activation=jax.nn.sigmoid)[:, 0]
