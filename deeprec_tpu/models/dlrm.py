"""DLRM on Criteo — the north-star benchmark model
(/root/reference/modelzoo/dlrm/train.py): bottom MLP over numerics, dim-d
embeddings per categorical field, pairwise dot interactions, top MLP."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import CRITEO_CAT, CRITEO_DENSE, criteo_features


@dataclasses.dataclass
class DLRM:
    emb_dim: int = 16
    capacity: int = 1 << 16
    bottom: Sequence[int] = (512, 256, 64, 16)
    top: Sequence[int] = (512, 256, 1)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()
    num_cat: int = len(CRITEO_CAT)
    num_dense: int = len(CRITEO_DENSE)

    def __post_init__(self):
        assert self.bottom[-1] == self.emb_dim, "bottom MLP must end at emb_dim"
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    def init(self, key):
        k1, k2 = jax.random.split(key)
        F = self.num_cat + 1
        inter = F * (F - 1) // 2
        return {
            "bottom": nn.mlp_init(k1, self.num_dense, list(self.bottom)),
            "top": nn.mlp_init(k2, inter + self.emb_dim, list(self.top)),
        }

    def apply(self, params, inputs, train: bool):
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], axis=-1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        bottom = nn.mlp_apply(params["bottom"], dense, final_activation=jax.nn.relu)
        embs = jnp.stack([inputs.pooled[c] for c in self._cats], axis=1)  # [B,F,D]
        stack = jnp.concatenate([bottom[:, None, :], embs], axis=1)
        inter = nn.dot_interaction(stack)
        top_in = jnp.concatenate([bottom, inter], axis=-1)
        return nn.mlp_apply(params["top"], top_in)[:, 0]


@dataclasses.dataclass
class DLRMDCN(DLRM):
    """DLRM_DCN — the MLPerf 2022 configuration the reference ships as
    modelzoo/mlperf/train.py: dot-product interactions replaced by a DCNv2
    cross network over [bottom | field embeddings]."""

    cross_depth: int = 3

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        w = (self.num_cat + 1) * self.emb_dim
        return {
            "bottom": nn.mlp_init(k1, self.num_dense, list(self.bottom)),
            "cross": nn.crossnet_init(k2, w, self.cross_depth),
            "top": nn.mlp_init(k3, w, list(self.top)),
        }

    def apply(self, params, inputs, train: bool):
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], axis=-1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        bottom = nn.mlp_apply(params["bottom"], dense, final_activation=jax.nn.relu)
        embs = [inputs.pooled[c] for c in self._cats]
        x0 = jnp.concatenate([bottom] + embs, axis=-1)
        cross = nn.crossnet_apply(params["cross"], x0)
        return nn.mlp_apply(params["top"], cross)[:, 0]
