"""DeepFM on Criteo (/root/reference/modelzoo/deepfm/train.py): FM
second-order interactions + deep MLP over shared field embeddings."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import CRITEO_CAT, CRITEO_DENSE, criteo_features


@dataclasses.dataclass
class DeepFM:
    emb_dim: int = 16
    capacity: int = 1 << 16
    hidden: Sequence[int] = (1024, 512, 256)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()
    num_cat: int = len(CRITEO_CAT)
    num_dense: int = len(CRITEO_DENSE)

    def __post_init__(self):
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    def init(self, key):
        k1, k2 = jax.random.split(key)
        deep_in = self.num_cat * self.emb_dim + self.num_dense
        return {
            "deep": nn.mlp_init(k1, deep_in, list(self.hidden) + [1]),
            "linear_w": jax.random.normal(k2, (self.num_cat + self.num_dense,))
            * 0.01,
            "bias": jnp.zeros(()),
        }

    def apply(self, params, inputs, train: bool):
        embs = jnp.stack([inputs.pooled[c] for c in self._cats], axis=1)  # [B,F,D]
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], axis=-1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        fm = nn.fm_apply(embs)[:, 0]
        B = embs.shape[0]
        deep_in = jnp.concatenate([embs.reshape(B, -1), dense], axis=-1)
        deep = nn.mlp_apply(params["deep"], deep_in)[:, 0]
        first = (
            jnp.concatenate([embs[:, :, 0], dense], axis=-1) @ params["linear_w"]
        )
        return fm + deep + first + params["bias"]
