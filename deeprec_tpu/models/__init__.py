from deeprec_tpu.models.wdl import WDL
from deeprec_tpu.models.dlrm import DLRM, DLRMDCN
from deeprec_tpu.models.deepfm import DeepFM
from deeprec_tpu.models.dcn import DCN, DCNv2
from deeprec_tpu.models.din import DIN
from deeprec_tpu.models.dien import DIEN
from deeprec_tpu.models.bst import BST
from deeprec_tpu.models.dssm import DSSM
from deeprec_tpu.models.masknet import MaskNet
from deeprec_tpu.models.multitask import DBMTL, ESMM, MMoE, PLE, SimpleMultiTask
from deeprec_tpu.models.registry import REGISTRY, build_model
