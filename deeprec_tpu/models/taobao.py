"""Taobao user-behavior feature schema shared by DIN/DIEN/BST
(reference: modelzoo/{din,dien,bst}/train.py — user/item/category ids plus a
clicked-item behavior sequence)."""
from __future__ import annotations

from typing import List

from deeprec_tpu.config import EmbeddingVariableOption, TableConfig
from deeprec_tpu.features import SparseFeature


def behavior_features(
    emb_dim: int = 16,
    capacity: int = 1 << 16,
    ev: EmbeddingVariableOption = EmbeddingVariableOption(),
    key_dtype: str = "int32",
    max_len: int = 200,
) -> List:
    """target_item/hist_items share one item table; target_cat/hist_cats share
    one category table (shared-embedding semantics, as in the reference
    models). `max_len` is the declared history length — serving frontends
    pad/trim ragged histories to it so each feature has ONE compiled shape."""

    def tc(name):
        return TableConfig(name=name, dim=emb_dim, capacity=capacity, ev=ev,
                           key_dtype=key_dtype)

    return [
        SparseFeature(name="user", table=tc("user"), pooling="mean"),
        SparseFeature(name="target_item", table=tc("target_item"), pooling="mean"),
        SparseFeature(name="hist_items", shared_table="target_item",
                      pooling="none", max_len=max_len),
        SparseFeature(name="target_cat", table=tc("target_cat"), pooling="mean"),
        SparseFeature(name="hist_cats", shared_table="target_cat",
                      pooling="none", max_len=max_len),
    ]
