"""Criteo feature schema shared by the CTR modelzoo (13 numeric I1-I13, 26
categorical C1-C26 — reference modelzoo/wide_and_deep/train.py et al.)."""
from __future__ import annotations

from typing import List

from deeprec_tpu.config import EmbeddingVariableOption, TableConfig
from deeprec_tpu.features import DenseFeature, SparseFeature

CRITEO_DENSE = [f"I{i}" for i in range(1, 14)]
CRITEO_CAT = [f"C{i}" for i in range(1, 27)]


def criteo_features(
    emb_dim: int = 16,
    capacity: int = 1 << 16,
    ev: EmbeddingVariableOption = EmbeddingVariableOption(),
    num_cat: int = 26,
    num_dense: int = 13,
    key_dtype: str = "int32",
) -> List:
    feats: List = []
    for name in CRITEO_CAT[:num_cat]:
        feats.append(
            SparseFeature(
                name=name,
                table=TableConfig(
                    name=name, dim=emb_dim, capacity=capacity, ev=ev,
                    key_dtype=key_dtype,
                ),
                pooling="mean",
            )
        )
    for name in CRITEO_DENSE[:num_dense]:
        feats.append(DenseFeature(name=name, width=1))
    return feats
