"""DCN / DCNv2 on Criteo (/root/reference/modelzoo/{dcn,dcnv2}/train.py):
cross network × deep tower, concatenated into the output head."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import CRITEO_CAT, CRITEO_DENSE, criteo_features


@dataclasses.dataclass
class DCNv2:
    emb_dim: int = 16
    capacity: int = 1 << 16
    cross_depth: int = 3
    hidden: Sequence[int] = (1024, 512)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()
    num_cat: int = len(CRITEO_CAT)
    num_dense: int = len(CRITEO_DENSE)

    def __post_init__(self):
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    # Cross-network flavor: v2 uses matrix weights; the DCN subclass swaps
    # in the vector-weight originals.
    _cross_init = staticmethod(nn.crossnet_init)
    _cross_apply = staticmethod(nn.crossnet_apply)

    def _width(self):
        return self.num_cat * self.emb_dim + self.num_dense

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        w = self._width()
        return {
            "cross": self._cross_init(k1, w, self.cross_depth),
            "deep": nn.mlp_init(k2, w, list(self.hidden)),
            "head": nn.dense_init(k3, w + self.hidden[-1], 1),
        }

    def apply(self, params, inputs, train: bool):
        embs = [inputs.pooled[c] for c in self._cats]
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], axis=-1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        x0 = jnp.concatenate(embs + [dense], axis=-1)
        cross = self._cross_apply(params["cross"], x0)
        deep = nn.mlp_apply(params["deep"], x0, final_activation=jax.nn.relu)
        out = nn.dense_apply(params["head"], jnp.concatenate([cross, deep], -1))
        return out[:, 0]


@dataclasses.dataclass
class DCN(DCNv2):
    """Original DCN (vector-weight cross network) — the reference's
    modelzoo/dcn/train.py model; v2 above is modelzoo/dcnv2."""

    _cross_init = staticmethod(nn.crossnet_v1_init)
    _cross_apply = staticmethod(nn.crossnet_v1_apply)
