"""MaskNet (reference modelzoo/masknet/train.py): serial instance-guided
MaskBlocks — each block projects the raw feature concat into a
multiplicative mask over the running hidden state."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import criteo_features


@dataclasses.dataclass
class MaskNet:
    emb_dim: int = 16
    capacity: int = 1 << 16
    num_blocks: int = 3
    block_dim: int = 64
    mask_hidden: int = 64
    hidden: Sequence[int] = (64,)
    num_cat: int = 26
    num_dense: int = 13
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    def _width(self):
        return self.num_cat * self.emb_dim + self.num_dense

    def init(self, key):
        W = self._width()
        ks = jax.random.split(key, 3 * self.num_blocks + 1)
        blocks = []
        d = W
        for i in range(self.num_blocks):
            blocks.append(
                {
                    "mask1": nn.dense_init(ks[3 * i], W, self.mask_hidden),
                    "mask2": nn.dense_init(ks[3 * i + 1], self.mask_hidden, d),
                    "proj": nn.dense_init(ks[3 * i + 2], d, self.block_dim),
                    "ln": nn.layernorm_init(self.block_dim),
                }
            )
            d = self.block_dim
        return {
            "blocks": blocks,
            "head": nn.mlp_init(ks[-1], self.block_dim, list(self.hidden) + [1]),
        }

    def apply(self, params, inputs, train: bool):
        embs = [inputs.pooled[c] for c in self._cats]
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], -1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        x = jnp.concatenate(embs + [dense], -1)
        h = x
        for blk in params["blocks"]:
            mask = nn.dense_apply(
                blk["mask2"], jax.nn.relu(nn.dense_apply(blk["mask1"], x))
            )
            h = nn.layernorm_apply(blk["ln"], nn.dense_apply(blk["proj"], mask * h))
            h = jax.nn.relu(h)
        return nn.mlp_apply(params["head"], h)[:, 0]
