"""Name -> model constructor registry (modelzoo CLI + serving frontend).

The reference resolves models by directory (modelzoo/<name>/train.py); here
one registry serves the python -m entry points. Constructor kwargs mirror
each model's dataclass fields.
"""
from __future__ import annotations

from deeprec_tpu.models.bst import BST
from deeprec_tpu.models.dcn import DCN, DCNv2
from deeprec_tpu.models.deepfm import DeepFM
from deeprec_tpu.models.dien import DIEN
from deeprec_tpu.models.din import DIN
from deeprec_tpu.models.dlrm import DLRM, DLRMDCN
from deeprec_tpu.models.dssm import DSSM
from deeprec_tpu.models.masknet import MaskNet
from deeprec_tpu.models.multitask import DBMTL, ESMM, MMoE, PLE, SimpleMultiTask
from deeprec_tpu.models.wdl import WDL

REGISTRY = {
    "wdl": WDL,
    "wide_and_deep": WDL,
    "dlrm": DLRM,
    "dlrm_dcn": DLRMDCN,
    "mlperf": DLRMDCN,
    "deepfm": DeepFM,
    "dcn": DCN,
    "dcnv2": DCNv2,
    "din": DIN,
    "dien": DIEN,
    "bst": BST,
    "dssm": DSSM,
    "masknet": MaskNet,
    "mmoe": MMoE,
    "ple": PLE,
    "esmm": ESMM,
    "dbmtl": DBMTL,
    "simple_multitask": SimpleMultiTask,
}


def build_model(name: str, **kwargs):
    try:
        cls = REGISTRY[name.lower()]
    except KeyError:
        raise KeyError(
            f"unknown model {name!r}; choose from {sorted(REGISTRY)}"
        ) from None
    return cls(**kwargs)
