"""BST — Behavior Sequence Transformer (reference modelzoo/bst/train.py):
the target item is appended to the behavior sequence, a transformer encoder
block mixes them, and the mean-pooled encoding + user feed the MLP head.
Learned positional embeddings as in the paper."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.models.taobao import behavior_features


@dataclasses.dataclass
class BST:
    emb_dim: int = 16
    capacity: int = 1 << 16
    heads: int = 4
    ff: int = 128
    blocks: int = 1
    max_len: int = 200
    # Pallas flash attention for long histories (SIM-scale); needs the
    # padded sequence length to be a multiple of 128.
    use_flash: bool = False
    hidden: Sequence[int] = (256, 64)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        self.features = behavior_features(self.emb_dim, self.capacity, self.ev)

    def init(self, key):
        ks = jax.random.split(key, self.blocks + 2)
        D = 2 * self.emb_dim
        return {
            "pos": jax.random.normal(ks[0], (self.max_len + 1, D)) * 0.02,
            "blocks": [
                nn.transformer_block_init(ks[1 + i], D, self.heads, self.ff)
                for i in range(self.blocks)
            ],
            "mlp": nn.mlp_init(ks[-1], self.emb_dim + 2 * D,
                               list(self.hidden) + [1]),
        }

    def apply(self, params, inputs, train: bool):
        hist_i, mask = inputs.seq["hist_items"]
        hist_c, _ = inputs.seq["hist_cats"]
        hist = jnp.concatenate([hist_i, hist_c], axis=-1)  # [B, L, D]
        target = jnp.concatenate(
            [inputs.pooled["target_item"], inputs.pooled["target_cat"]], axis=-1
        )
        B, L, D = hist.shape
        seq = jnp.concatenate([hist, target[:, None, :]], axis=1)  # [B, L+1, D]
        seq = seq + params["pos"][None, : L + 1, :]
        m = jnp.concatenate([mask, jnp.ones((B, 1), bool)], axis=1)
        for blk in params["blocks"]:
            seq = nn.transformer_block_apply(blk, seq, m, self.heads,
                                             flash=self.use_flash)
        denom = jnp.sum(m, axis=1, keepdims=True).astype(jnp.float32)
        # Mask BEFORE pooling: padded positions still carry positional
        # embedding + FF residuals through the encoder and would dilute the
        # mean for short histories.
        pooled = jnp.sum(seq * m[..., None], axis=1) / jnp.maximum(denom, 1.0)
        # The head sees the TARGET position's encoding alongside the pooled
        # sequence (the paper's usage: the target item rides the encoder and
        # its output embedding feeds the MLP). Mean-pool alone dilutes the
        # target to 1/(L+1) of the signal — first-order target effects
        # dominate CTR data, and BST smoke-tested 0.07 AUC behind DIN on the
        # same stream until the head got this direct path.
        x = jnp.concatenate(
            [inputs.pooled["user"], pooled, seq[:, L]], axis=-1
        )
        return nn.mlp_apply(params["mlp"], x)[:, 0]
