"""Multi-task CTR models (reference modelzoo/{esmm,mmoe,ple,dbmtl,
simple_multitask}): all return {task: logits}; the Trainer pairs each task
with batch['label_<task>'].

Shared scaffolding: Criteo-style sparse+dense features feeding a shared
embedding concat, then the per-architecture routing."""
from __future__ import annotations

import dataclasses
from typing import Dict, Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import criteo_features


@dataclasses.dataclass
class _MTBase:
    """Subclasses with a `tasks` field expose label_tasks for serving."""

    @property
    def label_tasks(self):
        return tuple(getattr(self, "tasks", ()))

    emb_dim: int = 8
    capacity: int = 1 << 14
    num_cat: int = 8
    num_dense: int = 4
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    def _width(self):
        return self.num_cat * self.emb_dim + self.num_dense

    def _concat(self, inputs):
        embs = [inputs.pooled[c] for c in self._cats]
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], -1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))
        return jnp.concatenate(embs + [dense], -1)


def _prob_logit(p, eps=1e-7):
    p = jnp.clip(p, eps, 1.0 - eps)
    return jnp.log(p) - jnp.log1p(-p)


@dataclasses.dataclass
class SimpleMultiTask(_MTBase):
    """Shared bottom MLP + independent task towers
    (modelzoo/simple_multitask/train.py)."""

    bottom: Sequence[int] = (128,)
    tower: Sequence[int] = (32,)
    tasks: Sequence[str] = ("ctr", "cvr")

    def init(self, key):
        ks = jax.random.split(key, 1 + len(self.tasks))
        return {
            "bottom": nn.mlp_init(ks[0], self._width(), list(self.bottom)),
            "towers": {
                t: nn.mlp_init(ks[1 + i], self.bottom[-1], list(self.tower) + [1])
                for i, t in enumerate(self.tasks)
            },
        }

    def apply(self, params, inputs, train: bool) -> Dict[str, jnp.ndarray]:
        h = nn.mlp_apply(params["bottom"], self._concat(inputs),
                         final_activation=jax.nn.relu)
        return {
            t: nn.mlp_apply(params["towers"][t], h)[:, 0] for t in self.tasks
        }


@dataclasses.dataclass
class ESMM(_MTBase):
    """Entire-space multi-task model (modelzoo/esmm): pCTR and pCVR towers on
    shared embeddings; supervised as ctr (clicks) and ctcvr = pCTR*pCVR
    (conversions over the whole exposure space)."""

    tower: Sequence[int] = (64, 32)
    label_tasks = ("ctr", "ctcvr")

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {
            "ctr": nn.mlp_init(k1, self._width(), list(self.tower) + [1]),
            "cvr": nn.mlp_init(k2, self._width(), list(self.tower) + [1]),
        }

    def apply(self, params, inputs, train: bool):
        x = self._concat(inputs)
        ctr_logit = nn.mlp_apply(params["ctr"], x)[:, 0]
        cvr_logit = nn.mlp_apply(params["cvr"], x)[:, 0]
        pctcvr = jax.nn.sigmoid(ctr_logit) * jax.nn.sigmoid(cvr_logit)
        return {"ctr": ctr_logit, "ctcvr": _prob_logit(pctcvr)}


@dataclasses.dataclass
class MMoE(_MTBase):
    """Multi-gate mixture of experts (modelzoo/mmoe): shared experts, one
    softmax gate per task."""

    num_experts: int = 4
    expert: Sequence[int] = (64,)
    tower: Sequence[int] = (32,)
    tasks: Sequence[str] = ("ctr", "cvr")

    def init(self, key):
        ks = jax.random.split(key, self.num_experts + 2 * len(self.tasks))
        W = self._width()
        return {
            "experts": [
                nn.mlp_init(ks[i], W, list(self.expert))
                for i in range(self.num_experts)
            ],
            "gates": {
                t: nn.dense_init(ks[self.num_experts + i], W, self.num_experts)
                for i, t in enumerate(self.tasks)
            },
            "towers": {
                t: nn.mlp_init(
                    ks[self.num_experts + len(self.tasks) + i],
                    self.expert[-1], list(self.tower) + [1],
                )
                for i, t in enumerate(self.tasks)
            },
        }

    def apply(self, params, inputs, train: bool):
        x = self._concat(inputs)
        experts = jnp.stack(
            [nn.mlp_apply(e, x, final_activation=jax.nn.relu)
             for e in params["experts"]],
            axis=1,
        )  # [B, E, H]
        out = {}
        for t in self.tasks:
            g = jax.nn.softmax(nn.dense_apply(params["gates"][t], x), axis=-1)
            h = jnp.einsum("be,beh->bh", g, experts)
            out[t] = nn.mlp_apply(params["towers"][t], h)[:, 0]
        return out


@dataclasses.dataclass
class PLE(_MTBase):
    """Progressive layered extraction (modelzoo/ple): one CGC layer with
    shared + per-task experts, gated per task, then task towers."""

    shared_experts: int = 2
    task_experts: int = 2
    expert: Sequence[int] = (64,)
    tower: Sequence[int] = (32,)
    tasks: Sequence[str] = ("ctr", "cvr")

    def init(self, key):
        T = len(self.tasks)
        n_exp = self.shared_experts + T * self.task_experts
        ks = jax.random.split(key, n_exp + 2 * T)
        W = self._width()
        i = 0
        experts = {"shared": []}
        for _ in range(self.shared_experts):
            experts["shared"].append(nn.mlp_init(ks[i], W, list(self.expert))); i += 1
        for t in self.tasks:
            experts[t] = []
            for _ in range(self.task_experts):
                experts[t].append(nn.mlp_init(ks[i], W, list(self.expert))); i += 1
        gates, towers = {}, {}
        for t in self.tasks:
            gates[t] = nn.dense_init(ks[i], W, self.shared_experts + self.task_experts); i += 1
            towers[t] = nn.mlp_init(ks[i], self.expert[-1], list(self.tower) + [1]); i += 1
        return {"experts": experts, "gates": gates, "towers": towers}

    def apply(self, params, inputs, train: bool):
        x = self._concat(inputs)
        shared = [
            nn.mlp_apply(e, x, final_activation=jax.nn.relu)
            for e in params["experts"]["shared"]
        ]
        out = {}
        for t in self.tasks:
            own = [
                nn.mlp_apply(e, x, final_activation=jax.nn.relu)
                for e in params["experts"][t]
            ]
            stack = jnp.stack(shared + own, axis=1)  # [B, S+K, H]
            g = jax.nn.softmax(nn.dense_apply(params["gates"][t], x), axis=-1)
            h = jnp.einsum("be,beh->bh", g, stack)
            out[t] = nn.mlp_apply(params["towers"][t], h)[:, 0]
        return out


@dataclasses.dataclass
class DBMTL(_MTBase):
    """Deep bayesian multi-task (modelzoo/dbmtl): shared bottom, task towers,
    and an explicit ctr→cvr causal link on the hidden features."""

    bottom: Sequence[int] = (128,)
    tower: Sequence[int] = (32,)
    label_tasks = ("ctr", "cvr")

    def init(self, key):
        k1, k2, k3, k4 = jax.random.split(key, 4)
        H = self.bottom[-1]
        return {
            "bottom": nn.mlp_init(k1, self._width(), list(self.bottom)),
            "ctr": nn.mlp_init(k2, H, list(self.tower) + [1]),
            "cvr": nn.mlp_init(k3, H + self.tower[-1], list(self.tower) + [1]),
            "link": nn.mlp_init(k4, H, list(self.tower)),
        }

    def apply(self, params, inputs, train: bool):
        h = nn.mlp_apply(params["bottom"], self._concat(inputs),
                         final_activation=jax.nn.relu)
        ctr_logit = nn.mlp_apply(params["ctr"], h)[:, 0]
        ctr_hidden = nn.mlp_apply(params["link"], h, final_activation=jax.nn.relu)
        cvr_in = jnp.concatenate([h, ctr_hidden], -1)
        cvr_logit = nn.mlp_apply(params["cvr"], cvr_in)[:, 0]
        return {"ctr": ctr_logit, "cvr": cvr_logit}
