"""DSSM two-tower retrieval (reference modelzoo/dssm/train.py): user tower
and item tower, cosine-similarity logit scaled by a learnable temperature."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption, TableConfig
from deeprec_tpu.features import SparseFeature


@dataclasses.dataclass
class DSSM:
    emb_dim: int = 16
    capacity: int = 1 << 16
    num_user_feats: int = 4
    num_item_feats: int = 4
    hidden: Sequence[int] = (256, 128, 64)
    # Separate user-tower widths (None = same as `hidden`). Production
    # two-tower models are ASYMMETRIC — the user tower encodes long
    # behavior histories and dwarfs the item tower (the data-flow
    # asymmetry PAPERS' "Deep Recommender Models Inference" optimizes,
    # and what makes serving-side user-tower reuse worth N×: one heavy
    # user pass scores N candidates through the cheap item tower). The
    # last width must match `hidden`'s (the towers meet in a dot
    # product).
    user_hidden: Sequence[int] = None
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        def tc(name):
            return TableConfig(name=name, dim=self.emb_dim, capacity=self.capacity,
                               ev=self.ev)

        if self.user_hidden is None:
            self.user_hidden = tuple(self.hidden)
        if tuple(self.user_hidden)[-1:] != tuple(self.hidden)[-1:]:
            raise ValueError(
                f"user_hidden must end in the shared tower dim "
                f"{tuple(self.hidden)[-1]}, got {tuple(self.user_hidden)}"
            )
        self.user_feats = [f"U{i}" for i in range(self.num_user_feats)]
        self.item_feats = [f"V{i}" for i in range(self.num_item_feats)]
        self.features = [
            SparseFeature(name=n, table=tc(n)) for n in self.user_feats
        ] + [SparseFeature(name=n, table=tc(n)) for n in self.item_feats]

    def init(self, key):
        k1, k2 = jax.random.split(key)
        return {
            "user": nn.mlp_init(k1, self.num_user_feats * self.emb_dim,
                                list(self.user_hidden)),
            "item": nn.mlp_init(k2, self.num_item_feats * self.emb_dim,
                                list(self.hidden)),
            "temp": jnp.asarray(5.0),
        }

    @staticmethod
    def _normalize(x):
        return x / jnp.maximum(jnp.linalg.norm(x, axis=-1, keepdims=True), 1e-6)

    def towers(self, params, inputs):
        u = self.user_vector(params, inputs)
        v = self.item_vectors(
            params, jnp.concatenate([inputs.pooled[n] for n in self.item_feats], -1)
        )
        return u, v

    def apply(self, params, inputs, train: bool):
        u, v = self.towers(params, inputs)
        return jnp.sum(u * v, axis=-1) * params["temp"]

    def user_vector(self, params, inputs):
        """User tower alone — compute once per user."""
        u = jnp.concatenate([inputs.pooled[n] for n in self.user_feats], -1)
        return self._normalize(nn.mlp_apply(params["user"], u))

    def item_vectors(self, params, item_embs):
        """Item tower over [N, F*D] stacked item features."""
        return self._normalize(nn.mlp_apply(params["item"], item_embs))

    def item_tower_params(self, params):
        """The dense subtree `item_vectors` reads — the retrieval
        engine's corpus-staleness fingerprint (serving/retrieval.py): a
        delta that leaves this subtree untouched (sparse-only online
        updates) folds targeted; one that moves it re-encodes the whole
        corpus. `temp` is excluded — it scales every score uniformly and
        cannot reorder a top-k."""
        return params["item"]

    def apply_with_user(self, params, user_vec, inputs):
        """Forward given precomputed user vectors (the serving-side
        sample-aware-compression hook: the predictor runs `user_vector`
        once per distinct user via nn.apply_grouped and finishes the row
        with this). Row-for-row equal to apply()."""
        v = self.item_vectors(
            params,
            jnp.concatenate([inputs.pooled[n] for n in self.item_feats], -1),
        )
        return jnp.sum(user_vec * v, axis=-1) * params["temp"]

    def score_items(self, params, user_vec, item_vecs):
        """Score a user against N candidate items at once — the
        sample-aware-compression pattern (user subgraph computed once per
        <user, N items> group, docs/docs_en/Sample-awared-Graph-Compression.md).
        user_vec [B, H], item_vecs [B, N, H] or [N, H]."""
        if item_vecs.ndim == 2:
            return user_vec @ item_vecs.T * params["temp"]  # [B, N]
        return jnp.einsum("bh,bnh->bn", user_vec, item_vecs) * params["temp"]
