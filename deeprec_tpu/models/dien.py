"""DIEN — Deep Interest Evolution Network (reference modelzoo/dien/train.py):
interest extraction GRU over behavior, then an attention-gated AUGRU whose
final hidden state is the evolved interest. The AUGRU runs as a lax.scan —
compiler-friendly recurrence, no dynamic lengths."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.models.taobao import behavior_features


@dataclasses.dataclass
class DIEN:
    emb_dim: int = 16
    capacity: int = 1 << 16
    gru_hidden: int = 32
    hidden: Sequence[int] = (200, 80)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()

    def __post_init__(self):
        self.features = behavior_features(self.emb_dim, self.capacity, self.ev)

    def init(self, key):
        ks = jax.random.split(key, 4)
        D = 2 * self.emb_dim
        H = self.gru_hidden
        in_dim = self.emb_dim + D + H
        return {
            "gru1": nn.gru_init(ks[0], D, H),
            "augru": nn.gru_init(ks[1], H, H),
            "att_w": nn.dense_init(ks[2], H, D),
            "mlp": nn.mlp_init(ks[3], in_dim, list(self.hidden) + [1]),
        }

    def apply(self, params, inputs, train: bool):
        hist_i, mask = inputs.seq["hist_items"]
        hist_c, _ = inputs.seq["hist_cats"]
        hist = jnp.concatenate([hist_i, hist_c], axis=-1)  # [B, L, D]
        target = jnp.concatenate(
            [inputs.pooled["target_item"], inputs.pooled["target_cat"]], axis=-1
        )
        # interest extraction
        _, states1 = nn.gru_apply(params["gru1"], hist, mask)  # [B, L, H]
        # attention scores vs target (bilinear through att_w)
        proj = nn.dense_apply(params["att_w"], states1)  # [B, L, D]
        scores = jnp.einsum("bld,bd->bl", proj, target) / jnp.sqrt(
            jnp.float32(target.shape[-1])
        )
        scores = jnp.where(mask, scores, -1e9)
        att = jax.nn.softmax(scores, axis=1)
        att = jnp.where(mask, att, 0.0)
        # interest evolution: AUGRU over extracted states
        final, _ = nn.gru_apply(params["augru"], states1, mask, att=att)
        x = jnp.concatenate([inputs.pooled["user"], target, final], axis=-1)
        return nn.mlp_apply(params["mlp"], x, activation=jax.nn.sigmoid)[:, 0]
