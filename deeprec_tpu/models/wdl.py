"""Wide & Deep on Criteo — the reference's baseline model
(/root/reference/modelzoo/wide_and_deep/train.py): 13 numeric + 26
categorical features; wide = linear over per-feature scalar embeddings,
deep = MLP over concatenated dim-d embeddings + numerics."""
from __future__ import annotations

import dataclasses
from typing import Sequence

import jax
import jax.numpy as jnp

from deeprec_tpu import nn
from deeprec_tpu.config import EmbeddingVariableOption
from deeprec_tpu.features import DenseFeature, SparseFeature
from deeprec_tpu.models.criteo import CRITEO_CAT, CRITEO_DENSE, criteo_features


@dataclasses.dataclass
class WDL:
    emb_dim: int = 16
    capacity: int = 1 << 16
    hidden: Sequence[int] = (1024, 512, 256)
    ev: EmbeddingVariableOption = EmbeddingVariableOption()
    num_cat: int = len(CRITEO_CAT)
    num_dense: int = len(CRITEO_DENSE)

    def __post_init__(self):
        self.features = criteo_features(
            emb_dim=self.emb_dim, capacity=self.capacity, ev=self.ev,
            num_cat=self.num_cat, num_dense=self.num_dense,
        )
        self._cats = [f.name for f in self.features if isinstance(f, SparseFeature)]
        self._dense = [f.name for f in self.features if isinstance(f, DenseFeature)]

    def init(self, key):
        k1, k2, k3 = jax.random.split(key, 3)
        deep_in = self.num_cat * self.emb_dim + self.num_dense
        return {
            "deep": nn.mlp_init(k1, deep_in, list(self.hidden) + [1]),
            # wide: linear over embeddings' first component + numerics
            "wide_w": jax.random.normal(k2, (self.num_cat + self.num_dense,)) * 0.01,
            "wide_b": jnp.zeros(()),
        }

    def apply(self, params, inputs, train: bool):
        embs = [inputs.pooled[c] for c in self._cats]  # each [B, d]
        dense = jnp.concatenate([inputs.dense[d] for d in self._dense], axis=-1)
        dense = jnp.log1p(jnp.maximum(dense, 0.0))  # Criteo standard transform
        deep_in = jnp.concatenate(embs + [dense], axis=-1)
        deep_out = nn.mlp_apply(params["deep"], deep_in)[:, 0]
        wide_in = jnp.concatenate([e[:, :1] for e in embs] + [dense], axis=-1)
        wide_out = wide_in @ params["wide_w"] + params["wide_b"]
        return deep_out + wide_out
