"""ctypes bindings for the native host runtime (libdeeprec_host.so).

Native C++ is the right tool for the host-side KV store backing multi-tier
embedding storage (DeepRec keeps this layer in C++ too — SURVEY.md §2.1). The
library auto-builds with `make` on first use; a pure-numpy fallback keeps the
framework functional in build-less environments (behavior-identical, slower).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
from typing import Optional, Tuple

import numpy as np

from deeprec_tpu.analysis.annotations import not_thread_safe

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "libdeeprec_host.so")
_lib = None
_build_attempted = False


def _try_build() -> Optional[ctypes.CDLL]:
    global _build_attempted
    if _build_attempted:
        return None
    _build_attempted = True
    try:
        subprocess.run(
            ["make", "-s"], cwd=_DIR, check=True, capture_output=True, timeout=120
        )
        return ctypes.CDLL(_SO)
    except Exception:
        return None


def load_library() -> Optional[ctypes.CDLL]:
    global _lib
    if _lib is not None:
        return _lib
    if os.path.exists(_SO):
        try:
            _lib = ctypes.CDLL(_SO)
            return _lib
        except OSError:
            pass
    _lib = _try_build()
    if _lib is not None:
        _configure(_lib)
    return _lib


def _configure(lib):
    u64, i64p, f32p, i32p, u8p = (
        ctypes.c_uint64,
        np.ctypeslib.ndpointer(np.int64, flags="C"),
        np.ctypeslib.ndpointer(np.float32, flags="C"),
        np.ctypeslib.ndpointer(np.int32, flags="C"),
        np.ctypeslib.ndpointer(np.uint8, flags="C"),
    )
    lib.hkv_create.restype = ctypes.c_void_p
    lib.hkv_create.argtypes = [ctypes.c_int, u64]
    lib.hkv_destroy.argtypes = [ctypes.c_void_p]
    lib.hkv_size.restype = u64
    lib.hkv_size.argtypes = [ctypes.c_void_p]
    lib.hkv_put_batch.argtypes = [ctypes.c_void_p, u64, i64p, f32p, i32p, i32p]
    lib.hkv_get_batch.argtypes = [ctypes.c_void_p, u64, i64p, f32p, i32p, i32p, u8p]
    lib.hkv_erase_batch.argtypes = [ctypes.c_void_p, u64, i64p]
    lib.hkv_export.argtypes = [ctypes.c_void_p, i64p, f32p, i32p, i32p]
    lib.hkv_save.restype = ctypes.c_int
    lib.hkv_save.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    lib.hkv_load.restype = ctypes.c_int
    lib.hkv_load.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
    if hasattr(lib, "criteo_parse"):
        lib.criteo_parse.restype = ctypes.c_int64
        lib.criteo_parse.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C"),
            np.ctypeslib.ndpointer(np.float32, flags="C"),
            np.ctypeslib.ndpointer(np.int32, flags="C"),
            ctypes.POINTER(ctypes.c_int64),
        ]
    if hasattr(lib, "criteo_parse_mt"):
        lib.criteo_parse_mt.restype = ctypes.c_int64
        lib.criteo_parse_mt.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_int,
            np.ctypeslib.ndpointer(np.float32, flags="C"),
            np.ctypeslib.ndpointer(np.float32, flags="C"),
            np.ctypeslib.ndpointer(np.int32, flags="C"),
            ctypes.POINTER(ctypes.c_int64),
        ]


@not_thread_safe
class HostKV:
    """int64 key -> (float32[dim] value, freq, version) host store.

    Native-backed when the .so is available; numpy-dict fallback otherwise.

    NOT thread-safe (neither backend is): the multi-tier choreography
    serializes every access behind MultiTierTable._settle() — background
    rounds own the store exclusively while running. DRT004 (the static
    analyzer) flags any new cross-thread access path.
    """

    def __init__(self, dim: int, initial_capacity: int = 1 << 16):
        self.dim = dim
        self._lib = load_library()
        if self._lib is not None:
            _configure(self._lib)
            self._h = self._lib.hkv_create(dim, initial_capacity)
            self._fallback = None
        else:
            self._h = None
            self._fallback = {}

    @property
    def native(self) -> bool:
        return self._h is not None

    def __len__(self) -> int:
        if self.native:
            return int(self._lib.hkv_size(self._h))
        return len(self._fallback)

    def put(self, keys, values, freqs=None, versions=None) -> None:
        keys = np.ascontiguousarray(keys, np.int64)
        values = np.ascontiguousarray(values, np.float32).reshape(len(keys), self.dim)
        freqs = np.ascontiguousarray(
            freqs if freqs is not None else np.zeros(len(keys)), np.int32
        )
        versions = np.ascontiguousarray(
            versions if versions is not None else np.full(len(keys), -1), np.int32
        )
        if self.native:
            self._lib.hkv_put_batch(self._h, len(keys), keys, values, freqs, versions)
        else:
            for i, k in enumerate(keys):
                self._fallback[int(k)] = (
                    values[i].copy(), int(freqs[i]), int(versions[i])
                )

    def get(self, keys) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        """-> (values [n, dim], freqs [n], versions [n], found [n] bool)"""
        keys = np.ascontiguousarray(keys, np.int64)
        n = len(keys)
        values = np.zeros((n, self.dim), np.float32)
        freqs = np.zeros(n, np.int32)
        versions = np.full(n, -1, np.int32)
        found = np.zeros(n, np.uint8)
        if self.native:
            self._lib.hkv_get_batch(self._h, n, keys, values, freqs, versions, found)
        else:
            for i, k in enumerate(keys):
                hit = self._fallback.get(int(k))
                if hit is not None:
                    values[i], freqs[i], versions[i] = hit
                    found[i] = 1
        return values, freqs, versions, found.astype(bool)

    def erase(self, keys) -> None:
        keys = np.ascontiguousarray(keys, np.int64)
        if self.native:
            self._lib.hkv_erase_batch(self._h, len(keys), keys)
        else:
            for k in keys:
                self._fallback.pop(int(k), None)

    def export(self) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
        n = len(self)
        keys = np.zeros(n, np.int64)
        values = np.zeros((n, self.dim), np.float32)
        freqs = np.zeros(n, np.int32)
        versions = np.zeros(n, np.int32)
        if self.native:
            self._lib.hkv_export(self._h, keys, values, freqs, versions)
        else:
            for i, (k, (v, f, ver)) in enumerate(self._fallback.items()):
                keys[i], values[i], freqs[i], versions[i] = k, v, f, ver
        return keys, values, freqs, versions

    def save(self, path: str) -> None:
        if self.native:
            rc = self._lib.hkv_save(self._h, path.encode())
            if rc != 0:
                raise IOError(f"hkv_save({path}) failed rc={rc}")
        else:
            k, v, f, ver = self.export()
            np.savez(path, keys=k, values=v, freqs=f, versions=ver)

    def load(self, path: str) -> None:
        if self.native:
            rc = self._lib.hkv_load(self._h, path.encode())
            if rc != 0:
                raise IOError(f"hkv_load({path}) failed rc={rc}")
        else:
            d = np.load(path if path.endswith(".npz") else path + ".npz")
            self.put(d["keys"], d["values"], d["freqs"], d["versions"])

    def __del__(self):
        if getattr(self, "_h", None) is not None and self._lib is not None:
            try:
                self._lib.hkv_destroy(self._h)
            except Exception:
                pass


def criteo_parse_native(
    buf: bytes, max_rows: int, num_dense: int = 13, num_cat: int = 26,
    threads: int = 0,
):
    """Parse Criteo TSV bytes with the native parser (multi-threaded when
    the library exports criteo_parse_mt; threads=0 picks the hardware
    count, threads=1 forces the single-thread path).

    Returns (rows, labels, dense, cats, consumed_bytes) or None when the
    native library is unavailable. The id hashing matches
    data/readers._hash_strings exactly, so outputs are interchangeable.
    """
    lib = load_library()
    if lib is None or not hasattr(lib, "criteo_parse"):
        return None
    _configure(lib)
    labels = np.zeros(max_rows, np.float32)
    dense = np.zeros((max_rows, num_dense), np.float32)
    cats = np.zeros((max_rows, num_cat), np.int32)
    consumed = ctypes.c_int64(0)
    if threads != 1 and hasattr(lib, "criteo_parse_mt"):
        rows = lib.criteo_parse_mt(
            buf, len(buf), max_rows, num_dense, num_cat, threads, labels,
            dense.reshape(-1), cats.reshape(-1), ctypes.byref(consumed),
        )
    else:
        rows = lib.criteo_parse(
            buf, len(buf), max_rows, num_dense, num_cat, labels,
            dense.reshape(-1), cats.reshape(-1), ctypes.byref(consumed),
        )
    return int(rows), labels, dense, cats, int(consumed.value)
