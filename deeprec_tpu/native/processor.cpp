// Serving C ABI: the symbol contract external RPC hosts code against.
//
// Mirrors the reference's processor ABI
// (/root/reference/serving/processor/serving/processor.h — initialize /
// process / batch_process / get_serving_model_info) so a host built for it
// can dlopen libdeeprec_processor.so unchanged. The implementation is this
// framework's own: an embedded CPython interpreter forwarding payloads to
// deeprec_tpu.serving.cabi, where the full serving stack (validation,
// request coalescing onto the TPU, full/delta hot-swap polling, warmup)
// lives. Payloads may be either the reference's protobuf wire format
// (serialized tensorflow.eas.PredictRequest -> PredictResponse,
// predict.proto — what reference-built hosts send) or JSON; cabi.py
// sniffs the format per request.
//
// Threading: any host thread may call process(); each entry point takes the
// GIL via PyGILState_Ensure. When this library boots the interpreter itself
// (a C host), the boot thread releases the GIL afterwards so other threads
// can enter. When loaded INTO a Python process (ctypes — how the test
// drives it), Py_IsInitialized() short-circuits the boot.
//
// Memory: process()/get_serving_model_info() malloc the output buffer; the
// caller frees it with free() (or the exported free_buffer alias).
//
// Build: make processor   (links against libpython; see Makefile)

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cstdlib>
#include <cstring>

namespace {

struct ProcessorState {
  PyObject* server;        // deeprec_tpu.serving.ModelServer
  PyObject* process_fn;    // cabi.process_request (JSON or protobuf)
  PyObject* info_fn;       // cabi.model_info_json
};

// Copy a Python (status, bytes) tuple into a malloc'd C buffer.
int unpack_reply(PyObject* res, void** output_data, int* output_size) {
  if (res == nullptr) {
    PyErr_Print();
    return -1;
  }
  int status = -1;
  PyObject* body = nullptr;
  if (PyTuple_Check(res) && PyTuple_Size(res) == 2) {
    status = static_cast<int>(PyLong_AsLong(PyTuple_GetItem(res, 0)));
    body = PyTuple_GetItem(res, 1);  // borrowed
  }
  if (body != nullptr && PyBytes_Check(body)) {
    Py_ssize_t n = PyBytes_Size(body);
    void* buf = std::malloc(static_cast<size_t>(n));
    if (buf != nullptr) {
      std::memcpy(buf, PyBytes_AsString(body), static_cast<size_t>(n));
      *output_data = buf;
      *output_size = static_cast<int>(n);
    } else {
      status = -1;
    }
  } else {
    status = -1;
  }
  Py_DECREF(res);
  return status;
}

}  // namespace

extern "C" {

// model_entry: unused slot kept for ABI compatibility (the reference passes
// a SavedModel path here; this framework's model comes from the config's
// registry name + ckpt_dir). model_config: JSON, see cabi.create_server.
// On success *state = 0 and the returned handle is passed to process();
// on failure returns nullptr and *state = -1.
void* initialize(const char* model_entry, const char* model_config,
                 int* state) {
  (void)model_entry;
  bool booted_here = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    booted_here = true;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  ProcessorState* ps = nullptr;
  PyObject* mod = PyImport_ImportModule("deeprec_tpu.serving.cabi");
  if (mod != nullptr) {
    PyObject* create = PyObject_GetAttrString(mod, "create_server");
    PyObject* server =
        create ? PyObject_CallFunction(create, "s", model_config) : nullptr;
    if (server != nullptr) {
      ps = new ProcessorState();
      ps->server = server;
      ps->process_fn = PyObject_GetAttrString(mod, "process_request");
      ps->info_fn = PyObject_GetAttrString(mod, "model_info_json");
    }
    Py_XDECREF(create);
    Py_DECREF(mod);
  }
  if (ps == nullptr) {
    PyErr_Print();
  }
  if (state != nullptr) {
    *state = ps != nullptr ? 0 : -1;
  }
  PyGILState_Release(gil);
  if (booted_here) {
    // Release the GIL held by the booting thread so process() may be
    // called from any host thread.
    PyEval_SaveThread();
  }
  return ps;
}

int get_serving_model_info(void* model_buf, void** output_data,
                           int* output_size);

// The predict path without the empty-payload ping (batch_process_n keeps
// per-request 400 semantics for a zero-size request).
static int process_predict(void* model_buf, const void* input_data,
                           int input_size, void** output_data,
                           int* output_size) {
  if (model_buf == nullptr || output_data == nullptr ||
      output_size == nullptr) {
    return -1;
  }
  auto* ps = static_cast<ProcessorState*>(model_buf);
  static const char kEmpty[] = "";
  const char* data =
      input_data != nullptr ? static_cast<const char*>(input_data) : kEmpty;
  int size = input_data != nullptr ? input_size : 0;
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallFunction(
      ps->process_fn, "Oy#", ps->server, data,
      static_cast<Py_ssize_t>(size));
  int status = unpack_reply(res, output_data, output_size);
  PyGILState_Release(gil);
  return status;
}

// Returns the serving status code (200/400/500, mirroring the HTTP
// frontend) or -1 on an internal error. *output_data is malloc'd JSON.
// input_size == 0 mirrors the reference (processor.cc:29-34): the model's
// debug/serving info is returned with status 200 — hosts use an empty
// payload as a liveness + introspection ping.
int process(void* model_buf, const void* input_data, int input_size,
            void** output_data, int* output_size) {
  if (input_size == 0) {
    return get_serving_model_info(model_buf, output_data, output_size);
  }
  return process_predict(model_buf, input_data, input_size, output_data,
                         output_size);
}

// Reference-ABI batch entry point. The ABI has no request count anywhere
// (processor.h:8), and the reference implementation resolves that with
// `sizeof(input_data)/sizeof(void*)` (message_coding.cc:79) — i.e. it
// ALWAYS processes exactly one request, whatever the host meant to pass.
// Hosts coded against the reference therefore observe batch-of-1
// semantics, and they do NOT null-terminate the array, so walking it here
// would read out of bounds. We match the observable reference behavior:
// exactly one request. A null input_size mirrors the reference's
// `if (input_size == 0)` pointer check: return model debug info. Hosts
// that want real batching use batch_process_n (explicit count, below).
int batch_process(void* model_buf, const void* input_data[], int* input_size,
                  void* output_data[], int* output_size) {
  if (model_buf == nullptr || output_data == nullptr ||
      output_size == nullptr) {
    return -1;
  }
  if (input_data == nullptr || input_size == nullptr) {
    return get_serving_model_info(model_buf, &output_data[0],
                                  &output_size[0]);
  }
  return process(model_buf, input_data[0], input_size[0], &output_data[0],
                 &output_size[0]);
}

// Extension (not in the reference ABI): batch with an explicit request
// count. Per-request statuses are not folded — the return is the first
// non-200 status, each output buffer carries its own error body. An empty
// (size-0) request is a client error for its slot, not an info ping — the
// ping semantic belongs to the single-request reference entry points only.
int batch_process_n(void* model_buf, const void* input_data[],
                    int* input_size, int num_requests, void* output_data[],
                    int* output_size) {
  if (model_buf == nullptr || input_data == nullptr ||
      input_size == nullptr || output_data == nullptr ||
      output_size == nullptr) {
    return -1;
  }
  int first_bad = 200;
  for (int i = 0; i < num_requests; ++i) {
    int rc = process_predict(model_buf, input_data[i], input_size[i],
                             &output_data[i], &output_size[i]);
    if (rc != 200 && first_bad == 200) {
      first_bad = rc;
    }
  }
  return first_bad;
}

int get_serving_model_info(void* model_buf, void** output_data,
                           int* output_size) {
  if (model_buf == nullptr || output_data == nullptr ||
      output_size == nullptr) {
    return -1;
  }
  auto* ps = static_cast<ProcessorState*>(model_buf);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* res = PyObject_CallFunction(ps->info_fn, "O", ps->server);
  int status = unpack_reply(res, output_data, output_size);
  PyGILState_Release(gil);
  return status;
}

void free_buffer(void* buf) { std::free(buf); }

// Stop the coalescing worker and drop the Python references. The
// interpreter itself is left running (it may be the host's).
void shutdown_processor(void* model_buf) {
  if (model_buf == nullptr) {
    return;
  }
  auto* ps = static_cast<ProcessorState*>(model_buf);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* closed = PyObject_CallMethod(ps->server, "close", nullptr);
  Py_XDECREF(closed);
  Py_XDECREF(ps->process_fn);
  Py_XDECREF(ps->info_fn);
  Py_DECREF(ps->server);
  PyGILState_Release(gil);
  delete ps;
}

}  // extern "C"
