// Fast Criteo-TSV batch parser — the native data-plane component.
//
// DeepRec's input pipeline parses columnar data in C++ kernels
// (core/kernels/data/parquet_batch_reader.cc, CSV via TF ops). Python-side
// pandas parsing can't feed a TPU at full rate; this parser turns raw TSV
// bytes into ready batch arrays (labels, log-transformed-ready dense floats,
// crc32-hashed categorical ids) in one pass, exposed via ctypes.
//
// Format per line: label \t I1..I13 \t C1..C26 (hex strings), '\t' separated,
// missing fields empty. Output ids use (crc32(token) ^ salt_i) & 0x7fffffff —
// the SAME mapping as data/readers.py so native and python readers agree.

#include <cstdint>
#include <cstdlib>
#include <cstring>
#include <thread>
#include <vector>

namespace {

// CRC32 (IEEE, reflected) — table-driven, matches zlib.crc32.
uint32_t crc_table[256];
bool crc_init_done = false;

void crc_init() {
  for (uint32_t i = 0; i < 256; ++i) {
    uint32_t c = i;
    for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  crc_init_done = true;
}

uint32_t crc32(const char* data, size_t n) {
  if (!crc_init_done) crc_init();
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < n; ++i)
    c = crc_table[(c ^ static_cast<uint8_t>(data[i])) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

}  // namespace

extern "C" {

// Parse up to max_rows lines from buf[0..len). Returns rows parsed; writes
// *consumed = bytes consumed (ends on a line boundary, so callers can stream
// chunks). labels [max_rows], dense [max_rows * num_dense], cats
// [max_rows * num_cat] (row-major). Missing dense -> 0, missing cat -> -1.
int64_t criteo_parse(
    const char* buf, int64_t len, int64_t max_rows, int num_dense, int num_cat,
    float* labels, float* dense, int32_t* cats, int64_t* consumed) {
  int64_t row = 0;
  int64_t pos = 0;
  while (row < max_rows) {
    // find end of line
    int64_t eol = pos;
    while (eol < len && buf[eol] != '\n') ++eol;
    if (eol >= len) break;  // incomplete line: stop, let caller refill

    int64_t p = pos;
    int field = 0;
    const int total_fields = 1 + num_dense + num_cat;
    while (field < total_fields && p <= eol) {
      int64_t start = p;
      while (p < eol && buf[p] != '\t') ++p;
      int64_t flen = p - start;
      if (field == 0) {
        labels[row] = flen ? static_cast<float>(strtol(buf + start, nullptr, 10))
                           : 0.f;
      } else if (field <= num_dense) {
        dense[row * num_dense + (field - 1)] =
            flen ? strtof(buf + start, nullptr) : 0.f;
      } else {
        int ci = field - 1 - num_dense;
        if (flen) {
          uint32_t salt = (uint32_t)(ci + 1) * 0x9E3779B9u & 0x7FFFFFFFu;
          cats[row * num_cat + ci] =
              (int32_t)((crc32(buf + start, flen) ^ salt) & 0x7FFFFFFFu);
        } else {
          cats[row * num_cat + ci] = -1;
        }
      }
      ++field;
      ++p;  // skip the tab / newline
    }
    // zero-fill any missing trailing fields
    for (; field <= num_dense; ++field)
      dense[row * num_dense + (field - 1)] = 0.f;
    for (; field < total_fields; ++field)
      cats[row * num_cat + (field - 1 - num_dense)] = -1;

    pos = eol + 1;
    ++row;
  }
  *consumed = pos;
  return row;
}

// Multi-threaded variant: pass 1 scans line boundaries (memchr), pass 2
// parses disjoint row ranges in parallel — each line writes to its own
// output slice, so no synchronization is needed. Same outputs bit-for-bit
// as criteo_parse. `threads` <= 0 picks the hardware count (capped at 16).
int64_t criteo_parse_mt(
    const char* buf, int64_t len, int64_t max_rows, int num_dense, int num_cat,
    int threads, float* labels, float* dense, int32_t* cats,
    int64_t* consumed) {
  if (!crc_init_done) crc_init();  // once, before threads spawn
  // pass 1: line starts for up to max_rows complete lines
  std::vector<int64_t> starts;
  starts.reserve(static_cast<size_t>(max_rows) + 1);
  int64_t pos = 0;
  while (static_cast<int64_t>(starts.size()) < max_rows) {
    const char* nl = static_cast<const char*>(
        memchr(buf + pos, '\n', static_cast<size_t>(len - pos)));
    if (!nl) break;
    starts.push_back(pos);
    pos = (nl - buf) + 1;
  }
  const int64_t nrows = static_cast<int64_t>(starts.size());
  starts.push_back(pos);  // sentinel: end of the consumed region
  *consumed = pos;
  if (nrows == 0) return 0;

  int T = threads > 0 ? threads
                      : static_cast<int>(std::thread::hardware_concurrency());
  if (T > 16) T = 16;
  if (T < 1) T = 1;
  if (nrows < 4 * T) T = 1;  // tiny batches: thread spawn costs more

  auto parse_range = [&](int64_t r0, int64_t r1) {
    for (int64_t r = r0; r < r1; ++r) {
      int64_t dummy;
      criteo_parse(buf + starts[r], starts[r + 1] - starts[r], 1, num_dense,
                   num_cat, labels + r, dense + r * num_dense,
                   cats + r * num_cat, &dummy);
    }
  };
  if (T == 1) {
    parse_range(0, nrows);
    return nrows;
  }
  std::vector<std::thread> pool;
  pool.reserve(T);
  const int64_t per = (nrows + T - 1) / T;
  for (int t = 0; t < T; ++t) {
    int64_t r0 = t * per;
    int64_t r1 = r0 + per < nrows ? r0 + per : nrows;
    if (r0 >= r1) break;
    pool.emplace_back(parse_range, r0, r1);
  }
  for (auto& th : pool) th.join();
  return nrows;
}

}  // extern "C"
