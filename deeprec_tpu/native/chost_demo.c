/* Pure-C serving host: the EAS-style integration path.
 *
 * dlopens libdeeprec_processor.so with NO Python running — exercising the
 * embedded-interpreter boot branch of initialize() (processor.cpp
 * booted_here) that ctypes-driven tests short-circuit. Mirrors the
 * reference SDK demo (serving/sdk/python/demo.py, but in C like an EAS
 * host): initialize with a JSON model config, process one request, print
 * the body, shut down.
 *
 * Usage: chost_demo <libdeeprec_processor.so> <model_config.json> <req file>
 * Exits 0 iff initialize returns state 0 and process returns 200.
 */
#include <dlfcn.h>
#include <stdio.h>
#include <stdlib.h>
#include <string.h>

typedef void* (*initialize_fn)(const char*, const char*, int*);
typedef int (*process_fn)(void*, const void*, int, void**, int*);
typedef void (*free_fn)(void*);
typedef void (*shutdown_fn)(void*);

static char* read_file(const char* path, long* out_len) {
  FILE* f = fopen(path, "rb");
  if (!f) return NULL;
  if (fseek(f, 0, SEEK_END) != 0) {
    fclose(f);
    return NULL;
  }
  long n = ftell(f);
  if (n < 0 || fseek(f, 0, SEEK_SET) != 0) {
    fclose(f);
    return NULL;  /* unseekable input (pipe/FIFO) */
  }
  char* buf = malloc((size_t)n + 1);
  if (!buf) {
    fclose(f);
    return NULL;
  }
  if (fread(buf, 1, (size_t)n, f) != (size_t)n) {
    fclose(f);
    free(buf);
    return NULL;
  }
  buf[n] = 0;
  fclose(f);
  *out_len = n;
  return buf;
}

int main(int argc, char** argv) {
  if (argc != 4) {
    fprintf(stderr, "usage: %s <lib.so> <config.json> <request file>\n",
            argv[0]);
    return 2;
  }
  void* lib = dlopen(argv[1], RTLD_NOW | RTLD_GLOBAL);
  if (!lib) {
    fprintf(stderr, "dlopen: %s\n", dlerror());
    return 2;
  }
  initialize_fn init = (initialize_fn)dlsym(lib, "initialize");
  process_fn process = (process_fn)dlsym(lib, "process");
  free_fn free_buffer = (free_fn)dlsym(lib, "free_buffer");
  shutdown_fn shutdown = (shutdown_fn)dlsym(lib, "shutdown_processor");
  if (!init || !process || !free_buffer || !shutdown) {
    fprintf(stderr, "missing ABI symbol\n");
    return 2;
  }

  long cfg_len = 0, req_len = 0;
  char* cfg = read_file(argv[2], &cfg_len);
  char* req = read_file(argv[3], &req_len);
  if (!cfg || !req) {
    fprintf(stderr, "cannot read config/request\n");
    return 2;
  }

  int state = -7;
  void* model = init("", cfg, &state);
  if (state != 0 || !model) {
    fprintf(stderr, "initialize failed: state=%d\n", state);
    return 3;
  }

  void* out = NULL;
  int out_len = 0;
  int rc = process(model, req, (int)req_len, &out, &out_len);
  printf("process rc=%d body=%.*s\n", rc, out_len, (char*)out);
  if (out) free_buffer(out);
  shutdown(model);
  free(cfg);
  free(req);
  return rc == 200 ? 0 : 4;
}
