// Host-DRAM KV store for embedding overflow tiers.
//
// The native piece of the multi-tier storage design (SURVEY.md §2.1): DeepRec
// keeps cold embeddings in DRAM/PMEM/SSD behind C++ KV interfaces
// (embedding/kv_interface.h, dense_hash_map_kv.h, ssd_hash_kv.h). On a TPU VM
// the analog is a host-memory table the Python tier choreographs against the
// in-HBM device table: demote cold rows here, promote them back on re-touch,
// spill to a file for the SSD tier. Open-addressing, power-of-two capacity,
// auto-growing; batch APIs only (the ctypes boundary is amortized over
// thousands of keys per call).
//
// Build: make (g++ -O3 -shared -fPIC). Exposed via ctypes — no pybind11
// dependency per the environment constraints.

#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <vector>

namespace {

constexpr int64_t kEmpty = INT64_MIN;

inline uint64_t mix64(uint64_t x) {
  x ^= x >> 33;
  x *= 0xff51afd7ed558ccdULL;
  x ^= x >> 33;
  x *= 0xc4ceb9fe1a85ec53ULL;
  x ^= x >> 33;
  return x;
}

struct Store {
  int dim;
  uint64_t capacity;  // power of two
  uint64_t size;
  std::vector<int64_t> keys;
  std::vector<float> values;    // [capacity, dim]
  std::vector<int32_t> freq;
  std::vector<int32_t> version;

  explicit Store(int d, uint64_t cap) : dim(d), capacity(cap), size(0) {
    keys.assign(capacity, kEmpty);
    values.assign(capacity * dim, 0.f);
    freq.assign(capacity, 0);
    version.assign(capacity, -1);
  }

  uint64_t probe(int64_t key) const {
    uint64_t mask = capacity - 1;
    uint64_t pos = mix64(static_cast<uint64_t>(key)) & mask;
    while (keys[pos] != kEmpty && keys[pos] != key) pos = (pos + 1) & mask;
    return pos;
  }

  void grow() {
    Store bigger(dim, capacity * 2);
    for (uint64_t i = 0; i < capacity; ++i) {
      if (keys[i] == kEmpty) continue;
      uint64_t pos = bigger.probe(keys[i]);
      bigger.keys[pos] = keys[i];
      std::memcpy(&bigger.values[pos * dim], &values[i * dim],
                  sizeof(float) * dim);
      bigger.freq[pos] = freq[i];
      bigger.version[pos] = version[i];
    }
    bigger.size = size;
    *this = std::move(bigger);
  }

  void put(int64_t key, const float* row, int32_t f, int32_t v) {
    if ((size + 1) * 4 >= capacity * 3) grow();  // keep load factor < 75%
    uint64_t pos = probe(key);
    if (keys[pos] == kEmpty) {
      keys[pos] = key;
      ++size;
    }
    std::memcpy(&values[pos * dim], row, sizeof(float) * dim);
    freq[pos] = f;
    version[pos] = v;
  }
};

}  // namespace

extern "C" {

void* hkv_create(int dim, uint64_t initial_capacity) {
  uint64_t cap = 1024;
  while (cap < initial_capacity) cap <<= 1;
  return new Store(dim, cap);
}

void hkv_destroy(void* h) { delete static_cast<Store*>(h); }

uint64_t hkv_size(void* h) { return static_cast<Store*>(h)->size; }

int hkv_dim(void* h) { return static_cast<Store*>(h)->dim; }

// Insert or overwrite n rows.
void hkv_put_batch(void* h, uint64_t n, const int64_t* keys,
                   const float* values, const int32_t* freqs,
                   const int32_t* versions) {
  Store* s = static_cast<Store*>(h);
  for (uint64_t i = 0; i < n; ++i) {
    s->put(keys[i], &values[i * s->dim], freqs ? freqs[i] : 0,
           versions ? versions[i] : -1);
  }
}

// Gather n rows; found[i]=1 when present (values/freqs/versions filled),
// untouched outputs otherwise.
void hkv_get_batch(void* h, uint64_t n, const int64_t* keys, float* out_values,
                   int32_t* out_freqs, int32_t* out_versions,
                   uint8_t* out_found) {
  Store* s = static_cast<Store*>(h);
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t pos = s->probe(keys[i]);
    if (s->keys[pos] == keys[i]) {
      out_found[i] = 1;
      std::memcpy(&out_values[i * s->dim], &s->values[pos * s->dim],
                  sizeof(float) * s->dim);
      if (out_freqs) out_freqs[i] = s->freq[pos];
      if (out_versions) out_versions[i] = s->version[pos];
    } else {
      out_found[i] = 0;
    }
  }
}

// Remove n keys (missing keys ignored). Rebuilds once at the end so probe
// chains stay healthy (backshift-free deletion).
void hkv_erase_batch(void* h, uint64_t n, const int64_t* keys) {
  Store* s = static_cast<Store*>(h);
  uint64_t erased = 0;
  for (uint64_t i = 0; i < n; ++i) {
    uint64_t pos = s->probe(keys[i]);
    if (s->keys[pos] == keys[i]) {
      s->keys[pos] = INT64_MIN + 1;  // tombstone, cleaned below
      ++erased;
    }
  }
  if (!erased) return;
  Store fresh(s->dim, s->capacity);
  for (uint64_t i = 0; i < s->capacity; ++i) {
    if (s->keys[i] == kEmpty || s->keys[i] == INT64_MIN + 1) continue;
    fresh.put(s->keys[i], &s->values[i * s->dim], s->freq[i], s->version[i]);
  }
  *s = std::move(fresh);
}

// Export all rows (caller allocates hkv_size() rows).
void hkv_export(void* h, int64_t* keys, float* values, int32_t* freqs,
                int32_t* versions) {
  Store* s = static_cast<Store*>(h);
  uint64_t j = 0;
  for (uint64_t i = 0; i < s->capacity; ++i) {
    if (s->keys[i] == kEmpty) continue;
    keys[j] = s->keys[i];
    std::memcpy(&values[j * s->dim], &s->values[i * s->dim],
                sizeof(float) * s->dim);
    freqs[j] = s->freq[i];
    versions[j] = s->version[i];
    ++j;
  }
}

// File spill/load — the SSD/LevelDB-tier analog (ssd_hash_kv.h): a flat
// binary record format (header + rows).
int hkv_save(void* h, const char* path) {
  Store* s = static_cast<Store*>(h);
  FILE* f = std::fopen(path, "wb");
  if (!f) return -1;
  uint64_t magic = 0xDEE99EC0011ULL, dim = s->dim, n = s->size;
  std::fwrite(&magic, 8, 1, f);
  std::fwrite(&dim, 8, 1, f);
  std::fwrite(&n, 8, 1, f);
  for (uint64_t i = 0; i < s->capacity; ++i) {
    if (s->keys[i] == kEmpty) continue;
    std::fwrite(&s->keys[i], 8, 1, f);
    std::fwrite(&s->values[i * s->dim], sizeof(float), s->dim, f);
    std::fwrite(&s->freq[i], 4, 1, f);
    std::fwrite(&s->version[i], 4, 1, f);
  }
  std::fclose(f);
  return 0;
}

int hkv_load(void* h, const char* path) {
  Store* s = static_cast<Store*>(h);
  FILE* f = std::fopen(path, "rb");
  if (!f) return -1;
  uint64_t magic = 0, dim = 0, n = 0;
  if (std::fread(&magic, 8, 1, f) != 1 || magic != 0xDEE99EC0011ULL ||
      std::fread(&dim, 8, 1, f) != 1 || dim != (uint64_t)s->dim ||
      std::fread(&n, 8, 1, f) != 1) {
    std::fclose(f);
    return -2;
  }
  std::vector<float> row(s->dim);
  for (uint64_t i = 0; i < n; ++i) {
    int64_t k;
    int32_t fr, ver;
    if (std::fread(&k, 8, 1, f) != 1 ||
        std::fread(row.data(), sizeof(float), s->dim, f) != (size_t)s->dim ||
        std::fread(&fr, 4, 1, f) != 1 || std::fread(&ver, 4, 1, f) != 1) {
      std::fclose(f);
      return -3;
    }
    s->put(k, row.data(), fr, ver);
  }
  std::fclose(f);
  return 0;
}

}  // extern "C"
