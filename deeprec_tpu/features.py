"""Feature specs — the feature_column analog.

DeepRec models declare inputs via feature_column
(categorical_column_with_embedding, python/feature_column/feature_column_v2.py:2080,
embedding_column, numeric_column). Here a model takes a list of FeatureSpecs;
the trainer resolves sparse ones against hash-embedding tables and hands the
model pooled ([B, D]) or sequence ([B, L, D] + mask) embeddings.

Batches are plain dicts: sparse features as int id arrays [B] or [B, L] padded
with `pad_value`; dense features as float arrays [B, W]; the label under
`label`.
"""
from __future__ import annotations

import dataclasses
from typing import Optional

from deeprec_tpu.config import TableConfig, validate_unique_budget


@dataclasses.dataclass(frozen=True)
class SparseFeature:
    """A categorical (id/multi-id) feature backed by a hash-embedding table.

    pooling: 'mean' | 'sum' | 'sqrtn' pool the bag to [B, D];
             'none' delivers the full sequence [B, L, D] plus mask (for
             attention models: DIN/DIEN/BST).
    shared_table: name of another SparseFeature whose table this one reuses
             (DeepRec shared_embedding_columns analog).
    max_len: optional declared bag length L. Features are auto-grouped for
             fused GroupEmbedding lookups only when their id shapes match;
             set distinct max_len values to keep differently-shaped features
             in separate groups.
    unique_budget: per-feature override of TableConfig.unique_budget (the
             hash-dedup unique budget, ops/dedup.py): int fixed budget,
             "auto" trainer-derived, "off" to force the legacy U=N path,
             None (default) to inherit the table's setting. Features
             sharing a bundle resolve to the largest member budget.
    """

    name: str
    table: Optional[TableConfig] = None
    pooling: str = "mean"
    pad_value: int = -1
    shared_table: Optional[str] = None
    max_len: Optional[int] = None
    unique_budget: Optional[object] = None  # None | "off" | "auto" | int

    def __post_init__(self):
        if (self.table is None) == (self.shared_table is None):
            raise ValueError(
                f"{self.name}: exactly one of table/shared_table must be set"
            )
        validate_unique_budget(self.unique_budget, f"feature {self.name}")


@dataclasses.dataclass(frozen=True)
class DenseFeature:
    """A numeric feature column, passed through (models normalize as needed)."""

    name: str
    width: int = 1


def sparse_features(specs) -> list:
    return [f for f in specs if isinstance(f, SparseFeature)]


def dense_features(specs) -> list:
    return [f for f in specs if isinstance(f, DenseFeature)]


def table_configs(specs) -> dict:
    """Unique tables declared by a spec list (shared tables deduped)."""
    out = {}
    for f in sparse_features(specs):
        if f.table is not None:
            out[f.name] = f.table
    return out


def resolve_table_name(spec: SparseFeature) -> str:
    return spec.shared_table if spec.shared_table is not None else spec.name
