"""deeprec_tpu — a TPU-native sparse-recommendation training framework.

Brand-new JAX/XLA/Pallas implementation of the capability set of DeepRec
(Alibaba's TF-1.15 recommendation engine, studied read-only at
/root/reference/): dynamic hash-table embeddings with admission filters and
eviction, frequency-aware sparse optimizers, pod-sharded tables over ICI
collectives, staged input pipelines, full+incremental checkpointing, a
modelzoo and a serving path. See SURVEY.md for the blueprint.
"""

from deeprec_tpu.config import (
    CBFFilter,
    CheckpointConfig,
    CheckpointOption,
    CounterFilter,
    EmbeddingVariableOption,
    GlobalStepEvict,
    InitializerOption,
    L2WeightEvict,
    MeshConfig,
    StorageOption,
    StorageType,
    TableConfig,
)
from deeprec_tpu.embedding.table import EmbeddingTable, TableState, UniqueLookup
from deeprec_tpu.embedding.combiners import combine
from deeprec_tpu.features import DenseFeature, SparseFeature

__version__ = "0.1.0"
