"""Poison-batch dead-lettering and the permanent-quarantine breaker.

When the step sentinel trips, ``TrainLoop`` rolls the model back to the
last verified checkpoint and SKIPS the offending batch — but the batch
itself must not vanish: operators need the payload for forensics
(which feature carried the NaN? which upstream job flipped the
labels?), and the loop needs memory of it, because a restart-and-replay
supervisor would otherwise feed the same poison forever. That is what
the dead-letter directory provides:

    <dir>/batch-<fingerprint>.npz      the offending batch's arrays
    <dir>/batch-<fingerprint>.json     step, flags, tripped kinds, count
    <dir>/quarantine.json              fingerprint -> trip count + the
                                       permanent set (atomic tmp+rename,
                                       same commit discipline as the
                                       checkpoint manifest)

A batch whose fingerprint trips across ``GuardPolicy.max_batch_trips``
rollbacks is PERMANENTLY quarantined: the loop drops it before
dispatch, forever, across process restarts — the crash-loop breaker
the Supervisor cannot provide (it can only tell "restart fixed it"
from "it died again"; the guard-trip heartbeat field plus this index
tells it "the data poisons it").
"""
from __future__ import annotations

import hashlib
import json
import os
from dataclasses import dataclass
from typing import Dict, List, Optional

import numpy as np


def batch_fingerprint(batch: Dict) -> str:
    """Content fingerprint of one batch: sha1 over the sorted keys and
    raw array bytes — stable across processes, so a permanently
    quarantined batch stays quarantined through any restart/replay."""
    h = hashlib.sha1()
    for k in sorted(batch):
        h.update(k.encode())
        a = np.ascontiguousarray(np.asarray(batch[k]))  # noqa: DRT002 — fingerprints hash the HOST batch before it is ever device_put
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    return h.hexdigest()[:16]


@dataclass(frozen=True)
class GuardPolicy:
    """TrainLoop-side rollback/quarantine policy.

    ``max_batch_trips`` is R from the firewall spec: trips of one batch
    fingerprint before it is permanently quarantined.
    ``replay_window`` bounds the in-memory batch buffer used to resume
    bit-identically after a rollback (it must cover at least one save
    cadence; batches older than the window cannot be replayed and the
    rollback degrades to resuming at the restored step)."""

    dead_letter_dir: str
    max_batch_trips: int = 2
    replay_window: int = 256


class DeadLetter:
    """The dead-letter directory: payloads, trip counts, permanent set.

    Host-side and rollback-cadence only — nothing here is on the train
    hot path. The index commits atomically so a crash mid-update leaves
    the previous intact index, never a torn one."""

    INDEX = "quarantine.json"

    def __init__(self, directory: str, max_batch_trips: int = 2):
        self.dir = directory
        self.max_batch_trips = max(1, int(max_batch_trips))
        os.makedirs(directory, exist_ok=True)
        self._index: Dict = {"trips": {}, "permanent": []}
        try:
            with open(os.path.join(directory, self.INDEX)) as f:
                loaded = json.load(f)
            if isinstance(loaded, dict):
                self._index["trips"].update(loaded.get("trips", {}))
                self._index["permanent"] = list(loaded.get("permanent", []))
        except (OSError, ValueError):
            pass  # fresh dir, or an unreadable index: start conservative

    # ------------------------------------------------------------ queries

    def is_quarantined(self, fingerprint: str) -> bool:
        return fingerprint in self._index["permanent"]

    def trip_count(self, fingerprint: str) -> int:
        return int(self._index["trips"].get(fingerprint, 0))

    @property
    def permanent_count(self) -> int:
        return len(self._index["permanent"])

    # ------------------------------------------------------------- record

    def record_trip(self, fingerprint: str, step: int, flags: int,
                    kinds: List[str], batch: Optional[Dict]) -> bool:
        """Account one sentinel trip against `fingerprint`; write the
        payload + meta on first sight. Returns True when the batch just
        crossed ``max_batch_trips`` and is now PERMANENTLY quarantined."""
        trips = self._index["trips"]
        trips[fingerprint] = int(trips.get(fingerprint, 0)) + 1  # noqa: DRT002 — JSON-index int at rollback cadence
        payload = os.path.join(self.dir, f"batch-{fingerprint}.npz")
        if batch is not None and not os.path.exists(payload):
            try:
                np.savez(payload,
                         **{k: np.asarray(v) for k, v in batch.items()})  # noqa: DRT002 — rollback-cadence dead-letter write of a HOST batch, never the step path
            except OSError:
                pass  # forensics are best-effort; the quarantine is not
        meta = {
            "fingerprint": fingerprint,
            "step": int(step),  # noqa: DRT002 — host ints at rollback cadence
            "flags": int(flags),  # noqa: DRT002 — host ints at rollback cadence
            "kinds": list(kinds),
            "trips": trips[fingerprint],
        }
        try:
            with open(os.path.join(
                    self.dir, f"batch-{fingerprint}.json"), "w") as f:
                json.dump(meta, f)
        except OSError:
            pass
        newly_permanent = (
            trips[fingerprint] >= self.max_batch_trips
            and fingerprint not in self._index["permanent"]
        )
        if newly_permanent:
            self._index["permanent"].append(fingerprint)
        self._commit()
        return newly_permanent

    def _commit(self) -> None:
        path = os.path.join(self.dir, self.INDEX)
        tmp = f"{path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(self._index, f)
            os.replace(tmp, path)
        except OSError:
            try:
                os.unlink(tmp)
            except OSError:
                pass
