"""Pre-swap canary: the quality gate of the delta-publish path.

The zero-stall serving update (PR 5/PR 7) assembles the next model on a
shadow state and swaps one reference — which also means a semantically
poisoned delta (NaN rows, garbage embeddings) ships to traffic with
zero stall and zero error. The canary closes that gap: BEFORE the swap,
``Predictor`` evaluates a fixed probe batch on the shadow state and
rejects the update when

  * any probe prediction is non-finite (always checked),
  * the prediction distribution shifted more than ``max_shift`` mean
    |Δp| against the probe predictions of the CURRENTLY served
    snapshot (a poisoned table drags scores violently; an honest delta
    at serving cadence moves them a little), or
  * labels are attached and the probe AUC fell under ``auc_floor``.

A rejected delta is quarantined with the PR 7 rename discipline (the
trainer's next save then re-anchors the chain), the old snapshot keeps
serving, and ``health()`` reports ``degraded`` with
``degraded_reason: quality_gate`` — freshness sacrificed BY CHOICE,
visibly, never silently.

Host-side and update-cadence only; the probe forward reuses the
predictor's jitted predict at a shape compiled once at attach time, so
the gate adds zero steady-state compiles (pinned under trace_guard).
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

import numpy as np


class QualityGateRejected(Exception):
    """A shadow state failed the pre-swap canary; the update must not
    publish. Carries the structured reason for health/metrics."""

    def __init__(self, reason: str, **details):
        super().__init__(reason)
        self.reason = reason
        self.details = details


def np_auc(probs: np.ndarray, labels: np.ndarray) -> float:
    """Rank AUC on host arrays (probe batches are small; ties averaged).
    Returns 0.5 when only one class is present."""
    probs = np.asarray(probs, np.float64).reshape(-1)  # noqa: DRT002 — pure-numpy AUC on host arrays
    labels = np.asarray(labels, np.float64).reshape(-1)  # noqa: DRT002 — pure-numpy AUC on host arrays
    pos = labels > 0.5
    n_pos = int(pos.sum())  # noqa: DRT002 — pure-numpy AUC on host arrays
    n_neg = probs.size - n_pos
    if n_pos == 0 or n_neg == 0:
        return 0.5
    order = np.argsort(probs, kind="mergesort")
    ranks = np.empty(probs.size, np.float64)
    ranks[order] = np.arange(1, probs.size + 1)
    # average tied ranks so identical scores split the credit
    sorted_p = probs[order]
    i = 0
    while i < probs.size:
        j = i
        while j + 1 < probs.size and sorted_p[j + 1] == sorted_p[i]:
            j += 1
        if j > i:
            ranks[order[i:j + 1]] = (i + 1 + j + 1) / 2.0
        i = j + 1
    return float((ranks[pos].sum() - n_pos * (n_pos + 1) / 2.0)  # noqa: DRT002 — pure-numpy AUC on host arrays
                 / (n_pos * n_neg))


@dataclass
class QualityGate:
    """Configuration + reference state of the pre-swap canary.

    ``probe`` is a label-free feature batch (one fixed shape — it
    compiles once and every later gate pass is cache-hit dispatch).
    ``labels`` + ``auc_floor`` add the absolute quality bound;
    ``max_shift`` is the relative prediction-distribution bound against
    the currently served snapshot. ``rejections``/``last_rejection``
    are the observability surface the predictor exports."""

    probe: Dict[str, np.ndarray]
    labels: Optional[np.ndarray] = None
    auc_floor: Optional[float] = None
    max_shift: float = 0.25
    rejections: int = 0
    last_rejection: Optional[Dict] = None
    _ref_probs: Optional[np.ndarray] = field(default=None, repr=False)

    @staticmethod
    def _flat(probs) -> np.ndarray:
        if isinstance(probs, dict):  # multi-task: concatenate all heads
            return np.concatenate(
                [np.asarray(v).reshape(-1) for _, v in sorted(probs.items())]  # noqa: DRT002 — update-cadence canary eval on already-host probe results
            )
        return np.asarray(probs).reshape(-1)  # noqa: DRT002 — update-cadence canary eval on already-host probe results

    def set_reference(self, probs) -> None:
        """Stamp the served snapshot's probe predictions — the baseline
        the next shadow state's shift is measured against."""
        self._ref_probs = self._flat(probs)

    def check(self, probs) -> None:
        """Raise QualityGateRejected when the shadow state's probe
        predictions fail the gate; otherwise return (the caller then
        publishes and calls ``set_reference`` with these probs)."""
        p = self._flat(probs)
        if not np.all(np.isfinite(p)):
            self._reject("nonfinite_predictions",
                         nonfinite=int((~np.isfinite(p)).sum()))  # noqa: DRT002 — host numpy count at update cadence
        if self._ref_probs is not None and self._ref_probs.shape == p.shape:
            shift = float(np.mean(np.abs(p - self._ref_probs)))  # noqa: DRT002 — host numpy mean at update cadence
            if shift > self.max_shift:
                self._reject("prediction_shift", shift=round(shift, 4),
                             bound=self.max_shift)
        if self.labels is not None and self.auc_floor is not None:
            auc = np_auc(p[: np.asarray(self.labels).size], self.labels)  # noqa: DRT002 — host numpy AUC at update cadence
            if auc < self.auc_floor:
                self._reject("auc_floor", auc=round(auc, 4),
                             floor=self.auc_floor)

    def _reject(self, reason: str, **details) -> None:
        self.rejections += 1
        self.last_rejection = {"reason": reason, **details}
        raise QualityGateRejected(reason, **details)
