"""On-device step sentinel: per-dispatch model-quality flags.

The checks run INSIDE the jitted train step (and inside the K-step
scan's body), so XLA fuses them with the step's own reductions; the
result is packed into ONE int32 bitmask scalar per step. The loop reads
the scalar of the PREVIOUS dispatch (by then already materialized —
reading it costs no pipeline stall), which is where the "detected ≤ 1
dispatch after injection" contract of ``tools/bench_guard.py`` comes
from. No check ever modifies the update math: with the sentinel ON and
untripped, training is bit-identical to sentinel OFF
(tests/test_guard.py pins this on table ints and values).

State that must persist across dispatches — the loss EMA the spike
check compares against — rides OUTSIDE TrainState in a tiny guard
carry ``{"ema": f32[]}`` threaded through ``Trainer.train_step(...,
guard=)`` and the scan carry of ``train_steps``; the updated EMA
returns in the metrics dict (``mets["guard_ema"]``) so the caller hands
it to the next dispatch without ever pulling it to the host.
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import jax.numpy as jnp

# Flag bits of the packed int32 sentinel scalar. Bounded set — these
# names are also the `kind=` label values of deeprec_guard_trips_total.
FLAG_NONFINITE_LOSS = 1
FLAG_NONFINITE_GRAD = 2
FLAG_GRAD_NORM = 4
FLAG_LOSS_SPIKE = 8
FLAG_ROW_NORM = 16

FLAG_KINDS = (
    (FLAG_NONFINITE_LOSS, "nonfinite_loss"),
    (FLAG_NONFINITE_GRAD, "nonfinite_grad"),
    (FLAG_GRAD_NORM, "grad_norm"),
    (FLAG_LOSS_SPIKE, "loss_spike"),
    (FLAG_ROW_NORM, "row_norm"),
)


def flag_kinds(flags: int) -> List[str]:
    """Decode a host-read flags scalar into its tripped kind names."""
    return [name for bit, name in FLAG_KINDS if flags & bit]


@dataclasses.dataclass(frozen=True)
class SentinelConfig:
    """Thresholds of the on-device step sentinel.

    Non-finite loss/grad checks are always on. ``spike_ratio`` trips
    when the step loss exceeds ``spike_ratio ×`` the running EMA of
    clean-step losses (the EMA never learns from a tripped step, so a
    poison burst cannot drag the baseline up). ``grad_norm_max`` bounds
    the global L2 norm over dense AND embedding grads.
    ``row_norm_max`` bounds the max L2 norm of the table rows THIS step
    updated (only touched rows are gathered — never a full-table scan
    on the hot path). ``row_clamp_norm`` additionally rescales updated
    rows down to that L2 norm (row hygiene: changes the math, off by
    default). ``row_evict_quantile``/``row_evict_factor`` configure the
    maintain()-cadence anomaly eviction: occupied rows whose norm
    exceeds ``factor ×`` the occupied-norm quantile are re-initialized.
    Pick a MID quantile (0.9 is the intended shape) — an extreme
    quantile (0.999+) is dominated by the anomalous rows themselves and
    inflates its own bound out of reach.
    """

    spike_ratio: float = 4.0
    ema_decay: float = 0.9
    grad_norm_max: Optional[float] = None
    row_norm_max: Optional[float] = None
    row_clamp_norm: Optional[float] = None
    row_evict_quantile: Optional[float] = None
    row_evict_factor: float = 8.0


def guard_init() -> Dict[str, jnp.ndarray]:
    """Fresh guard carry: EMA < 0 means unseeded (first clean step
    seeds it with its own loss; the spike check stays off until then)."""
    return {"ema": jnp.full((), -1.0, jnp.float32)}


def _tree_finite(tree) -> jnp.ndarray:
    """Scalar bool: every float leaf of `tree` is finite."""
    import jax

    ok = jnp.asarray(True)
    for leaf in jax.tree.leaves(tree):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            ok = ok & jnp.all(jnp.isfinite(leaf))
    return ok


def grad_observations(g_dense, g_embs) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """(grads_finite bool[], grad_norm_sq f32[]) over dense + embedding
    grads — one fused reduction tree, no host value."""
    import jax

    finite = _tree_finite(g_dense) & _tree_finite(g_embs)
    sq = jnp.zeros((), jnp.float32)
    for leaf in jax.tree.leaves((g_dense, g_embs)):
        if hasattr(leaf, "dtype") and jnp.issubdtype(leaf.dtype, jnp.floating):
            sq = sq + jnp.sum(jnp.square(leaf.astype(jnp.float32)))
    return finite, sq


def step_flags(
    cfg: SentinelConfig,
    loss: jnp.ndarray,
    grads_finite: jnp.ndarray,
    grad_norm_sq: jnp.ndarray,
    row_norm_max: Optional[jnp.ndarray],
    guard: Dict[str, jnp.ndarray],
) -> Tuple[jnp.ndarray, Dict[str, jnp.ndarray]]:
    """Fold one step's observations into (flags int32[], new guard).

    The EMA only advances on untripped steps; flags is the OR of every
    tripped check, so the host decodes WHAT tripped from the one scalar
    it reads per dispatch."""
    loss = jnp.asarray(loss, jnp.float32)
    ema = guard["ema"]
    flags = jnp.zeros((), jnp.int32)
    loss_ok = jnp.isfinite(loss)
    flags = flags | jnp.where(loss_ok, 0, FLAG_NONFINITE_LOSS)
    flags = flags | jnp.where(grads_finite, 0, FLAG_NONFINITE_GRAD)
    if cfg.grad_norm_max is not None:
        bound = jnp.float32(cfg.grad_norm_max) ** 2
        # A non-finite norm must not dodge the bound check via NaN
        # comparison semantics — the nonfinite-grad bit already fires.
        flags = flags | jnp.where(grad_norm_sq > bound, FLAG_GRAD_NORM, 0)
    spike = (ema > 0) & loss_ok & (loss > jnp.float32(cfg.spike_ratio) * ema)
    flags = flags | jnp.where(spike, FLAG_LOSS_SPIKE, 0)
    if row_norm_max is not None and cfg.row_norm_max is not None:
        flags = flags | jnp.where(
            ~jnp.isfinite(row_norm_max)
            | (row_norm_max > jnp.float32(cfg.row_norm_max)),
            FLAG_ROW_NORM, 0,
        )
    clean = flags == 0
    decay = jnp.float32(cfg.ema_decay)
    new_ema = jnp.where(
        clean,
        jnp.where(ema < 0, loss, decay * ema + (1.0 - decay) * loss),
        ema,
    )
    return flags, {"ema": new_ema}


def guard_carry(mets: Dict) -> Optional[Dict[str, jnp.ndarray]]:
    """Rebuild the guard carry for the NEXT dispatch from a step's
    metrics (device references only — nothing is read to the host).
    K-step scans stack metric leaves [K]; the last entry is the carry."""
    ema = mets.get("guard_ema")
    if ema is None:
        return None
    if getattr(ema, "ndim", 0):
        ema = ema[-1]
    return {"ema": ema}
