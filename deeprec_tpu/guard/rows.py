"""Row hygiene: maintain()-cadence anomaly eviction.

The step sentinel bounds the rows a SINGLE dispatch writes; this pass
catches slow contamination — a hot poisoned id whose row drifts to an
absurd norm over many small steps between checkpoints. At maintain()
cadence (host-side, never the hot path) every occupied row's L2 norm is
compared against ``factor ×`` the occupied-population quantile; rows
past the bound are dropped via the table's rebuild (probe chains heal,
optimizer slots restart at their init value) so the key re-initializes
on next sight instead of serving garbage. Non-finite rows always count
as anomalous regardless of the quantile.
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp


def anomalous_row_mask(table, ts, quantile: float,
                       factor: float) -> jnp.ndarray:
    """[C] bool — occupied rows whose L2 norm exceeds ``factor ×`` the
    occupied-norm ``quantile``, or is non-finite. Device-side; O(C·D)
    read, maintain cadence only."""
    from deeprec_tpu.ops.packed import unpack_array

    vals = unpack_array(ts.values, ts.capacity).astype(jnp.float32)
    norm = jnp.sqrt(jnp.sum(jnp.square(vals), axis=1))
    occ = table.occupied(ts)
    bad_finite = occ & ~jnp.isfinite(norm)
    # quantile over the occupied population only: empty slots are all-zero
    # rows and would drag the bound to ~0 on a sparse table
    pop = jnp.where(occ, norm, jnp.nan)
    q = jnp.nanquantile(pop, jnp.float32(quantile))
    bound = jnp.where(jnp.isfinite(q), q * jnp.float32(factor), jnp.inf)
    return bad_finite | (occ & jnp.isfinite(norm) & (norm > bound))


def anomaly_evict(table, ts, quantile: float, factor: float,
                  slot_fills) -> Tuple[object, int]:
    """Re-initialize anomalous rows of one LOCAL table state. Returns
    (new_state, evicted_count); a zero count returns the input state
    untouched (no rebuild paid)."""
    mask = anomalous_row_mask(table, ts, quantile, factor)
    n = int(jnp.sum(mask))
    if n == 0:
        return ts, 0
    return table.rebuild(ts, keep=~mask, slot_fills=slot_fills), n


def touched_row_norms(table, values, slot_ix) -> jnp.ndarray:
    """[U] L2 norms of the rows `slot_ix` addresses (invalid ix -> 0) —
    the per-step sentinel's post-apply read of exactly the rows this
    dispatch updated, through the table's packed-layout-aware gather."""
    safe = jnp.where(slot_ix >= 0, slot_ix, 0)
    rows = table._gather(values, safe, _capacity_of(values, table))
    rows = rows.astype(jnp.float32)
    n = jnp.sqrt(jnp.sum(jnp.square(rows), axis=-1))
    return jnp.where(slot_ix >= 0, n, 0.0)


def _capacity_of(values, table) -> int:
    """Logical capacity of a (possibly packed) values array: rows × pack
    factor — values is [C // P, P * D]."""
    rows, width = values.shape[-2], values.shape[-1]
    return rows * (width // table.cfg.dim)


def clamp_rows(table, values, slot_ix, norms, clamp: float,
               seed) -> jnp.ndarray:
    """Rescale rows past `clamp` L2 down onto the bound (non-finite
    norms clamp to zero-scale — a NaN row cannot be rescued by
    scaling). Writes only the offending rows; everything else is
    untouched, preserving the bit-exact no-op contract when nothing
    exceeds the bound."""
    safe = jnp.where(slot_ix >= 0, slot_ix, 0)
    rows = table._gather(values, safe, _capacity_of(values, table))
    rows = rows.astype(jnp.float32)
    finite = jnp.isfinite(norms) & jnp.all(jnp.isfinite(rows), axis=-1)
    scale = jnp.where(
        finite, jnp.float32(clamp) / jnp.maximum(norms, 1e-30), 0.0
    )
    over = (slot_ix >= 0) & (~finite | (norms > jnp.float32(clamp)))
    new_rows = (rows * scale[..., None]).astype(values.dtype)
    return table._scatter(
        values, jnp.where(over, slot_ix, -1), new_rows,
        _capacity_of(values, table), seed=seed,
    )
