"""Model-quality firewall: semantic-fault defense for the online loop.

PR 7 made the pipeline survive *process* faults and PR 12 *membership*
faults; this package defends the remaining class — *semantic* faults,
where every process is healthy but the MODEL goes bad: a poisoned batch
or an exploding gradient writes NaN/garbage table rows, and the
zero-stall delta chain then ships them to serving with no stall and no
error. Production recommenders treat "we silently served a bad model"
as the worst outage class, worse than downtime (PAPERS: Tensor
Casting's observation that sparse-path corruption is silent — the dense
loss can look plausible for many steps).

Four layers, one firewall (docs/fault-tolerance.md "Semantic faults"):

  * ``sentinel``  — on-device per-dispatch step checks (non-finite
    loss/grad, loss-spike vs an EMA, global grad-norm, updated-row-norm)
    packed into ONE int32 flags scalar carried through the K-step scan;
    the trainer reads one dispatch-old scalar per step — zero added host
    syncs, zero steady-state compiles.
  * ``quarantine`` — TrainLoop rollback policy: a tripped dispatch
    restores the last verified checkpoint (PR 7 ``valid_chain()``),
    replays the non-poisoned window bit-identically, dead-letters the
    offending batch, and permanently quarantines a batch that trips
    across ``max_batch_trips`` rollbacks — the crash-loop breaker the
    Supervisor cannot provide (restart-and-replay hits the same poison
    forever).
  * row hygiene — optional per-step row-norm clamp plus an
    anomaly-eviction pass in ``Trainer.maintain()`` (rows whose norm
    explodes past a quantile bound are re-initialized and counted).
  * ``canary``    — the gated delta-publish path: ``Predictor`` evaluates
    a fixed probe batch on the shadow state BEFORE the snapshot swap; a
    failing delta is quarantined with the PR 7 rename discipline, the
    old snapshot keeps serving, and ``health()`` reports
    ``degraded: quality_gate``.

``tools/bench_guard.py`` measures the whole firewall under injected
poison (``online/faults.py`` injectors) and ``roofline.py
--assert-guard`` gates it in CI: serving AUC never crosses the floor,
ZERO failed requests, detection ≤ 1 dispatch.
"""
from deeprec_tpu.guard.canary import QualityGate, QualityGateRejected
from deeprec_tpu.guard.quarantine import (
    DeadLetter,
    GuardPolicy,
    batch_fingerprint,
)
from deeprec_tpu.guard.sentinel import (
    FLAG_GRAD_NORM,
    FLAG_LOSS_SPIKE,
    FLAG_NONFINITE_GRAD,
    FLAG_NONFINITE_LOSS,
    FLAG_ROW_NORM,
    SentinelConfig,
    flag_kinds,
    guard_init,
)

__all__ = [
    "SentinelConfig", "guard_init", "flag_kinds",
    "FLAG_NONFINITE_LOSS", "FLAG_NONFINITE_GRAD", "FLAG_GRAD_NORM",
    "FLAG_LOSS_SPIKE", "FLAG_ROW_NORM",
    "GuardPolicy", "DeadLetter", "batch_fingerprint",
    "QualityGate", "QualityGateRejected",
]
