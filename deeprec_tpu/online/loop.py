"""The online-learning loop: streaming train -> delta chain -> serve.

`TrainLoop` is the trainer half: consume batches from any iterable (a
TCPStreamReader following a broker, a FileTailReader, a WorkQueue
dataset, a synthetic generator), run `Trainer.train_step`, and emit
`save_incremental_async` on a cadence with periodic full re-anchors.
Every step stamps a lease-style heartbeat (online/supervisor.py) and the
loop honors the elastic EXIT_RESCALE contract: a posted scaling plan
checkpoints, acks, and returns the rescale exit code for the supervisor
to respawn at the new size. Save failures NEVER kill training — they are
logged, surfaced through the heartbeat, and self-heal via the
CheckpointManager's force-full escalation.

`ServeLoop` is the serving half: a Predictor + ModelServer (+ optional
HTTP front) whose poll thread survives any failure with capped jittered
backoff, quarantines corrupt deltas (serving through from the last good
snapshot), and stamps its health — staleness_seconds,
consecutive_poll_failures, last_good_version — into a heartbeat the
supervisor's wedge detection reads.

Run a trainer worker as a process (what the supervisor and
tools/bench_freshness.py spawn):

    python -m deeprec_tpu.online.loop --ckpt DIR --steps 200 \
        --source tcp://127.0.0.1:9000 --batch-size 256 --save-every 10 \
        --heartbeat DIR/trainer.hb

It prints the line protocol tests assert on: FRESH | RESUMED <step>,
STEP <n> <loss>, SAVED <kind> <step>, DONE.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Optional

from deeprec_tpu.data.pipeline import record_stall
from deeprec_tpu.obs import metrics as obs_metrics
from deeprec_tpu.obs import trace as obs_trace
from deeprec_tpu.online.supervisor import Heartbeat
from deeprec_tpu.parallel.elastic import EXIT_RESCALE, ElasticCoordinator
from deeprec_tpu.training.checkpoint import CheckpointManager

_log = logging.getLogger(__name__)


class TrainLoop:
    """Supervised continuous training over a batch stream.

    save cadence: every `save_every` steps; the first save and every
    `full_every`-th after it are FULL (chain anchors), the rest are
    incremental deltas — both on the async writer so the npz IO overlaps
    training. `on_step(step)` is the fault-injection seam (kill-at-step
    runs there, AFTER the step's save cadence fired, so a kill at a save
    step tests the async writer dying with the save in flight)."""

    def __init__(
        self,
        trainer,
        ckpt: CheckpointManager,
        batches: Iterable[Dict],
        save_every: int = 50,
        full_every: int = 10,
        heartbeat: Optional[Heartbeat] = None,
        coordinator: Optional[ElasticCoordinator] = None,
        elastic_every: int = 10,
        max_steps: Optional[int] = None,
        on_step: Optional[Callable[[int], None]] = None,
        log_every: int = 0,
        reader=None,
        guard=None,
        lr_fn: Optional[Callable[[int], float]] = None,
    ):
        self.trainer = trainer
        self.ckpt = ckpt
        self.batches = batches
        # Model-quality firewall (guard/): `guard` is a GuardPolicy and
        # requires the trainer to carry a step sentinel — the loop reads
        # the sentinel's one-dispatch-old flags scalar each step, rolls
        # back to the last verified checkpoint on a trip, dead-letters
        # the poisoned batch, and permanently quarantines repeat
        # offenders. `lr_fn(step)` optionally overrides the lr per step
        # (schedules, and the exploding-LR fault injector).
        self.guard = guard
        self.lr_fn = lr_fn
        self.dead_letter = None
        if guard is not None:
            if trainer is not None and getattr(trainer, "sentinel",
                                               None) is None:
                raise ValueError(
                    "TrainLoop(guard=) requires Trainer(sentinel="
                    "SentinelConfig(...)) — the rollback policy consumes "
                    "the on-device sentinel's flags"
                )
            from deeprec_tpu.guard.quarantine import DeadLetter

            self.dead_letter = DeadLetter(
                guard.dead_letter_dir, guard.max_batch_trips
            )
        self.guard_trips = 0
        self.rollbacks = 0
        self.batches_skipped = 0
        self.replay_gaps = 0
        # Input-stall ledger: how long the training thread waited for a
        # batch (total + last dispatch). With a staged source this is a
        # queue pop — nonzero values mean the HOST pipeline is the
        # bottleneck (docs/data.md; deeprec_input_stall_seconds).
        self.input_stall_s = 0.0
        self.last_input_stall_s = 0.0
        # [(bad_step, detect_step, flags, kinds, fingerprint)] — the
        # detection ledger tools/bench_guard.py matches injections
        # against (detect_step - bad_step is the latency in dispatches;
        # ≤ 1 by construction of the deferred flags read).
        self.trip_log: list = []
        self.last_rollback_ms: Optional[float] = None
        self.last_verified_step: Optional[int] = None
        self._guard_carry = None
        self._pending = None  # (step, batch, fingerprint, flags device ref)
        self._replay_buf: deque = deque()
        if heartbeat is None:
            # Supervisor contract (launch.py supervise_worker): a spawned
            # worker finds its lease file in DEEPREC_HEARTBEAT_FILE —
            # without this fallback a supervised worker that didn't
            # thread --heartbeat through would never stamp the lease and
            # be killed as wedged while perfectly healthy.
            hb_path = os.environ.get("DEEPREC_HEARTBEAT_FILE")
            if hb_path:
                heartbeat = Heartbeat(hb_path)
        self.save_every = max(1, int(save_every))
        self.full_every = max(1, int(full_every))
        self.heartbeat = heartbeat
        self.coordinator = coordinator
        self.elastic_every = max(1, int(elastic_every))
        self.max_steps = max_steps
        self.on_step = on_step
        self.log_every = log_every
        self.reader = reader  # optional: stream health rides the heartbeat
        self.saves = 0
        self.save_failures = 0
        self.last_save_step: Optional[int] = None
        self.last_save_error: Optional[str] = None
        # obs plane (process-wide registry; no-op singletons when off):
        # one counter inc per step is the whole per-step cost — the
        # counter's own ring answers steps/sec over any window, and the
        # gauge is refreshed at save cadence so scrapes between saves
        # stay free.
        reg = obs_metrics.default_registry()
        self._m_steps = reg.counter(
            "deeprec_train_steps", "training steps completed")
        self._m_step = reg.gauge(
            "deeprec_train_step", "current train step")
        self._m_steps_per_sec = reg.gauge(
            "deeprec_train_steps_per_sec",
            "training throughput over the trailing 30 s window")
        self._m_saves = reg.counter(
            "deeprec_train_saves", "cadence checkpoint saves")
        self._m_save_failures = reg.counter(
            "deeprec_train_save_failures", "cadence saves that failed")
        self._reg = reg
        if guard is not None:
            self._m_rollbacks = reg.counter(
                "deeprec_guard_rollbacks",
                "sentinel-tripped rollbacks to the last verified "
                "checkpoint")
            self._m_quarantined = reg.counter(
                "deeprec_guard_batches_quarantined",
                "batches permanently quarantined after repeated trips")
            self._m_last_verified = reg.gauge(
                "deeprec_guard_last_verified_step",
                "newest step whose sentinel flags read clean")
        # Whether the chain has (or will durably have — an async full may
        # still be in flight) an anchor; checking latest_full() alone
        # would race the background writer and over-anchor.
        self._anchored = ckpt.latest_full() is not None

    # ------------------------------------------------------------ helpers

    def _print(self, line: str) -> None:
        if self.log_every:
            print(line, flush=True)

    def _beat(self, step: int, status: str = "ok") -> None:
        if self.heartbeat is None:
            return
        extra = {
            "saves": self.saves,
            "save_failures": self.save_failures,
        }
        if self.guard is not None:
            # The guard-trip field the Supervisor reads to distinguish
            # "restart fixes it" from "the data poisons it" (a restart
            # budget cannot — replay hits the same poison forever).
            extra["guard_trips"] = self.guard_trips
            extra["rollbacks"] = self.rollbacks
            extra["batches_quarantined"] = self.dead_letter.permanent_count
            extra["last_verified_step"] = self.last_verified_step
        if self.reader is not None:
            extra["stream_connect_failures"] = getattr(
                self.reader, "consecutive_connect_failures", 0
            )
            extra["stream_reconnects"] = getattr(self.reader, "reconnects", 0)
        extra["input_stall_s"] = round(self.input_stall_s, 6)
        self.heartbeat.beat(step=step, status=status, **extra)

    def restore_or_init(self):
        """Resume from the (verified) chain, or start fresh — the worker
        restart entry point.

        FileNotFoundError means "fresh start" ONLY when no anchor exists
        on disk: a concurrent serving process can quarantine-rename a
        link between this process's chain verification and the np.load
        that reads it, which also surfaces as FileNotFoundError. That
        race retries (re-verification no longer lists the renamed dir);
        if the chain is still unreadable after retries we raise — a
        supervised restart beats silently training from step 0 over a
        live chain."""
        last_err = None
        for _ in range(3):
            try:
                state = self.ckpt.restore()
                self._print(f"RESUMED {int(state.step)}")
                return state
            except FileNotFoundError as e:
                if self.ckpt.latest_full() is None:
                    state = self.trainer.init(0)
                    self._print("FRESH")
                    return state
                last_err = e
                time.sleep(0.05)
        raise last_err

    def _save(self, state, step: int):
        """One cadence save; failures degrade (log + heartbeat), never
        raise into the train loop — the manager escalates the next save
        to full on a lost delta, so the chain self-heals."""
        # Full when the chain has no anchor yet (fresh dir, or everything
        # quarantined), else every full_every-th save of THIS process —
        # a restarted worker resumes on deltas, it doesn't re-anchor.
        want_full = (
            not self._anchored or (self.saves + 1) % self.full_every == 0
        )
        t0w = time.time()
        try:
            if want_full:
                state, path = self.ckpt.save_async(state)
                self._anchored = True
            else:
                state, path = self.ckpt.save_incremental_async(state)
            self.saves += 1
            self.last_save_step = step
            self.last_save_error = None
            self._m_saves.inc()
            self._m_step.set(step)
            self._m_steps_per_sec.set(self._m_steps.window_rate(30.0))
            obs_trace.phase_span(
                "ckpt_save_" + ("full" if want_full else "delta"),
                t0w, time.time(), cat="train")
            self._print(f"SAVED {os.path.basename(path).split('-')[0]} {step}")
        except Exception as e:
            self.save_failures += 1
            self.last_save_error = str(e)
            self._m_save_failures.inc()
            # A failed writer may have taken the would-be anchor with it;
            # re-derive from disk so the next cadence re-anchors if needed.
            self._anchored = self.ckpt.latest_full() is not None
            _log.warning("save at step %d failed (training continues): %s",
                         step, e)
            self._print(f"SAVE_FAILED {step}")
        return state

    # ----------------------------------------------- model-quality firewall

    def _train_one(self, state, batch, next_step: int):
        """One dispatched train step, with the lr schedule and the
        sentinel carry threaded through (device references only)."""
        import jax.numpy as jnp

        jb = {k: jnp.asarray(v) for k, v in batch.items()}
        kw = {}
        if self.lr_fn is not None:
            kw["lr"] = self.lr_fn(next_step)
        if self.guard is not None:
            kw["guard"] = self._guard_carry
        state, mets = self.trainer.train_step(state, jb, **kw)
        if self.guard is not None:
            from deeprec_tpu.guard.sentinel import guard_carry

            self._guard_carry = guard_carry(mets)
        return state, mets

    def _remember(self, step: int, batch, fp: str) -> None:
        """Append to the bounded replay buffer rollbacks resume from."""
        self._replay_buf.append((step, batch, fp))
        while len(self._replay_buf) > self.guard.replay_window:
            self._replay_buf.popleft()

    def _guard_check(self, state, step: int, batch, fp: str, mets):
        """Deferred sentinel read: park THIS step's flags, read the
        PREVIOUS dispatch's — by now materialized on the host side of an
        already-retired dispatch, so the read never stalls the pipeline
        (detection latency: exactly one dispatch). Returns the possibly
        rolled-back (state, step)."""
        import numpy as np

        prev, self._pending = (
            self._pending, (step, batch, fp, mets["guard_flags"])
        )
        if prev is None:
            return state, step
        t, b_t, fp_t, fl = prev
        flags = int(np.asarray(fl))  # noqa: DRT002 — ONE-DISPATCH-OLD scalar: its dispatch retired while the current one was enqueued, so this read is a materialized-value copy, not a pipeline stall (the sentinel's documented read contract)
        if flags == 0:
            self.last_verified_step = t
            if self.guard is not None:
                self._m_last_verified.set(t)
            return state, step
        return self._guard_rollback(state, step, t, b_t, fp_t, flags)

    def _guard_flush(self, state, step: int):
        """Drain the deferred check at a loop boundary (end of stream,
        max_steps): the final dispatch's flags must be read before the
        final save can be trusted."""
        import numpy as np

        prev, self._pending = self._pending, None
        if prev is None:
            return state, step
        t, b_t, fp_t, fl = prev
        flags = int(np.asarray(fl))  # noqa: DRT002 — loop-boundary drain, once per run
        if flags == 0:
            self.last_verified_step = t
            self._m_last_verified.set(t)
            return state, step
        return self._guard_rollback(state, step, t, b_t, fp_t, flags)

    def _record_trip(self, fp: str, step: int, flags: int, batch,
                     detect_step: Optional[int] = None) -> None:
        from deeprec_tpu.guard.sentinel import flag_kinds

        kinds = flag_kinds(flags)
        self.trip_log.append(
            (step, detect_step if detect_step is not None else step,
             flags, kinds, fp)
        )
        self.guard_trips += 1
        for kind in kinds:  # bounded label set: the five sentinel bits
            self._reg.counter(
                "deeprec_guard_trips",
                "step-sentinel trips by tripped check", {"kind": kind},
            ).inc()
        permanent = self.dead_letter.record_trip(fp, step, flags, kinds,
                                                 batch)
        self._print(f"GUARD_TRIP {step} {flags} {','.join(kinds)}")
        if permanent:
            self._m_quarantined.inc()
            self._print(f"GUARD_QUARANTINE {fp}")
        _log.warning("guard: sentinel tripped at step %d (%s)%s", step,
                     ",".join(kinds),
                     " — batch permanently quarantined" if permanent else "")

    def _restore_verified(self):
        """Restore the chain tip (valid_chain semantics); a chain with
        nothing left restarts from step 0 — loud, never wedged.

        MODEL state only: `CheckpointManager.restore` also rewinds any
        registered dataset readers to the checkpoint's positions, but the
        rollback replays its window from the in-memory buffer — a
        rewound reader would re-deliver the same batches and the window
        would train TWICE (and a TCP reader's offset would undercount,
        replaying trained data across the next reconnect). Reader
        positions are pinned across the restore so the live stream
        resumes exactly where it was."""
        self.rollbacks += 1
        self._m_rollbacks.inc()
        # Detach registered readers for the duration: restore() must not
        # touch their positions at all (not even transiently — a reader
        # polling from another thread could read the rewound offset).
        readers = self.ckpt.datasets
        self.ckpt.datasets = {}
        try:
            return self.ckpt.restore()
        except FileNotFoundError:
            _log.warning("guard: no intact checkpoint predates the poison "
                         "— restarting from a fresh init")
            return self.trainer.init(0)
        finally:
            self.ckpt.datasets = readers

    def _guard_rollback(self, state, step: int, bad_step: int, bad_batch,
                        bad_fp: str, flags: int):
        """The semantic-fault recovery: dead-letter the batch, drop every
        chain link that may carry its update, restore the last verified
        checkpoint, and replay the buffered non-poisoned window — the
        result is bit-identical to a clean run minus the skipped batch
        (tests/test_guard.py pins it on table contents)."""
        import numpy as np

        t0 = time.perf_counter()
        self._record_trip(bad_fp, bad_step, flags, bad_batch,
                          detect_step=step)
        self._pending = None
        self._guard_carry = None
        # Saves at or past the poisoned step captured poisoned state —
        # quarantine them (PR 7 rename discipline; _effective_kind then
        # escalates the next save to full, re-anchoring the chain).
        try:
            self.ckpt.wait()
        except RuntimeError:
            pass  # a lost async save is already escalated to full
        for kind in ("full", "incr"):
            for s in self.ckpt._list(kind):
                if s >= bad_step:
                    self.ckpt.quarantine(
                        os.path.join(self.ckpt.dir, f"{kind}-{s}"),
                        f"guard rollback past poisoned step {bad_step}",
                    )
        self._anchored = self.ckpt.latest_full() is not None
        state = self._restore_verified()
        s0 = int(state.step)  # noqa: DRT002 — rollback cadence, not the step loop
        # Replay the buffered window minus the poisoned batch. A tripped
        # REPLAYED batch is dead-lettered, dropped from the queue, and
        # the pass restarts from the same restored anchor (no saves run
        # during replay, so the anchor is stable); the queue shrinks by
        # one per trip, so this terminates.
        queue = [(b, f) for (s, b, f) in self._replay_buf
                 if s0 < s <= step and s != bad_step]
        expect = max(
            0, step - s0 - (1 if s0 < bad_step <= step else 0)
        )
        if len(queue) < expect:
            self.replay_gaps += 1
            _log.warning(
                "guard: replay buffer covers %d of %d rolled-back steps "
                "(GuardPolicy.replay_window too small for the save "
                "cadence) — resuming with a gap", len(queue), expect)
        while True:
            tripped = False
            cur = int(state.step)  # noqa: DRT002 — rollback cadence, not the step loop
            self._guard_carry = None
            for qi, (b, f) in enumerate(queue):
                state, mets = self._train_one(state, b, cur + 1)
                cur += 1
                fl = int(np.asarray(mets["guard_flags"]))  # noqa: DRT002 — replay is the cold recovery path: synchronous checks ARE the point here
                if fl:
                    self._record_trip(f, cur, fl, b)
                    queue = queue[:qi] + queue[qi + 1:]
                    state = self._restore_verified()
                    tripped = True
                    break
            if not tripped:
                break
        new_step = int(state.step)  # noqa: DRT002 — rollback cadence, not the step loop
        self._replay_buf = deque(
            (s0 + i + 1, b, f) for i, (b, f) in enumerate(queue)
        )
        self.last_rollback_ms = round((time.perf_counter() - t0) * 1e3, 3)
        self.last_verified_step = new_step
        self._m_last_verified.set(new_step)
        self._print(f"GUARD_ROLLBACK {bad_step} -> {new_step}")
        self._beat(new_step, status="degraded")
        return state, new_step

    # ---------------------------------------------------------------- run

    def run(self, state=None):
        """Returns (final_state, exit_code): 0 done, EXIT_RESCALE when a
        scaling plan was acked (caller exits with it; the supervisor
        respawns the new generation)."""
        if state is None:
            state = self.restore_or_init()
        # Host-side step mirror: train_step advances the device counter by
        # exactly 1, so ONE sync here seeds a host int and the loop never
        # blocks on the step scalar again. The previous per-iteration
        # int(state.step) was a device sync on EVERY step (DRT002) — it
        # made the host wait for each dispatch to finish before enqueueing
        # the next, forfeiting the async-dispatch overlap.
        step = int(state.step)
        self._beat(step, status="running")
        guard_on = self.guard is not None
        batches = iter(self.batches)
        while True:
            # Batch acquisition is timed: with a staged source this is a
            # queue pop, so the wait IS the host-input stall — exported
            # per dispatch as deeprec_input_stall_seconds{site=train_loop}
            # and totalled into the heartbeat (input_stall_s).
            t0_in = time.perf_counter()
            try:
                batch = next(batches)
            except StopIteration:
                break
            wait = time.perf_counter() - t0_in
            self.input_stall_s += wait
            self.last_input_stall_s = wait
            record_stall("train_loop", wait)
            if self.max_steps is not None and step >= self.max_steps:
                break  # a resumed worker may already be at the target
            fp = None
            if guard_on:
                from deeprec_tpu.guard.quarantine import batch_fingerprint

                fp = batch_fingerprint(batch)
                if self.dead_letter.is_quarantined(fp):
                    # The crash-loop breaker: a permanently quarantined
                    # batch never reaches the trainer again, across any
                    # number of restarts and stream replays.
                    self.batches_skipped += 1
                    self._print(f"GUARD_SKIP {fp}")
                    continue
            state, mets = self._train_one(state, batch, step + 1)
            step += 1
            self._m_steps.inc()
            if guard_on:
                self._remember(step, batch, fp)
                state, step = self._guard_check(state, step, batch, fp,
                                                mets)
            if self.log_every and step % self.log_every == 0:
                self._print(f"STEP {step} {float(mets['loss']):.5f}")  # noqa: DRT002 — log-cadence-gated sync, deliberate
            if step % self.save_every == 0:
                state = self._save(state, step)
            self._beat(
                step,
                status="ok" if self.last_save_error is None else "degraded",
            )
            if self.coordinator is not None and step % self.elastic_every == 0:
                target = self.coordinator.should_scale()
                if target is not None:
                    # Elastic contract: durable checkpoint, ack, planned
                    # exit — the supervisor respawns at the new size.
                    try:
                        self.ckpt.wait()
                    except RuntimeError:
                        pass  # lost async delta: the sync full below re-anchors
                    state, _ = self.ckpt.save(state)
                    self.coordinator.ack_rescale()
                    self._print(f"RESCALE {step} -> {target}")
                    return state, EXIT_RESCALE
            if self.on_step is not None:
                self.on_step(step)
            if self.max_steps is not None and step >= self.max_steps:
                break
        if guard_on:
            # The final dispatch's flags are still pending — read them
            # before trusting the final save with its state.
            state, step = self._guard_flush(state, step)
        # Drain the writer and flush rows dirtied since the last cadence
        # save, so a clean exit leaves a chain as fresh as training got.
        try:
            self.ckpt.wait()
            if self.last_save_step != step:
                state = self._save(state, step)
                self.ckpt.wait()
        except Exception as e:
            self.save_failures += 1
            self.last_save_error = str(e)
            _log.warning("final save failed: %s", e)
        self._beat(step, status="done")
        self._print("DONE")
        return state, 0


def wait_for_full_checkpoint(ckpt_dir: str, timeout: float = 120.0,
                             poll_secs: float = 0.25) -> None:
    """Block until some full checkpoint is committed under `ckpt_dir` —
    serving can only boot from an anchor. Raises TimeoutError."""
    import re

    deadline = time.monotonic() + timeout
    pat = re.compile(r"^full-(\d+)$")
    while True:
        try:
            names = os.listdir(ckpt_dir)
        except OSError:
            names = []
        for d in names:
            if pat.match(d) and os.path.exists(
                os.path.join(ckpt_dir, d, "manifest.json")
            ):
                return
        if time.monotonic() > deadline:
            raise TimeoutError(
                f"no full checkpoint appeared under {ckpt_dir} "
                f"within {timeout}s"
            )
        time.sleep(poll_secs)


class ServeLoop:
    """Serving half of the loop: poll the delta chain under live load.

    Wraps Predictor + ModelServer (+ HttpServer when `http_port` is not
    None; 0 picks a free port) with a poll thread that:
      * NEVER dies — failures back off (capped, jittered) and retry;
      * quarantines corrupt deltas via the manager and keeps serving the
        last good snapshot (degraded-serving contract);
      * stamps every round's health into `heartbeat` for the
        supervisor's wedge detection (a wedged poller stops beating; a
        failing one beats with status="degraded" — distinguishable).
    `pause()`/`resume()` gate the polling for deterministic fault tests
    (corrupt a delta BEFORE the poller can apply it)."""

    def __init__(
        self,
        model,
        ckpt_dir: str,
        poll_secs: float = 0.5,
        heartbeat: Optional[Heartbeat] = None,
        http_port: Optional[int] = None,
        max_batch: int = 64,
        max_wait_ms: float = 2.0,
        device=None,
        stores: Optional[Dict] = None,
        max_backoff_secs: float = 10.0,
        wait_for_checkpoint_secs: float = 0.0,
        quality_gate=None,
    ):
        from deeprec_tpu.serving.http_server import HttpServer
        from deeprec_tpu.serving.predictor import ModelServer, Predictor

        if wait_for_checkpoint_secs > 0:
            wait_for_full_checkpoint(ckpt_dir, wait_for_checkpoint_secs)
        self.predictor = Predictor(model, ckpt_dir, stores=stores,
                                   device=device, quality_gate=quality_gate)
        self.server = ModelServer(self.predictor, max_batch=max_batch,
                                  max_wait_ms=max_wait_ms)
        self.http = None
        if http_port is not None:
            self.http = HttpServer(self.server, port=http_port).start()
        self.heartbeat = heartbeat
        self.poll_secs = poll_secs
        self.max_backoff_secs = max_backoff_secs
        self.poll_rounds = 0
        self.update_failures = 0
        self._stop = threading.Event()
        self._paused = threading.Event()
        self._thread = threading.Thread(
            target=self._poll_loop, daemon=True, name="serve-poll"
        )
        self._thread.start()

    # ------------------------------------------------------------ polling

    def _poll_loop(self) -> None:
        # The shared survivability loop (predictor._run_poll_loop: never
        # dies, capped jittered backoff); this class only adds the pause
        # gate and the per-round heartbeat stamp.
        from deeprec_tpu.serving.predictor import _run_poll_loop

        _run_poll_loop(self, self._stop, self.poll_secs,
                       max_backoff_secs=self.max_backoff_secs,
                       pause=self._paused, on_round=self._on_round)

    def _on_round(self, status: str) -> None:
        self.poll_rounds += 1
        if self.heartbeat is None:
            return
        # The heartbeat payload IS the unified health schema
        # (obs/schema.py — the predictor emits it), re-stamped with the
        # poll round's own status; historical keys ride along as
        # canonical members, so existing readers keep working.
        h = self.predictor.health()
        h["status"] = status if status != "ok" else h["status"]
        self.heartbeat.beat(**h)

    def pause(self) -> None:
        self._paused.set()

    def resume(self) -> None:
        self._paused.clear()

    def poll_now(self) -> bool:
        """Synchronous poll (test/bench convenience; same lock as the
        background thread, so it composes)."""
        return self.predictor.poll_updates()

    # ------------------------------------------------------------ facade

    def request_versioned(self, features, timeout: float = 30.0):
        return self.server.request_versioned(features, timeout=timeout)

    def warmup(self, example) -> int:
        return self.server.warmup(example)

    def health(self) -> Dict:
        return self.predictor.health()

    def close(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        if self.http is not None:
            self.http.stop()
        self.server.close()


# -------------------------------------------------------- worker entry


def _build_reader(source: str, batch_size: int, num_dense: int,
                  num_cat: int):
    """'synthetic' | 'tcp://host:port' | 'tail:path' -> (iterable, reader
    or None). The tcp reader is returned for offset checkpointing."""
    if source.startswith("tcp://"):
        from deeprec_tpu.data.stream import TCPStreamReader

        host, port = source[len("tcp://"):].rsplit(":", 1)
        r = TCPStreamReader(host, int(port), batch_size=batch_size,
                            num_dense=num_dense, num_cat=num_cat,
                            reconnect_secs=0.2)
        return iter(r), r
    if source.startswith("tail:"):
        from deeprec_tpu.data.stream import FileTailReader

        r = FileTailReader(source[len("tail:"):], batch_size=batch_size,
                           num_dense=num_dense, num_cat=num_cat,
                           poll_secs=0.1)
        return iter(r), r
    from deeprec_tpu.data import SyntheticCriteo

    gen = SyntheticCriteo(batch_size=batch_size, num_cat=num_cat,
                          num_dense=num_dense, vocab=500, seed=0)

    def batches():
        while True:
            yield gen.batch()

    return batches(), None


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(description="online training worker")
    p.add_argument("--ckpt", required=True)
    p.add_argument("--source", default="synthetic",
                   help="synthetic | tcp://host:port | tail:path")
    p.add_argument("--steps", type=int, default=200)
    p.add_argument("--batch-size", type=int, default=256)
    p.add_argument("--save-every", type=int, default=10)
    p.add_argument("--full-every", type=int, default=10)
    p.add_argument("--heartbeat",
                   default=os.environ.get("DEEPREC_HEARTBEAT_FILE"))
    p.add_argument("--elastic-dir",
                   default=os.environ.get("DEEPREC_ELASTIC_DIR"))
    p.add_argument("--num-cat", type=int, default=2)
    p.add_argument("--num-dense", type=int, default=2)
    p.add_argument("--emb-dim", type=int, default=4)
    p.add_argument("--capacity", type=int, default=1 << 12)
    p.add_argument("--lr", type=float, default=0.2)
    p.add_argument("--log-every", type=int, default=1)
    args = p.parse_args(argv)

    import optax

    from deeprec_tpu.models import WDL
    from deeprec_tpu.online import faults
    from deeprec_tpu.optim import Adagrad
    from deeprec_tpu.training import Trainer

    hb = Heartbeat(args.heartbeat) if args.heartbeat else None
    if hb is not None:
        hb.beat(status="booting")  # leases start before the first compile

    model = WDL(emb_dim=args.emb_dim, capacity=args.capacity, hidden=(16,),
                num_cat=args.num_cat, num_dense=args.num_dense)
    tr = Trainer(model, Adagrad(lr=args.lr), optax.adam(5e-3))
    batches, reader = _build_reader(args.source, args.batch_size,
                                    args.num_dense, args.num_cat)
    datasets = {"stream": reader} if reader is not None else None
    ck = CheckpointManager(args.ckpt, tr, datasets=datasets)
    coord = (
        ElasticCoordinator(args.elastic_dir) if args.elastic_dir else None
    )
    loop = TrainLoop(
        tr, ck, batches, save_every=args.save_every,
        full_every=args.full_every, heartbeat=hb, coordinator=coord,
        max_steps=args.steps, on_step=faults.env_kill_step(),
        log_every=args.log_every, reader=reader,
    )
    _, code = loop.run()
    return code


if __name__ == "__main__":
    raise SystemExit(main())
