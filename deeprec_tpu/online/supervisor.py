"""Process supervision for the online-learning loop: heartbeat leases,
wedge detection, and restarts under an exponential-backoff budget.

The reference runs an external dead-PS detector plus a restart protocol
(SURVEY §5); on a TPU pod the equivalent control plane is a supervisor
process on the same host (or the K8s operator above it) watching
lease-style heartbeat files on the shared FS:

  * every worker stamps `<name>.hb` once per unit of progress (train
    step, serve poll round) via `Heartbeat.beat` — an atomic
    tmp+rename JSON write, so a reader never sees a torn lease;
  * the supervisor declares a worker WEDGED when its lease is older than
    `lease_secs` (live process, no progress — a hung collective, a
    deadlocked writer) and kills it; a dead process is detected by
    `Popen.poll` directly;
  * either way the worker is restarted with capped exponential backoff,
    against a `max_restarts` consecutive-failure budget (reset by any
    healthy stretch), so a crash-looping worker degrades to a loud
    terminal failure instead of a fork bomb;
  * exit code `elastic.EXIT_RESCALE` is the PLANNED-exit contract from
    `parallel/elastic.py`: the worker checkpointed and acked a scaling
    plan, so the supervisor respawns it immediately (optionally with new
    argv from `on_rescale`) without charging the failure budget.

`deeprec_tpu.launch.supervise_elastic` remains the multi-process rescale
choreographer; this Supervisor adds the liveness half (death + wedge +
budget) and is what `tools/bench_freshness.py` drives faults against.
"""
from __future__ import annotations

import json
import os
import random
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Union

from deeprec_tpu.parallel.elastic import EXIT_RESCALE
from deeprec_tpu.utils import backoff as _backoff

_log_lock = threading.Lock()


def _now() -> float:
    return time.time()


class Heartbeat:
    """Lease-style liveness file: one atomic JSON stamp per progress unit.

    Format: ``{"pid": int, "time": unix_seconds, "step": int|null,
    "status": str, ...extra}``. Writes go through a tempfile in the same
    directory + ``os.replace`` so a reader (the supervisor, possibly on
    another host via shared FS) sees either the previous or the new
    stamp, never a torn one — the same commit discipline as the
    checkpoint manifest and the WorkQueue cursor."""

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def beat(self, step: Optional[int] = None, status: str = "ok",
             **extra) -> None:
        payload = {"pid": os.getpid(), "time": _now(), "step": step,
                   "status": status, **extra}
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, self.path)
        except OSError:
            # A heartbeat must never take its worker down with it (full
            # disk, vanished dir): missing beats surface as a stale lease
            # on the supervisor side, which is the correct signal anyway.
            try:
                os.unlink(tmp)
            except OSError:
                pass

    @staticmethod
    def read(path: str) -> Optional[dict]:
        """Last stamp, or None when missing/unreadable (a torn stamp is
        impossible by construction, so unreadable means 'no lease')."""
        try:
            with open(path) as f:
                return json.load(f)
        except (OSError, ValueError):
            return None

    @staticmethod
    def age(path: str, now: Optional[float] = None) -> Optional[float]:
        """Seconds since the last stamp, or None when there is none."""
        hb = Heartbeat.read(path)
        if hb is None or "time" not in hb:
            return None
        return max(0.0, (now if now is not None else _now()) - hb["time"])


@dataclass
class ProcessSpec:
    """One supervised worker.

    argv may be a list or a zero-arg callable returning one (re-evaluated
    on every (re)spawn, so restarts can pick up new ports/paths).
    `lease_secs=None` disables wedge detection (processes that only make
    coarse progress). `grace_secs` is how long after a (re)spawn the
    lease clock is suspended — JAX import + first compile produce no
    steps for tens of seconds and must not read as a wedge."""

    name: str
    argv: Union[Sequence[str], Callable[[], Sequence[str]]]
    heartbeat_path: Optional[str] = None
    lease_secs: Optional[float] = 15.0
    grace_secs: float = 60.0
    max_restarts: int = 5
    backoff_base_secs: float = 0.5
    backoff_max_secs: float = 30.0
    # dict, or a zero-arg callable returning one (re-evaluated per spawn:
    # fresh coordinator ports and the like)
    env: Optional[Union[dict, Callable[[], dict]]] = None
    cwd: Optional[str] = None
    # EXIT_RESCALE hook: called with this spec; may return replacement
    # argv for the next generation (None keeps the current argv).
    on_rescale: Optional[Callable[["ProcessSpec"], Optional[Sequence]]] = None
    stdout: Optional[str] = None  # path; worker stderr is merged into it


@dataclass
class _ProcState:
    proc: Optional[subprocess.Popen] = None
    spawned_at: float = 0.0
    consecutive_failures: int = 0
    restarts: int = 0
    wedge_kills: int = 0
    rescales: int = 0
    last_exit: Optional[int] = None
    gave_up: bool = False
    done: bool = False  # clean zero exit: not restarted
    next_spawn_at: float = 0.0  # backoff gate
    log: List[str] = field(default_factory=list)


class Supervisor:
    """Watch a set of ProcessSpecs: restart the dead, kill-and-restart
    the wedged, respawn EXIT_RESCALE exits for free, and give up loudly
    when a worker exhausts its consecutive-failure budget.

    Use either as a foreground loop (`run(stop_event)`) or started on a
    thread (`start()` / `stop()`). `stats()` returns per-worker restart
    accounting — the numbers `tools/bench_freshness.py` records per
    injected fault."""

    def __init__(self, specs: Sequence[ProcessSpec], poll_secs: float = 0.25,
                 on_event: Optional[Callable[[str], None]] = None,
                 keep_alive: bool = False):
        self.specs = list(specs)
        self.poll_secs = poll_secs
        self._states: Dict[str, _ProcState] = {
            s.name: _ProcState() for s in self.specs
        }
        self._on_event = on_event
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._rng = random.Random(0xFA117)
        # Elastic fleets add/remove specs at runtime (the serving
        # autoscaler): mutations serialize on _speclock, and keep_alive
        # stops run() from returning in the window where every CURRENT
        # worker happens to be done (more may be added next tick).
        self.keep_alive = keep_alive
        self._speclock = threading.Lock()

    # ------------------------------------------------------------- events

    def _event(self, spec_name: str, msg: str) -> None:
        line = f"supervisor[{spec_name}]: {msg}"
        st = self._states.get(spec_name)
        if st is not None:  # spec may have been removed mid-event
            st.log.append(line)
        if self._on_event is not None:
            self._on_event(line)
        else:
            with _log_lock:
                print(line, file=sys.stderr, flush=True)

    # -------------------------------------------------------------- spawn

    def _argv(self, spec: ProcessSpec) -> List[str]:
        a = spec.argv() if callable(spec.argv) else spec.argv
        return [str(x) for x in a]

    def _spawn(self, spec: ProcessSpec) -> None:
        st = self._states.get(spec.name)
        if st is None:
            return  # spec removed (fleet retire) between check and spawn
        env = dict(os.environ)
        if spec.env:
            extra = spec.env() if callable(spec.env) else spec.env
            env.update({k: str(v) for k, v in extra.items()})
        out = None
        if spec.stdout:
            out = open(spec.stdout, "ab")
        st.proc = subprocess.Popen(
            self._argv(spec), env=env, cwd=spec.cwd,
            stdout=out, stderr=subprocess.STDOUT if out else None,
        )
        if out is not None:
            out.close()  # child holds its own descriptor
        st.spawned_at = time.monotonic()
        self._event(spec.name, f"spawned pid {st.proc.pid}")

    def start(self) -> "Supervisor":
        # Same guard as run()'s startup loop: a spec added via add_spec
        # before start() already has a live proc (and add_spec holds the
        # next_spawn_at=inf gate while ITS spawn runs) — spawning again
        # would double-fork and orphan the first PID.
        now = time.monotonic()
        for spec in list(self.specs):
            st = self._states.get(spec.name)
            if st is not None and st.proc is None and now >= st.next_spawn_at:
                self._spawn(spec)
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="online-supervisor")
        self._thread.start()
        return self

    # -------------------------------------------------------------- watch

    def run(self, stop: Optional[threading.Event] = None) -> None:
        stop = stop or self._stop
        # Foreground use: spawn anything start() didn't. Honors the
        # next_spawn_at gate (add_spec publishes inf while IT spawns —
        # spawning here too would double-fork and orphan one PID) and
        # tolerates specs removed concurrently.
        now = time.monotonic()
        for spec in list(self.specs):
            st = self._states.get(spec.name)
            if st is not None and st.proc is None and now >= st.next_spawn_at:
                self._spawn(spec)
        while not stop.wait(self.poll_secs):
            for spec in list(self.specs):
                self._check(spec)
            if not self.keep_alive and all(
                    s.done or s.gave_up
                    for s in list(self._states.values())):
                return

    def _check(self, spec: ProcessSpec) -> None:
        st = self._states.get(spec.name)
        if st is None or st.done or st.gave_up:
            return  # removed mid-round (fleet retire) or settled
        now = time.monotonic()
        if st.proc is None:
            if now >= st.next_spawn_at:
                self._spawn(spec)
            return
        rc = st.proc.poll()
        if rc is None:
            # A healthy stretch (alive past the startup grace) repays the
            # consecutive-failure budget: only back-to-back crashes with
            # no real work in between exhaust it.
            if (st.consecutive_failures
                    and now - st.spawned_at > spec.grace_secs):
                st.consecutive_failures = 0
            self._check_lease(spec, st, now)
            return
        st.last_exit = rc
        st.proc = None
        if rc == 0:
            st.done = True
            self._event(spec.name, "exited cleanly")
            return
        if rc == EXIT_RESCALE:
            # Planned exit (elastic contract): checkpointed + acked, so a
            # respawn is free — no backoff, budget untouched, and the
            # hook may hand back resized argv.
            st.rescales += 1
            st.consecutive_failures = 0
            if spec.on_rescale is not None:
                new_argv = spec.on_rescale(spec)
                if new_argv is not None:
                    spec.argv = list(new_argv)
            self._event(spec.name, f"EXIT_RESCALE -> respawn (#{st.rescales})")
            self._spawn(spec)
            return
        self._restart(spec, st, f"died rc={rc}")

    def _check_lease(self, spec: ProcessSpec, st: _ProcState,
                     now: float) -> None:
        if spec.lease_secs is None or spec.heartbeat_path is None:
            return
        if now - st.spawned_at < spec.grace_secs:
            return  # startup grace: imports/compiles beat no leases
        age = Heartbeat.age(spec.heartbeat_path)
        # A missing lease after grace counts as wedged too (the worker
        # never reached its loop), with the spawn moment as its "stamp".
        if age is None:
            age = now - st.spawned_at
        if age <= spec.lease_secs:
            return
        self._event(
            spec.name,
            f"wedged (lease {age:.1f}s > {spec.lease_secs}s) -> SIGKILL",
        )
        try:
            st.proc.kill()
            st.proc.wait(timeout=10)
        except (OSError, subprocess.TimeoutExpired):
            pass
        st.last_exit = -signal.SIGKILL
        st.proc = None
        # Incremented LAST: stats() readers gating on wedge_kills (tests,
        # the freshness bench) must observe the kill's outcome fields.
        st.wedge_kills += 1
        self._restart(spec, st, "wedged")

    def _restart(self, spec: ProcessSpec, st: _ProcState, why: str) -> None:
        st.consecutive_failures += 1
        if st.consecutive_failures > spec.max_restarts:
            st.gave_up = True
            self._event(
                spec.name,
                f"{why}; restart budget exhausted "
                f"({spec.max_restarts}) — giving up",
            )
            return
        delay = _backoff.jittered_backoff(
            st.consecutive_failures, spec.backoff_base_secs,
            spec.backoff_max_secs, self._rng)
        st.restarts += 1
        st.next_spawn_at = time.monotonic() + delay
        self._event(
            spec.name,
            f"{why}; restart {st.consecutive_failures}/{spec.max_restarts} "
            f"in {delay:.2f}s",
        )

    # ------------------------------------------------------------ control

    def add_spec(self, spec: ProcessSpec, spawn: bool = True) -> None:
        """Adopt a NEW worker at runtime (the serving autoscaler's
        scale-up path): the spec joins the watch set and is spawned
        immediately (or on the next poll round when `spawn=False`).

        The spec is published with its spawn gate CLOSED
        (next_spawn_at=inf) until our own _spawn below finishes —
        otherwise the poll loop's _check can race us in the window
        between publish and spawn and fork a SECOND process that the
        state record then orphans (untracked, unkilled at stop())."""
        st = _ProcState()
        if spawn:
            st.next_spawn_at = float("inf")
        with self._speclock:
            if spec.name in self._states:
                raise ValueError(f"duplicate spec name {spec.name!r}")
            self._states[spec.name] = st
            self.specs.append(spec)
        if spawn:
            try:
                self._spawn(spec)
            finally:
                st.next_spawn_at = 0.0

    def remove_spec(self, name: str, kill: bool = True) -> bool:
        """Stop watching a worker (the scale-down path). The polite
        retirement is: request a drain, wait for the worker's clean
        exit (state.done), THEN remove — `kill=False` leaves a
        still-running process alone (it is expected to exit on its
        own); `kill=True` reaps it. Returns whether the spec existed."""
        with self._speclock:
            st = self._states.pop(name, None)
            self.specs = [s for s in self.specs if s.name != name]
        if st is None:
            return False
        if kill and st.proc is not None:
            try:
                st.proc.kill()
                st.proc.wait(timeout=10)
            except (OSError, subprocess.TimeoutExpired):
                pass
        return True

    def state(self, name: str) -> Optional[_ProcState]:
        """The live accounting record for one worker (None when
        unknown) — the autoscaler polls `.done` to confirm a retired
        member's clean exit before removing its spec."""
        return self._states.get(name)

    def note_progress(self, name: str) -> None:
        """External progress signal (e.g. the bench saw fresh steps
        served): resets the worker's consecutive-failure budget, so only
        back-to-back failures with no useful work in between exhaust it."""
        self._states[name].consecutive_failures = 0

    def pid(self, name: str) -> Optional[int]:
        p = self._states[name].proc
        return p.pid if p is not None else None

    def kill(self, name: str, sig: int = signal.SIGKILL) -> bool:
        """Fault-injection surface: signal a supervised worker (the
        supervisor then notices the death and restarts it on budget)."""
        p = self._states[name].proc
        if p is None:
            return False
        try:
            os.kill(p.pid, sig)
            return True
        except OSError:
            return False

    def stats(self) -> Dict[str, Dict]:
        """Per-worker restart accounting (the numbers
        tools/bench_freshness.py records per injected fault), extended
        with the lease view — heartbeat age and remaining restart
        budget — and mirrored into the obs plane as per-worker gauges
        (deeprec_supervisor_*, worker=<spec name>: bounded label set)."""
        from deeprec_tpu.obs import metrics as obs_metrics

        reg = (obs_metrics.default_registry()
               if obs_metrics.metrics_enabled() else None)
        specs = {s.name: s for s in list(self.specs)}
        out = {}
        for name, st in list(self._states.items()):
            spec = specs.get(name)
            hb = (Heartbeat.read(spec.heartbeat_path)
                  if spec is not None and spec.heartbeat_path else None)
            hb_age = (max(0.0, _now() - hb["time"])
                      if hb and "time" in hb else None)
            out[name] = {
                "restarts": st.restarts,
                "wedge_kills": st.wedge_kills,
                "rescales": st.rescales,
                "consecutive_failures": st.consecutive_failures,
                "last_exit": st.last_exit,
                "gave_up": st.gave_up,
                "done": st.done,
                "alive": st.proc is not None and st.proc.poll() is None,
                "heartbeat_age_seconds": (
                    round(hb_age, 3) if hb_age is not None else None),
                "restart_budget_remaining": (
                    max(0, spec.max_restarts - st.consecutive_failures)
                    if spec is not None else None),
            }
            if hb is not None and "guard_trips" in hb:
                # Quality-firewall view (TrainLoop guard heartbeat
                # fields): a worker with restarts AND rising guard_trips
                # is poisoned by its DATA — a restart budget cannot fix
                # that; the permanent batch quarantine does.
                out[name]["guard_trips"] = hb.get("guard_trips")
                out[name]["rollbacks"] = hb.get("rollbacks")
                out[name]["batches_quarantined"] = hb.get(
                    "batches_quarantined")
                out[name]["last_verified_step"] = hb.get(
                    "last_verified_step")
            if reg is not None:
                lab = {"worker": name}
                reg.gauge("deeprec_supervisor_restarts",
                          "worker restarts", lab).set(st.restarts)
                reg.gauge("deeprec_supervisor_wedge_kills",
                          "wedge-detected kills", lab).set(st.wedge_kills)
                reg.gauge("deeprec_supervisor_restart_budget_remaining",
                          "consecutive failures left before give-up",
                          lab).set(out[name]["restart_budget_remaining"]
                                   or 0)
                reg.gauge("deeprec_supervisor_alive",
                          "worker process liveness",
                          lab).set(1 if out[name]["alive"] else 0)
                if hb_age is not None:
                    reg.gauge("deeprec_supervisor_heartbeat_age_seconds",
                              "age of the worker's lease stamp",
                              lab).set(hb_age)
        return out

    def stop(self, kill_workers: bool = True) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        if kill_workers:
            for st in list(self._states.values()):
                if st.proc is not None:
                    try:
                        st.proc.kill()
                        st.proc.wait(timeout=10)
                    except (OSError, subprocess.TimeoutExpired):
                        pass
                    st.proc = None
