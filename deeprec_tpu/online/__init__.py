"""Continuous-training subsystem: trainer -> delta chain -> serving as one
supervised pipeline (ROADMAP "close the online-learning loop").

The DeepRec production story (SURVEY §5 failure detection + incremental
replay, §3.4 ModelInstanceMgr) recomposed from this repo's parts:

  * `online.loop.TrainLoop`   — consume a stream/WorkQueue, emit
    `save_incremental_async` on a cadence, stamp heartbeats, honor the
    elastic EXIT_RESCALE contract.
  * `online.loop.ServeLoop`   — Predictor + ModelServer (+ optional HTTP
    front) polling the delta chain under live load, with a poll thread
    that survives any failure and heartbeats its health.
  * `online.supervisor`       — lease-style heartbeat files, and a
    Supervisor that restarts dead or wedged worker processes under an
    exponential-backoff restart budget.
  * `online.faults`           — deterministic fault injectors (kill at
    step, torn checkpoint write, corrupt-delta bit flip, broker outage)
    shared by the tests and `tools/bench_freshness.py`.

See docs/fault-tolerance.md for the supervision model and the
degraded-serving contract.
"""
_EXPORTS = {
    "TrainLoop": "deeprec_tpu.online.loop",
    "ServeLoop": "deeprec_tpu.online.loop",
    "wait_for_full_checkpoint": "deeprec_tpu.online.loop",
    "Heartbeat": "deeprec_tpu.online.supervisor",
    "ProcessSpec": "deeprec_tpu.online.supervisor",
    "Supervisor": "deeprec_tpu.online.supervisor",
}

__all__ = sorted(_EXPORTS)


def __getattr__(name):
    # Lazy re-exports: `python -m deeprec_tpu.online.loop` must not find
    # the module pre-imported by its own package __init__ (runpy warns,
    # and the double-import would run module code twice).
    if name in _EXPORTS:
        import importlib

        return getattr(importlib.import_module(_EXPORTS[name]), name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
