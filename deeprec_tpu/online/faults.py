"""Deterministic fault injectors for the online-learning loop.

Promoted out of tests/test_fault_recovery.py's inline subprocess
machinery so the checkpoint-corruption matrix, the poll-survivability
tests, and tools/bench_freshness.py all drive the SAME failure modes:

  * `kill_self_at_step` / `env_kill_step` — SIGKILL the current process
    the moment a given train step completes (a real kill -9, not a
    polite exception), wired through `TrainLoop` via the
    DEEPREC_FAULT_KILL_STEP env var for subprocess workers.
  * `install_torn_write` — arm the CheckpointManager's `on_write` seam
    (PR 4) to leave a half-written dir: real table file, no manifest —
    exactly what a writer killed between two np.savez calls leaves.
  * `corrupt_latest_delta` / `flip_bit` — flip one bit in a COMMITTED
    checkpoint's payload, the post-commit corruption class (disk rot,
    truncating copy) that manifests digests + quarantine exist for.
  * `truncate_file` — tear a committed npz (partial copy / torn fsync).
  * `BrokerOutage` — stop a FileStreamServer and later revive it on the
    same port, the broker-disconnect class TCPStreamReader's backoff
    reconnect handles.
  * subprocess helpers (`spawn_worker`, `wait_for_line`, `sigkill`) for
    tests that need a real process to murder.
  * fleet injectors (`torn_lease_write`, `env_slow_join_secs`,
    `sigkill_fleet_member`) — the serving-fleet failure modes
    (serving/fleet.py): a torn lease file a reader must skip (never
    trust), a slow joiner that is reachable but unannounced, and member
    / frontend SIGKILL mid-stream, all driven by tools/bench_fleet.py
    and tests/test_fleet.py.
"""
from __future__ import annotations

import os
import signal
import subprocess
import sys
import time
from typing import Callable, List, Optional, Tuple

KILL_STEP_ENV = "DEEPREC_FAULT_KILL_STEP"
SLOW_JOIN_ENV = "DEEPREC_FAULT_SLOW_JOIN_SECS"


# ------------------------------------------------------------ kill at step


def kill_self_at_step(kill_step: int) -> Callable[[int], None]:
    """Hook for TrainLoop(on_step=...): SIGKILL this process right after
    `kill_step` completes. SIGKILL, not sys.exit — the point is that no
    finally-block, atexit, or writer drain gets to run."""

    def hook(step: int) -> None:
        if step >= kill_step:
            os.kill(os.getpid(), signal.SIGKILL)

    return hook


def env_kill_step() -> Optional[Callable[[int], None]]:
    """The subprocess form: DEEPREC_FAULT_KILL_STEP=N arms
    kill_self_at_step(N) in a worker started by the supervisor/bench."""
    v = os.environ.get(KILL_STEP_ENV)
    if not v:
        return None
    return kill_self_at_step(int(v))


# ---------------------------------------------------------- torn writes


def install_torn_write(ck, junk_file: str = "table_junk_t0.npz") -> None:
    """Arm `ck.on_write` to die mid-save ONCE: the dir exists and holds a
    real (junk) table file, but no manifest — the state a SIGKILL between
    npz writes leaves behind. Restore must treat the dir as absent."""
    import numpy as np

    def seam(path):
        ck.on_write = None  # one-shot
        os.makedirs(path, exist_ok=True)
        np.savez(os.path.join(path, junk_file), junk=np.zeros(3))
        raise KeyboardInterrupt("injected torn write")

    ck.on_write = seam


# ------------------------------------------------------ bit flips / tears


def flip_bit(path: str, offset: Optional[int] = None, bit: int = 4) -> int:
    """Flip one bit of `path` in place; returns the byte offset flipped.
    Default offset is mid-file — inside some array's payload, past the
    zip headers, so the tear is in DATA (the manifests' digest/zip-CRC
    checks must catch it; a header flip would fail earlier and cheaper)."""
    with open(path, "rb") as f:
        data = bytearray(f.read())
    if not data:
        raise ValueError(f"{path} is empty")
    off = len(data) // 2 if offset is None else offset
    data[off] ^= 1 << bit
    with open(path, "wb") as f:
        f.write(bytes(data))
    return off


def truncate_file(path: str, keep_fraction: float = 0.5) -> int:
    """Truncate a committed file to a fraction of its size (torn copy /
    partial replication). Returns the new size."""
    size = os.path.getsize(path)
    new = max(1, int(size * keep_fraction))
    with open(path, "rb+") as f:
        f.truncate(new)
    return new


def corrupt_latest_delta(ckpt_dir: str, mode: str = "bitflip",
                         kind: str = "incr") -> Optional[str]:
    """Corrupt the newest COMMITTED `kind-*` dir's first table file
    (bitflip | truncate). Returns the corrupted file's path, or None when
    no committed dir of that kind exists yet. Only dirs with a manifest
    count — corrupting an in-flight save would test the torn-write path,
    not the post-commit one."""
    import re

    pat = re.compile(rf"^{kind}-(\d+)$")
    steps = sorted(
        int(m.group(1))
        for d in os.listdir(ckpt_dir)
        if (m := pat.match(d))
        and os.path.exists(os.path.join(ckpt_dir, d, "manifest.json"))
    )
    if not steps:
        return None
    path = os.path.join(ckpt_dir, f"{kind}-{steps[-1]}")
    tables = sorted(
        f for f in os.listdir(path) if f.startswith("table_")
    )
    if not tables:
        return None
    target = os.path.join(path, tables[0])
    if mode == "truncate":
        truncate_file(target)
    else:
        flip_bit(target)
    return target


# ----------------------------------------------------------- fleet faults


def torn_lease_write(registry, addr: str, role: str = "backend",
                     pid: Optional[int] = None) -> str:
    """Plant a TORN lease file (truncated mid-JSON) at the path the
    member at `addr` would stamp — what a non-atomic writer or FS
    corruption leaves. The registry's own writes are atomic tmp+rename
    (Heartbeat), so this deliberately bypasses them; a sweep must read
    it as 'no lease' (skip), never trust it and never crash. Returns
    the planted path."""
    path = registry.lease_path(addr, role, pid=pid)
    with open(path, "w") as f:
        f.write('{"pid": 1234, "time": 17')  # cut mid-value
    return path


def env_slow_join_secs() -> float:
    """The slow-joiner fault, subprocess form: DEEPREC_FAULT_SLOW_JOIN_SECS
    delays a fleet backend's FIRST lease stamp — the process binds its
    socket and serves, but stays unannounced. The fleet must keep full
    service meanwhile (nobody routes to an unleased member) and admit it
    when the stamp finally lands."""
    v = os.environ.get(SLOW_JOIN_ENV)
    return float(v) if v else 0.0


def sigkill_fleet_member(proc: subprocess.Popen, wait: float = 30.0) -> int:
    """SIGKILL a fleet member (backend or frontend) mid-stream: sockets
    drop, the lease goes stale and eviction retires it — no drain, no
    unregister, the exact opposite of the polite exit. Alias of
    `sigkill` with the fleet contract spelled out: the tier must retry
    in-flight requests on siblings with zero failed requests."""
    return sigkill(proc, wait=wait)


# --------------------------------------------------------- data poison
#
# The semantic-fault injector set (guard/ firewall, docs/fault-tolerance.md
# "Semantic faults"): unlike every fault above, nothing crashes — the
# process stays healthy while the DATA (or the optimizer schedule) poisons
# the model. Driven by tools/bench_guard.py and tests/test_guard.py.


def poison_batch(batch, mode: str, magnitude: float = 1e30,
                 seed: int = 0) -> dict:
    """Return a poisoned copy of `batch`:

      * ``nan``        — every dense feature value becomes NaN (a
        corrupt upstream join / log-shipper bug);
      * ``extreme``    — dense features take ±`magnitude` (unit bugs,
        overflowed counters);
      * ``label_flip`` — labels invert (a polarity bug in the label
        pipeline: gradients are confidently wrong, loss spikes while
        every value stays finite — the case only the loss-spike EMA
        catches).
    """
    import numpy as np

    out = {k: np.array(v, copy=True) for k, v in batch.items()}
    rng = np.random.default_rng(seed)
    if mode == "nan":
        for k, v in out.items():
            if not k.startswith("label") and np.issubdtype(
                    v.dtype, np.floating):
                out[k] = np.full_like(v, np.nan)
    elif mode == "extreme":
        for k, v in out.items():
            if not k.startswith("label") and np.issubdtype(
                    v.dtype, np.floating):
                out[k] = np.where(rng.random(v.shape) < 0.5,
                                  magnitude, -magnitude).astype(v.dtype)
    elif mode == "label_flip":
        for k, v in out.items():
            if k.startswith("label"):
                out[k] = (1.0 - v).astype(v.dtype)
    else:
        raise ValueError(f"unknown poison mode {mode!r}")
    return out


class PoisonInjector:
    """Wrap a batch iterable, poisoning chosen deliveries.

    ``plan`` maps 1-based delivery index -> poison mode; ``repeat_from``
    (optional) replays the LAST poisoned batch verbatim on every later
    delivery whose index is in ``repeat_at`` — the stream-replay shape
    that drives a batch across R rollbacks into permanent quarantine.
    ``injected`` records (index, mode, fingerprint) for the bench's
    detection-latency ledger."""

    def __init__(self, source, plan: dict, repeat_at=()):
        from deeprec_tpu.guard.quarantine import batch_fingerprint

        self._fp = batch_fingerprint
        self.source = source
        self.plan = dict(plan)
        self.repeat_at = set(repeat_at)
        self.injected = []  # [(delivery index, mode, fingerprint)]
        self._last_poisoned = None

    def __iter__(self):
        i = 0
        for batch in self.source:
            i += 1
            if i in self.repeat_at and self._last_poisoned is not None:
                out = self._last_poisoned
                self.injected.append((i, "repeat", self._fp(out)))
                yield out
                continue
            mode = self.plan.get(i)
            if mode is not None:
                out = poison_batch(batch, mode, seed=i)
                self._last_poisoned = out
                self.injected.append((i, mode, self._fp(out)))
                yield out
            else:
                yield batch


def exploding_lr(base_lr: float, start: int, length: int,
                 factor: float = 1e6) -> Callable[[int], float]:
    """TrainLoop(lr_fn=...) injector: a runaway learning-rate window —
    steps in [start, start+length) train at ``base_lr * factor`` (a bad
    schedule push / config typo). The data is clean; only the sentinel's
    grad/row-norm and non-finite checks can see the damage."""

    def lr_fn(step: int) -> float:
        if start <= step < start + length:
            return base_lr * factor
        return base_lr

    return lr_fn


# --------------------------------------------------------- broker outage


class BrokerOutage:
    """Take a FileStreamServer down and bring it back on the SAME port —
    the disconnect/reconnect cycle TCPStreamReader's jittered backoff is
    specified against. The revived broker serves the same file, and the
    reader's OFFSET header makes the resume exactly-once."""

    def __init__(self, server):
        self.server = server
        self.port = server.port
        self.path = server.path
        self.follow = server.follow
        self.poll_secs = server.poll_secs
        self.down_at: Optional[float] = None
        self.outages = 0

    def down(self) -> None:
        self.server.stop()
        self.down_at = time.monotonic()
        self.outages += 1

    def up(self):
        """Revive on the same port (allow_reuse_address makes the rebind
        race-free against lingering TIME_WAIT sockets)."""
        from deeprec_tpu.data.stream import FileStreamServer

        self.server = FileStreamServer(
            self.path, port=self.port, follow=self.follow,
            poll_secs=self.poll_secs,
        ).start()
        self.down_at = None
        return self.server


# ------------------------------------------------- subprocess machinery


def spawn_worker(argv: List[str], env: Optional[dict] = None,
                 cwd: Optional[str] = None) -> subprocess.Popen:
    """Start a worker with line-buffered captured stdout (stderr merged),
    CPU-pinned jax defaults unless the caller overrides."""
    e = {**os.environ, "PYTHONPATH": "", "JAX_PLATFORMS": "cpu"}
    if env:
        e.update({k: str(v) for k, v in env.items()})
    return subprocess.Popen(
        argv, env=e, cwd=cwd, stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT, text=True, bufsize=1,
    )


def wait_for_line(proc: subprocess.Popen, pred: Callable[[str], bool],
                  timeout: float = 240.0) -> Tuple[Optional[str], List[str]]:
    """Read the worker's stdout until `pred(line)` matches (returns that
    line) or the stream ends / times out (returns None). All consumed
    lines ride along for assertion messages."""
    lines: List[str] = []
    deadline = time.time() + timeout
    while time.time() < deadline:
        line = proc.stdout.readline()
        if not line:
            return None, lines
        line = line.rstrip("\n")
        lines.append(line)
        if pred(line):
            return line, lines
    return None, lines


def sigkill(proc: subprocess.Popen, wait: float = 30.0) -> int:
    """kill -9 and reap; returns the exit code (negative signal)."""
    os.kill(proc.pid, signal.SIGKILL)
    return proc.wait(timeout=wait)


def python_argv(script_path: str) -> List[str]:
    return [sys.executable, script_path]
